#!/usr/bin/env python
"""Scripted cluster-validation walkthrough.

Analogue of the reference's notebook-driven GKE smoke test
(``examples/gke/test_notebook.py``, SURVEY §2 #33): a narrated,
step-by-step run of the full user journey — submit a TpuJob manifest,
watch the phase transitions, inspect per-replica status, verify
success, delete, and verify garbage collection.

Two modes:

* default — runs against the in-process LocalWorld (no cluster
  needed), so the walkthrough doubles as an install-check anywhere.
* ``--kubectl`` — emits the equivalent kubectl commands for a real GKE
  cluster with the operator chart installed, instead of executing
  locally.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
if _REPO_ROOT not in sys.path:  # runnable from a source checkout
    sys.path.append(_REPO_ROOT)

EXAMPLE = os.path.join(os.path.dirname(__file__), "..", "tpu_job_cpu_smoke.yaml")


def narrate(step: str) -> None:
    print(f"\n== {step} ==")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--manifest", default=EXAMPLE)
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument(
        "--kubectl", action="store_true",
        help="print kubectl equivalents for a real cluster instead",
    )
    args = p.parse_args(argv)

    if args.kubectl:
        name = "$(yq .metadata.name " + args.manifest + ")"
        for step, cmd in [
            ("submit", f"kubectl create -f {args.manifest}"),
            ("watch phase", f"kubectl get tpujob {name} -o jsonpath='{{.status.phase}}' -w"),
            ("replica status", f"kubectl get tpujob {name} -o jsonpath='{{.status.replicaStatuses}}'"),
            ("logs", f"kubectl logs -l tpu_job_name={name},task_index=0"),
            ("delete", f"kubectl delete tpujob {name}"),
            ("verify GC", f"kubectl get jobs,services -l tpu_job_name={name}"),
        ]:
            narrate(step)
            print(f"$ {cmd}")
        return 0

    from k8s_tpu import spec as S
    from k8s_tpu.client.job_client import load_tpu_job_yaml
    from k8s_tpu.tools.local_world import LocalWorld

    narrate(f"load manifest {os.path.relpath(args.manifest)}")
    with open(args.manifest) as f:
        job = load_tpu_job_yaml(f.read())
    job.metadata.namespace = job.metadata.namespace or "default"
    ns, name = job.metadata.namespace, job.metadata.name
    print(f"TpuJob {ns}/{name}")

    with LocalWorld() as world:
        narrate("submit (kubectl create -f equivalent)")
        world.api.create(job)

        narrate("watch phase transitions")
        seen, deadline = [], time.time() + args.timeout
        while time.time() < deadline:
            got = world.api.get(ns, name)
            phase = got.status.phase
            if not seen or seen[-1] != phase:
                seen.append(phase)
                print(f"phase: {phase}")
            if phase == S.TpuJobPhase.DONE:
                break
            time.sleep(0.1)
        else:
            print("TIMEOUT waiting for Done", file=sys.stderr)
            return 1

        narrate("inspect final status")
        got = world.api.get(ns, name)
        print(f"state: {got.status.state}")
        for rs in got.status.replica_statuses:
            print(f"  {rs.replica_type}: {rs.state} {rs.replicas_states}")
        if got.status.state != S.TpuJobState.SUCCEEDED:
            print(f"FAILED: {got.status.reason}", file=sys.stderr)
            return 1

        narrate("delete + verify GC")
        world.api.delete(ns, name)
        leftovers = []
        deadline = time.time() + args.timeout
        while time.time() < deadline:
            leftovers = [
                o.metadata.name
                for res in (world.client.jobs, world.client.services,
                            world.client.config_maps)
                for o in res.list(ns)
                if (o.metadata.labels or {}).get("tpu_job_name") == name
            ]
            if not leftovers:
                break
            time.sleep(0.1)
        else:
            print(f"GC incomplete: {leftovers}", file=sys.stderr)
            return 1
        print("all job resources garbage-collected")

    print("\nSMOKE WALKTHROUGH PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
