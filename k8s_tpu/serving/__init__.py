"""TPU-native continuous-batching serving (ragged decode).

The reference framework has no serving path at all (its operator only
wires *training* clusters — SURVEY.md §0); this package is original
capability built on the repo's decode stack: the fused single-token
decode kernel (`k8s_tpu/ops/attention.py`) extended with per-row cache
depths, and `LlamaConfig(ragged_decode=True)`. Prompts prefill in
bounded chunks under a per-round token budget (docs/SERVING.md), so a
long admission never stalls in-flight decode streams.
"""

from k8s_tpu.serving.engine import ContinuousBatchingEngine, Request
from k8s_tpu.serving.server import ServingFrontend

__all__ = ["ContinuousBatchingEngine", "Request", "ServingFrontend"]
