"""HTTP front-end for the continuous-batching engine — the piece that
makes serving an OPERATOR WORKLOAD instead of a library.

The reference operator's defining contract is that it *runs* the
workload (``/root/reference/pkg/trainer/replicas.go:216-268`` builds a
Service + Job per replica and the training process inside); until round
5 the serving engine could only be driven in-process. This module gives
it a deployable surface: ``programs/serving.py`` runs a
:class:`ServingFrontend` under the SPMD launcher, so a TpuJob manifest
(`examples/`) serves traffic through the same lifecycle — create →
Running → (delete ⇒ SIGTERM ⇒ drain) — as every training job.

Split of responsibilities, single-threaded where it matters:

- HTTP handler threads (stdlib ``ThreadingHTTPServer``) only call
  ``engine.submit`` (documented thread-safe) and wait on a per-request
  event. They never touch scheduling state.
- The PUMP runs in the caller's thread (:meth:`serve`): it alone calls
  ``engine.step``/``pop_finished`` — the engine's single-threaded
  scheduling contract — and resolves waiter events as requests finish.
- Drain: on SIGTERM (job delete / TPU maintenance) the front-end stops
  accepting (503s new requests), pumps until every in-flight request
  finished, releases any stragglers, and closes the engine. In-flight
  work is never dropped while the kubelet grace period allows.

API (JSON over HTTP, stdlib only — this rides in the same ConfigMap-
shipped image as the launcher):

- ``POST /v1/generate`` ``{"prompt": [int, ...], "max_new_tokens": N}``
  → ``{"rid": n, "tokens": [int, ...], "latency_s": s}`` (blocks until
  the request finishes; token-id interface — tokenization is the
  caller's, same contract as :func:`k8s_tpu.models.llama.generate`).
- ``GET /healthz`` → engine stats (TTFT, queue depth, prefill/decode
  counters), the in-flight partial prompt's prefill progress, and the
  scheduler's knobs + in-flight counts (the operator's
  ``--health-port`` idiom, per-pod).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np


class ServingFrontend:
    """Bind an HTTP server to ``engine``; :meth:`serve` pumps until
    ``should_stop()`` goes true, then drains. ``port=0`` binds an
    ephemeral port (read :attr:`port` after construction — the program
    prints it as a machine-readable event for clients/tests)."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 request_timeout: float = 300.0):
        self.engine = engine
        self.request_timeout = float(request_timeout)
        self._lock = threading.Lock()
        self._waiters: Dict[int, threading.Event] = {}
        self._results: Dict[int, object] = {}
        self._work = threading.Event()   # poked by submissions
        self._draining = False
        self.served = 0                  # results DELIVERED to a waiter, lifetime
        self.abandoned = 0               # finished after the waiter timed out

        frontend = self

        class Handler(BaseHTTPRequestHandler):
            # the pod log is the operator's observability surface —
            # default per-request stderr lines would swamp it
            def log_message(self, fmt, *args):  # noqa: D102
                pass

            def _json(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - stdlib naming
                if self.path != "/healthz":
                    return self._json(404, {"error": "not found"})
                with frontend._lock:
                    in_flight = len(frontend._waiters)
                eng = frontend.engine
                # scheduler observability (chunked prefill): queue
                # depth and TTFT ride in stats; the in-flight partial
                # prompt's progress and the scheduling knobs are
                # engine attributes (getattr: stubs/legacy engines
                # without them still serve a valid payload)
                progress = getattr(eng, "prefill_progress", dict)()
                return self._json(200, {
                    "ok": not frontend._draining,
                    "draining": frontend._draining,
                    "in_flight": in_flight,
                    "served": frontend.served,
                    "abandoned": frontend.abandoned,
                    "prefill_progress": {
                        str(rid): p for rid, p in progress.items()},
                    "scheduler": {
                        "chunked_prefill": getattr(
                            eng, "chunked_prefill", None),
                        "decode_chunk": getattr(eng, "decode_chunk", None),
                        "prefill_chunk": getattr(
                            eng, "prefill_chunk", None),
                        "max_tokens_per_round": getattr(
                            eng, "max_tokens_per_round", None),
                    },
                    "stats": {k: round(v, 4) if isinstance(v, float) else v
                              for k, v in frontend.engine.stats.items()},
                })

            def do_POST(self):  # noqa: N802
                if self.path != "/v1/generate":
                    return self._json(404, {"error": "not found"})
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n))
                    prompt = np.asarray(req["prompt"], np.int32)
                    max_new = int(req.get("max_new_tokens", 16))
                except Exception as e:  # malformed request → caller's 400
                    return self._json(400, {"error": f"bad request: {e}"})
                t0 = time.perf_counter()
                try:
                    tokens = frontend.submit_and_wait(prompt, max_new)
                except RuntimeError as e:   # draining/closed
                    return self._json(503, {"error": str(e)})
                except ValueError as e:     # engine validation
                    return self._json(400, {"error": str(e)})
                except TimeoutError as e:
                    return self._json(504, {"error": str(e)})
                return self._json(200, {
                    "tokens": [int(t) for t in tokens],
                    "latency_s": round(time.perf_counter() - t0, 4),
                })

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = int(self._server.server_address[1])
        self._http_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="serving-http",
        )

    # -- handler-thread side ---------------------------------------------

    def submit_and_wait(self, prompt, max_new_tokens: int):
        """Submit one request and block until its tokens are ready.
        Raises RuntimeError while draining (503 to the client) so the
        load balancer retries another replica during rollout."""
        with self._lock:
            if self._draining:
                raise RuntimeError("draining: not accepting new requests")
            rid = self.engine.submit(prompt, max_new_tokens)
            ev = threading.Event()
            self._waiters[rid] = ev
        self._work.set()
        if not ev.wait(self.request_timeout):
            with self._lock:
                self._waiters.pop(rid, None)
                # the engine may still finish this request later; with
                # the waiter gone _resolve_finished drops the tokens,
                # but the finish could also have raced this timeout —
                # purge either way so nothing accumulates
                self._results.pop(rid, None)
            raise TimeoutError(f"request {rid} timed out")
        with self._lock:
            result = self._results.pop(rid)
        if isinstance(result, Exception):
            raise result
        return result

    # -- pump side ---------------------------------------------------------

    def _resolve_finished(self) -> None:
        done = self.engine.pop_finished()
        if not done:
            return
        with self._lock:
            for rid, req in done.items():
                ev = self._waiters.pop(rid, None)
                if ev is not None:
                    self.served += 1
                    self._results[rid] = np.asarray(req.tokens, np.int32)
                    ev.set()
                else:
                    # no waiter ⇒ the client timed out and left: drop
                    # the tokens instead of accumulating them forever —
                    # and don't count undelivered work as served
                    self.abandoned += 1

    def serve(self, should_stop) -> None:
        """Run the pump until ``should_stop()`` — then drain and close.
        Call from the process main thread (the engine's scheduling
        thread); returns only when the engine is fully drained."""
        self._http_thread.start()
        try:
            while not should_stop():
                busy = self.engine.step()
                self._resolve_finished()
                if not busy:
                    # idle: block on the submission poke, not a spin —
                    # 50 ms bounds shutdown-signal latency when no
                    # client ever connects
                    self._work.wait(0.05)
                    self._work.clear()
        finally:
            self.drain()

    def drain(self) -> None:
        """Stop intake, finish in-flight requests, close the engine.
        Idempotent; also releases every still-parked waiter (a request
        that raced the shutdown gets its tokens if the engine finished
        it, a 503 RuntimeError otherwise)."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        self._server.shutdown()
        try:
            while self.engine.step():
                self._resolve_finished()
            self._resolve_finished()
        finally:
            # even if the drain pump raises (e.g. a device error
            # surfacing out of step()), parked handler threads must be
            # released and the engine/listener closed — otherwise each
            # client blocks its full request_timeout and the harvester
            # threads leak past the kubelet grace period
            with self._lock:
                for rid, ev in list(self._waiters.items()):
                    self._results[rid] = RuntimeError(
                        "server draining before request finished")
                    ev.set()
                self._waiters.clear()
            self.engine.close()
            self._server.server_close()
