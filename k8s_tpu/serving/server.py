"""HTTP front-end for the continuous-batching engine — the piece that
makes serving an OPERATOR WORKLOAD instead of a library.

The reference operator's defining contract is that it *runs* the
workload (``/root/reference/pkg/trainer/replicas.go:216-268`` builds a
Service + Job per replica and the training process inside); until round
5 the serving engine could only be driven in-process. This module gives
it a deployable surface: ``programs/serving.py`` runs a
:class:`ServingFrontend` under the SPMD launcher, so a TpuJob manifest
(`examples/`) serves traffic through the same lifecycle — create →
Running → (delete ⇒ SIGTERM ⇒ drain) — as every training job.

Split of responsibilities, single-threaded where it matters:

- HTTP handler threads (stdlib ``ThreadingHTTPServer``) only call
  ``engine.submit`` (documented thread-safe) and wait on a per-request
  event. They never touch scheduling state.
- The PUMP runs in the caller's thread (:meth:`serve`): it alone calls
  ``engine.step``/``pop_finished`` — the engine's single-threaded
  scheduling contract — and resolves waiter events as requests finish.
- Drain: on SIGTERM (job delete / TPU maintenance) the front-end stops
  accepting (503s new requests), pumps until every in-flight request
  finished, releases any stragglers, and closes the engine. In-flight
  work is never dropped while the kubelet grace period allows.

API (JSON over HTTP, stdlib only — this rides in the same ConfigMap-
shipped image as the launcher):

- ``POST /v1/generate`` ``{"prompt": [int, ...], "max_new_tokens": N}``
  → ``{"rid": n, "tokens": [int, ...], "latency_s": s}`` (blocks until
  the request finishes; token-id interface — tokenization is the
  caller's, same contract as :func:`k8s_tpu.models.llama.generate`).
- ``GET /healthz`` → engine stats (TTFT, queue depth, prefill/decode
  counters), the in-flight partial prompt's prefill progress, and the
  scheduler's knobs + in-flight counts (the operator's
  ``--health-port`` idiom, per-pod).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from k8s_tpu.serving import kv_transfer


class Overloaded(RuntimeError):
    """Raised by :meth:`ServingFrontend.submit_and_wait` when the
    engine's live queue depth exceeds ``max_queue_depth`` — mapped to
    HTTP 429 + ``Retry-After``. A router in front of this replica
    depends on the rejection being IMMEDIATE and honest: queueing the
    request unboundedly instead would hide the saturation signal it
    load-balances on."""


class _Result:
    """One finished request's payload + timing, resolved to a waiter."""

    __slots__ = ("tokens", "ttft_s", "itl_ms", "spans", "kv")

    def __init__(self, tokens, ttft_s: float, itl_ms: float,
                 spans=None, kv=None):
        self.tokens = tokens
        self.ttft_s = ttft_s
        self.itl_ms = itl_ms
        # request-path decomposition (docs/OBSERVABILITY.md):
        # engine_queue_s + prefill_s == ttft_s by construction (all
        # three derive from the same request timestamps), decode_s is
        # the stream tail after the first token
        self.spans = spans or {}
        # prefill-only requests: the working-cache KV snapshot +
        # handoff metadata (docs/SERVING.md "Disaggregation")
        self.kv = kv


class ServingFrontend:
    """Bind an HTTP server to ``engine``; :meth:`serve` pumps until
    ``should_stop()`` goes true, then drains. ``port=0`` binds an
    ephemeral port (read :attr:`port` after construction — the program
    prints it as a machine-readable event for clients/tests).

    ``max_queue_depth`` > 0 enables backpressure: a request arriving
    while ``engine.queue_depth()`` is at/over the threshold is refused
    with 429 + ``Retry-After: retry_after_s`` instead of queueing
    unboundedly (the per-replica saturation contract the fleet router
    routes on)."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 request_timeout: float = 300.0,
                 max_queue_depth: int = 0, retry_after_s: float = 1.0,
                 role: str = "", kv_store_max: int = 32,
                 kv_store_max_bytes: int = 1 << 30,
                 kv_ttl_s: float = 120.0,
                 kv_push_timeout: float = 30.0,
                 migration: bool = False,
                 kv_migration_ttl_s: float = 600.0,
                 prefix_fetch_timeout: float = 10.0):
        self.engine = engine
        self.request_timeout = float(request_timeout)
        self.max_queue_depth = int(max_queue_depth)
        self.retry_after_s = float(retry_after_s)
        # live migration (docs/SERVING.md "Live migration & prefix
        # directory"): off by default — every route below exists
        # regardless (a peer may call them), but the healthz surface
        # only grows the migration block when enabled, keeping
        # no-migration fleets byte-identical on their key sets
        self.migration = bool(migration)
        # migration mirrors must outlive a whole decode stream, not one
        # router leg — their own, longer TTL (the per-kind fix)
        self.kv_migration_ttl_s = float(kv_migration_ttl_s)
        self.prefix_fetch_timeout = float(prefix_fetch_timeout)
        self.kv_migration_expired = 0   # expired MIGRATION handles (dedicated cue)
        self.mirrors_out = 0            # /v1/mirror exports pushed to a peer
        self.migrated_out = 0           # drain_migrate slots handed off
        self.migrated_in = 0            # /v1/migrate resumes served here
        # trace_id -> in-flight rid: lets the router address a live
        # request by the trace id it already knows (mirror/migrate)
        self._trace_rids: Dict[str, int] = {}
        # re-imported rid -> original rid: a failed drain hand-off
        # re-admits locally under a NEW rid; the original waiter must
        # still resolve (see drain_migrate / _resolve_finished)
        self._aliases: Dict[int, int] = {}
        # disaggregation (docs/SERVING.md "Disaggregation"): "" =
        # interleaved (today's fleet), "prefill"/"decode" = phase pool
        # membership. Steering-only: every replica keeps the full
        # route surface so the fallback ladder always has somewhere
        # to land.
        self.role = str(role or "")
        self.kv_store_max = int(kv_store_max)
        self.kv_store_max_bytes = int(kv_store_max_bytes)
        self.kv_ttl_s = float(kv_ttl_s)
        self.kv_push_timeout = float(kv_push_timeout)
        self._lock = threading.Lock()
        self._waiters: Dict[int, threading.Event] = {}
        self._results: Dict[int, object] = {}
        self._work = threading.Event()   # poked by submissions
        self._draining = False
        self.served = 0                  # results DELIVERED to a waiter, lifetime
        self.abandoned = 0               # finished after the waiter timed out
        self.rejected = 0                # refused by backpressure (429s)
        self._healthz_faults = 0         # armed stats-endpoint failures (chaos)
        # received-KV handle store (decode pool): handle -> (meta,
        # leaves, nbytes); single-use (popped by /v1/decode) and
        # bounded by COUNT and BYTES — each entry is a full per-
        # request KV snapshot (hundreds of MB for a long prompt), so a
        # count bound alone would let orphaned handoffs (router died,
        # decode leg fell back) pin tens of GB of dead host buffers
        # (the prefix-LRU bytes-accounting lesson)
        self._kv_store: "OrderedDict[str, tuple]" = OrderedDict()
        self._kv_store_bytes = 0
        self.kv_received = 0
        self.kv_bytes_in = 0
        self.kv_pushed = 0
        self.kv_push_failures = 0
        self.kv_bytes_out = 0

        frontend = self

        class Handler(BaseHTTPRequestHandler):
            # the pod log is the operator's observability surface —
            # default per-request stderr lines would swamp it
            def log_message(self, fmt, *args):  # noqa: D102
                pass

            def _json(self, code: int, payload: dict, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - stdlib naming
                if self.path == "/metrics":
                    # Prometheus text exposition off the process-global
                    # registry: the engine-side ktpu_serving_* /
                    # ktpu_obs_hbm_* series a fleet scrape reads
                    # per-replica (docs/SERVING.md "Fleet")
                    frontend._export_gauges()
                    from k8s_tpu.controller import metrics as M

                    body = M.REGISTRY.expose().encode("utf-8")
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    return self.wfile.write(body)
                if self.path.startswith("/v1/prefix/"):
                    return self._prefix_get(
                        self.path[len("/v1/prefix/"):])
                if self.path != "/healthz":
                    return self._json(404, {"error": "not found"})
                if frontend._consume_healthz_fault():
                    # chaos router-stats-flake: the stats endpoint
                    # errors while the data plane keeps serving — a
                    # poller must treat this as a miss, not a crash
                    return self._json(500, {"error": "chaos: stats flake"})
                with frontend._lock:
                    in_flight = len(frontend._waiters)
                eng = frontend.engine
                # scheduler observability (chunked prefill): queue
                # depth and TTFT ride in stats; the in-flight partial
                # prompt's progress and the scheduling knobs are
                # engine attributes (getattr: stubs/legacy engines
                # without them still serve a valid payload)
                progress = getattr(eng, "prefill_progress", dict)()
                hbm = frontend._export_gauges()
                return self._json(200, {
                    "ok": not frontend._draining,
                    # engine device-memory telemetry: HBM allocator
                    # stats (absent on backends without memory_stats)
                    # — capacity planning reads this next to
                    # stats.prefix_cache_bytes
                    **({"hbm": hbm} if hbm else {}),
                    # phase-pool membership + KV-handoff counters
                    # (docs/SERVING.md "Disaggregation"); absent for
                    # interleaved replicas so the pre-disagg healthz
                    # shape is byte-identical
                    **({"role": frontend.role,
                        "kv": frontend._kv_store_stats()}
                       if frontend.role else {}),
                    # live migration + prefix directory (docs/
                    # SERVING.md): mirror/drain counters plus the
                    # prefix digests this replica holds — the router's
                    # healthz poll builds the fleet-wide directory
                    # from these. Absent unless migration is enabled
                    # (no-migration fleets stay byte-identical).
                    **({"migration": frontend._migration_stats()}
                       if frontend.migration else {}),
                    "draining": frontend._draining,
                    "in_flight": in_flight,
                    "served": frontend.served,
                    "abandoned": frontend.abandoned,
                    "rejected": frontend.rejected,
                    "queue_depth": frontend._queue_depth(),
                    "prefill_progress": {
                        str(rid): p for rid, p in progress.items()},
                    "scheduler": {
                        "chunked_prefill": getattr(
                            eng, "chunked_prefill", None),
                        "decode_chunk": getattr(eng, "decode_chunk", None),
                        "prefill_chunk": getattr(
                            eng, "prefill_chunk", None),
                        "max_tokens_per_round": getattr(
                            eng, "max_tokens_per_round", None),
                        "max_queue_depth": frontend.max_queue_depth,
                        "prefix_cache_tokens": getattr(
                            eng, "prefix_cache_tokens", None),
                    },
                    "stats": {k: round(v, 4) if isinstance(v, float) else v
                              for k, v in frontend.engine.stats.items()},
                })

            def _trace_id(self):
                # trace propagation: honor the caller's id (the router
                # forwards one), mint one otherwise — every response
                # carries the id its spans are attributable under
                trace_id = self.headers.get("X-KTPU-Trace-Id", "")
                if not trace_id:
                    import uuid

                    trace_id = "req-" + uuid.uuid4().hex[:12]
                return trace_id

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n)

            def do_POST(self):  # noqa: N802
                if self.path == "/v1/generate":
                    return self._generate()
                if self.path == "/v1/prefill":
                    return self._prefill()
                if self.path == "/v1/decode":
                    return self._decode()
                if self.path.startswith("/v1/kv/"):
                    return self._kv_put(self.path[len("/v1/kv/"):])
                if self.path.startswith("/v1/migrate/"):
                    return self._migrate(self.path[len("/v1/migrate/"):])
                if self.path == "/v1/mirror":
                    return self._mirror()
                if self.path == "/v1/drain_migrate":
                    return self._drain_migrate()
                return self._json(404, {"error": "not found"})

            def _generate(self):
                try:
                    req = json.loads(self._body())
                    prompt = np.asarray(req["prompt"], np.int32)
                    max_new = int(req.get("max_new_tokens", 16))
                except Exception as e:  # malformed request → caller's 400
                    return self._json(400, {"error": f"bad request: {e}"})
                trace_id = self._trace_id()
                t0 = time.perf_counter()
                try:
                    result = frontend.submit_and_wait(
                        prompt, max_new, trace_id=trace_id)
                except Overloaded as e:     # backpressure → caller retries
                    return self._json(
                        429, {"error": str(e)},
                        headers={"Retry-After":
                                 f"{frontend.retry_after_s:g}"})
                except RuntimeError as e:   # draining/closed
                    return self._json(503, {"error": str(e)})
                except ValueError as e:     # engine validation
                    return self._json(400, {"error": str(e)})
                except TimeoutError as e:
                    return self._json(504, {"error": str(e)})
                return self._json(200, {
                    "tokens": [int(t) for t in result.tokens],
                    "latency_s": round(time.perf_counter() - t0, 4),
                    # per-request stream timing: the fleet router
                    # aggregates these into the TTFT/ITL percentiles
                    # the SLO autoscaler scales on
                    "ttft_s": round(result.ttft_s, 4),
                    "itl_ms": round(result.itl_ms, 3),
                    "trace_id": trace_id,
                    # engine-side span decomposition: queue+prefill
                    # sum to ttft_s (same timestamps), decode is the
                    # rest of the stream (docs/OBSERVABILITY.md)
                    "spans": {k: round(v, 4)
                              for k, v in result.spans.items()},
                })

            def _prefill(self):
                """Disaggregation, prefill leg: chunked-prefill the
                prompt to completion, push the finished working KV to
                the router-chosen decode target, return the handle +
                spans. A failed push degrades to serving the WHOLE
                request locally (the local-prefill fallback) — a lost
                transfer costs latency, never the request."""
                try:
                    req = json.loads(self._body())
                    prompt = np.asarray(req["prompt"], np.int32)
                    max_new = int(req.get("max_new_tokens", 16))
                    kv_target = str(req.get("kv_target") or "")
                    handle = str(req.get("handle") or "")
                    prefix_from = str(req.get("prefix_from") or "")
                    if not kv_target or not handle:
                        raise ValueError("kv_target and handle required")
                except Exception as e:
                    return self._json(400, {"error": f"bad request: {e}"})
                trace_id = self._trace_id()
                try:
                    code, payload = frontend.prefill_and_push(
                        prompt, max_new, kv_target, handle,
                        prefix_from=prefix_from)
                except Overloaded as e:
                    return self._json(
                        429, {"error": str(e)},
                        headers={"Retry-After":
                                 f"{frontend.retry_after_s:g}"})
                except RuntimeError as e:
                    return self._json(503, {"error": str(e)})
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
                except TimeoutError as e:
                    return self._json(504, {"error": str(e)})
                payload["trace_id"] = trace_id
                return self._json(code, payload)

            def _decode(self):
                """Disaggregation, decode leg: seed a slot from a
                received KV handle and stream to completion. 404 on an
                unknown handle — the router's cue to fall back."""
                try:
                    req = json.loads(self._body())
                    handle = str(req.get("handle") or "")
                    max_new = int(req.get("max_new_tokens", 16))
                    if not handle:
                        raise ValueError("handle required")
                except Exception as e:
                    return self._json(400, {"error": f"bad request: {e}"})
                trace_id = self._trace_id()
                entry = frontend._kv_pop(handle)
                if entry is None:
                    return self._json(
                        404, {"error": f"unknown kv handle {handle!r}"})
                meta, leaves, nbytes = entry
                t0 = time.perf_counter()
                try:
                    result = frontend.submit_and_wait_kv(
                        {**meta, "leaves": leaves}, max_new,
                        trace_id=trace_id)
                except Overloaded as e:
                    # admission never happened and the snapshot is
                    # intact: restore it so a post-backoff retry costs
                    # nothing instead of a full interleaved re-prefill
                    frontend._kv_restore(handle, meta, leaves, nbytes)
                    return self._json(
                        429, {"error": str(e)},
                        headers={"Retry-After":
                                 f"{frontend.retry_after_s:g}"})
                except RuntimeError as e:
                    frontend._kv_restore(handle, meta, leaves, nbytes)
                    return self._json(503, {"error": str(e)})
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
                except TimeoutError as e:
                    return self._json(504, {"error": str(e)})
                return self._json(200, {
                    "tokens": [int(t) for t in result.tokens],
                    "latency_s": round(time.perf_counter() - t0, 4),
                    "ttft_s": round(result.ttft_s, 4),
                    "itl_ms": round(result.itl_ms, 3),
                    "trace_id": trace_id,
                    "handle": handle,
                    "spans": {k: round(v, 4)
                              for k, v in result.spans.items()},
                })

            def _kv_put(self, handle: str):
                """Receive one KV handoff (the peer-shard-wire idiom:
                framed bytes, crc32 per chunk). A corrupt/truncated
                body is the SENDER's 400 — it then takes the local
                fallback instead of poisoning the decode pool."""
                if not handle:
                    return self._json(400, {"error": "empty handle"})
                body = self._body()
                if len(body) > frontend.kv_store_max_bytes:
                    # reject BEFORE unpack: accepting a snapshot the
                    # store cannot hold would 200 the push and then
                    # self-evict it — every decode leg 404s and the
                    # request pays prefill TWICE. A 413 here makes the
                    # sender take its local-prefill fallback instead
                    # (decode from the snapshot it already holds).
                    return self._json(413, {
                        "error": f"kv body {len(body)} bytes exceeds "
                                 f"store capacity "
                                 f"{frontend.kv_store_max_bytes}"})
                try:
                    meta, leaves = kv_transfer.unpack_kv(body)
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
                frontend._kv_store_put(handle, meta, leaves, len(body))
                return self._json(200, {
                    "ok": True, "handle": handle, "bytes": len(body)})

            def _migrate(self, handle: str):
                """Live-migration intake: resume a mid-stream request
                from a pushed slot export (drain) or its periodic
                mirror (reactive, after the source died). The resume
                budget derives from the manifest, so the caller's body
                may be empty; the response carries the FULL token list
                — previously-streamed tokens included — bit-identical
                to what the unmigrated stream would have produced
                (greedy decode, same weights). 404 on an unknown or
                expired handle — the caller's cue to fall down the
                ladder."""
                if not handle:
                    return self._json(400, {"error": "empty handle"})
                trace_id = self._trace_id()
                entry = frontend._kv_pop(handle)
                if entry is None:
                    return self._json(
                        404, {"error": f"unknown kv handle {handle!r}"})
                meta, leaves, nbytes = entry
                if (meta or {}).get("kind") != "migration":
                    # a disagg handoff is not resumable state — put it
                    # back (its decode leg may still claim it) and
                    # reject the kind mismatch loudly
                    frontend._kv_restore(handle, meta, leaves, nbytes)
                    return self._json(400, {
                        "error": f"handle {handle!r} is not a "
                                 f"migration export"})
                t0 = time.perf_counter()
                try:
                    result = frontend.submit_and_wait_kv(
                        {**meta, "leaves": leaves},
                        int(meta.get("budget", 0)) + 1,
                        trace_id=trace_id)
                except Overloaded as e:
                    frontend._kv_restore(handle, meta, leaves, nbytes)
                    return self._json(
                        429, {"error": str(e)},
                        headers={"Retry-After":
                                 f"{frontend.retry_after_s:g}"})
                except RuntimeError as e:
                    frontend._kv_restore(handle, meta, leaves, nbytes)
                    return self._json(503, {"error": str(e)})
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
                except TimeoutError as e:
                    return self._json(504, {"error": str(e)})
                with frontend._lock:
                    frontend.migrated_in += 1
                return self._json(200, {
                    "tokens": [int(t) for t in result.tokens],
                    "latency_s": round(time.perf_counter() - t0, 4),
                    "ttft_s": round(result.ttft_s, 4),
                    "itl_ms": round(result.itl_ms, 3),
                    "trace_id": trace_id,
                    "handle": handle,
                    "migrated": True,
                    "spans": {k: round(v, 4)
                              for k, v in result.spans.items()},
                })

            def _mirror(self):
                """Router-driven periodic slot mirror: export the named
                live request's resumable state WITHOUT removing it and
                push the snapshot into the chosen peer's handle store —
                the checkpoint the reactive-migration rung resumes from
                if this pod dies mid-stream."""
                try:
                    req = json.loads(self._body())
                    trace_id = str(req.get("trace_id") or "")
                    target = str(req.get("target") or "")
                    handle = str(req.get("handle") or "")
                    if not trace_id or not target or not handle:
                        raise ValueError(
                            "trace_id, target and handle required")
                except Exception as e:
                    return self._json(400, {"error": f"bad request: {e}"})
                with frontend._lock:
                    rid = frontend._trace_rids.get(trace_id)
                if rid is None:
                    return self._json(404, {
                        "error": f"no live request for trace "
                                 f"{trace_id!r}"})
                export = getattr(frontend.engine, "export_slot", None)
                if not callable(export):
                    return self._json(
                        501, {"error": "engine cannot export slots"})
                try:
                    kv = export(rid, remove=False)
                except ValueError as e:
                    return self._json(400, {"error": str(e)})
                if kv is None:
                    # queued / mid-prefill / already finished: nothing
                    # mirrorable right now — the router just retries on
                    # its next mirror tick
                    return self._json(
                        404, {"error": "request not mirrorable"})
                ok, nbytes, err = frontend._push_kv(target, handle, kv)
                if not ok:
                    return self._json(
                        502, {"error": f"mirror push failed: {err}"})
                with frontend._lock:
                    frontend.mirrors_out += 1
                return self._json(200, {
                    "ok": True, "handle": handle,
                    "tokens": len(kv.get("tokens") or ()),
                    "bytes": nbytes})

            def _drain_migrate(self):
                """Source side of ``router.drain_replica``: hand every
                slotted in-flight request to one of the given peers and
                resolve the original waiters with the peers' tokens —
                the zero-downtime resize contract."""
                try:
                    req = json.loads(self._body())
                    targets = [str(t) for t in (req.get("targets") or [])
                               if t]
                    if not targets:
                        raise ValueError("targets required")
                except Exception as e:
                    return self._json(400, {"error": f"bad request: {e}"})
                return self._json(200, frontend.drain_migrate(targets))

            def _prefix_get(self, digest: str):
                """Prefix-directory fetch: serve this replica's
                captured shared-prefix snapshot (crc-framed, the same
                wire as every other KV move) to a peer whose local LRU
                missed."""
                export = getattr(frontend.engine, "export_prefix", None)
                packed = export(digest) if callable(export) else None
                if packed is None:
                    return self._json(
                        404, {"error": f"prefix {digest!r} not held"})
                meta, leaves = packed
                body = kv_transfer.pack_kv(meta, leaves)
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                return self.wfile.write(body)

        class Server(ThreadingHTTPServer):
            daemon_threads = True
            # stock backlog is 5: a burst of concurrent clients (a
            # router fanning a fleet's traffic in) overflows it and
            # the dropped SYNs retransmit after a full second —
            # measured as 1s request-latency cliffs at 16 clients
            request_queue_size = 128

        self._server = Server((host, port), Handler)
        self.host = host
        self.port = int(self._server.server_address[1])
        self._http_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="serving-http",
        )

    # -- handler-thread side ---------------------------------------------

    def _export_gauges(self):
        """Refresh the process-global serving gauges (prefix-KV-cache
        device bytes + HBM allocator stats) — called on every /healthz
        and /metrics read so a scrape always sees current truth.
        Best-effort: telemetry must never break the probe. Returns the
        hbm block (or None) for the healthz body."""
        try:
            from k8s_tpu.controller import metrics as M

            M.SERVING_PREFIX_CACHE_BYTES.set(float(
                self.engine.stats.get("prefix_cache_bytes", 0) or 0))
            stats = self.engine.stats
            if stats.get("spec_decode_rounds"):
                # self-speculative decode telemetry (docs/SERVING.md
                # "Disaggregation"): lifetime totals exported as
                # gauges the fleet scrape reads per replica
                M.SERVING_SPEC_DECODE_ROUNDS.set(
                    float(stats.get("spec_decode_rounds", 0) or 0))
                M.SERVING_SPEC_DECODE_DRAFTED.set(
                    float(stats.get("spec_decode_drafted", 0) or 0))
                M.SERVING_SPEC_DECODE_ACCEPTED.set(
                    float(stats.get("spec_decode_accepted", 0) or 0))
        except Exception:
            pass
        try:
            from k8s_tpu.obs.health import hbm_block

            return hbm_block(task="serving")
        except Exception:
            return None

    def _queue_depth(self) -> int:
        qd = getattr(self.engine, "queue_depth", None)
        if callable(qd):
            return int(qd())
        return int(self.engine.stats.get("queue_depth", 0))

    def _consume_healthz_fault(self) -> bool:
        with self._lock:
            if self._healthz_faults > 0:
                self._healthz_faults -= 1
                return True
        return False

    def arm_healthz_faults(self, n: int = 1) -> None:
        """Chaos hook (``router-stats-flake``): the next ``n`` GET
        /healthz requests return 500 while generation keeps working."""
        with self._lock:
            self._healthz_faults += int(n)

    def submit_and_wait(self, prompt, max_new_tokens: int,
                        trace_id: str = "") -> _Result:
        """Submit one request and block until its tokens are ready;
        returns a :class:`_Result` (tokens + TTFT/ITL timing).
        Raises RuntimeError while draining (503 to the client) so the
        load balancer retries another replica during rollout, and
        :class:`Overloaded` (429) when backpressure is on and the
        engine queue is at the threshold."""
        return self._submit_and_wait(
            lambda: self.engine.submit(prompt, max_new_tokens),
            trace_id=trace_id)

    def submit_and_wait_kv(self, kv: dict, max_new_tokens: int,
                           trace_id: str = "") -> _Result:
        """Decode-pool intake: same contract as :meth:`submit_and_wait`
        over a received KV seed instead of a prompt."""
        return self._submit_and_wait(
            lambda: self.engine.submit_with_kv(kv, max_new_tokens),
            trace_id=trace_id)

    def submit_and_wait_prefill(self, prompt,
                                max_new_tokens: int) -> _Result:
        """Prefill-pool intake: the result's ``kv`` field carries the
        finished working-cache snapshot (``Request.kv_result``)."""
        return self._submit_and_wait(
            lambda: self.engine.submit_prefill(prompt, max_new_tokens))

    def _submit_and_wait(self, submit_fn, trace_id: str = "") -> _Result:
        with self._lock:
            if self._draining:
                raise RuntimeError("draining: not accepting new requests")
            if self.max_queue_depth > 0 \
                    and self._queue_depth() >= self.max_queue_depth:
                self.rejected += 1
                raise Overloaded(
                    f"engine queue depth {self._queue_depth()} >= "
                    f"max_queue_depth {self.max_queue_depth}")
            rid = submit_fn()
            ev = threading.Event()
            self._waiters[rid] = ev
            if trace_id and self.migration:
                # the router addresses live requests by the trace id it
                # minted (mirror ticks); registration lives exactly as
                # long as the waiter
                self._trace_rids[trace_id] = rid
        self._work.set()
        try:
            if not ev.wait(self.request_timeout):
                with self._lock:
                    self._waiters.pop(rid, None)
                    # the engine may still finish this request later;
                    # with the waiter gone _resolve_finished drops the
                    # tokens, but the finish could also have raced this
                    # timeout — purge either way so nothing accumulates
                    self._results.pop(rid, None)
                raise TimeoutError(f"request {rid} timed out")
            with self._lock:
                result = self._results.pop(rid)
        finally:
            if trace_id:
                with self._lock:
                    if self._trace_rids.get(trace_id) == rid:
                        del self._trace_rids[trace_id]
        if isinstance(result, Exception):
            raise result
        return result

    # -- disaggregation: KV handoff ---------------------------------------

    def _kv_expire_locked(self) -> None:
        """Drop entries older than ``kv_ttl_s`` (caller holds the
        lock). Size bounds alone only reclaim on NEW pushes — an
        orphaned handoff (router gave up after the retry, or died
        between legs) on a then-quiet pod would pin its hundreds of
        MB of host snapshot indefinitely; the TTL bounds retention in
        TIME as well as bytes.

        Per-KIND TTLs: a disagg handoff lives ``kv_ttl_s`` (one router
        leg), a migration mirror lives ``kv_migration_ttl_s`` (it must
        survive a whole decode stream — the 120s default silently
        expired long streams' mirrors right when they were needed).
        Expiring a MIGRATION handle increments its own counter: a
        peer's /v1/migrate then 404s for a *known, counted* reason
        instead of silently aliasing the disagg 404-fallback cue.
        Full scan, not head-pop: per-kind cutoffs break the
        insert-order == expiry-order property, and the store is
        bounded at ``kv_store_max`` entries anyway."""
        now = time.monotonic()
        for handle in list(self._kv_store):
            meta, _, nb, born = self._kv_store[handle]
            mig = (meta or {}).get("kind") == "migration"
            ttl = self.kv_migration_ttl_s if mig else self.kv_ttl_s
            if ttl <= 0 or now - born <= ttl:
                continue
            del self._kv_store[handle]
            self._kv_store_bytes -= nb
            if mig:
                self.kv_migration_expired += 1

    def _kv_insert(self, handle: str, meta: dict, leaves,
                   nbytes: int) -> None:
        """Shared insert/evict (count AND bytes bounds); caller holds
        no lock."""
        with self._lock:
            self._kv_expire_locked()
            old = self._kv_store.pop(handle, None)
            if old is not None:
                self._kv_store_bytes -= old[2]
            self._kv_store[handle] = (meta, leaves, int(nbytes),
                                      time.monotonic())
            self._kv_store_bytes += int(nbytes)
            while self._kv_store and (
                    len(self._kv_store) > self.kv_store_max
                    or self._kv_store_bytes > self.kv_store_max_bytes):
                _, (_, _, nb, _) = self._kv_store.popitem(last=False)
                self._kv_store_bytes -= nb

    def _kv_store_put(self, handle: str, meta: dict, leaves,
                      nbytes: int) -> None:
        self._kv_insert(handle, meta, leaves, nbytes)
        with self._lock:
            self.kv_received += 1
            self.kv_bytes_in += int(nbytes)

    def _kv_pop(self, handle: str):
        """Single-use handle lookup: ``(meta, leaves, nbytes)`` —
        popped so a replayed decode call can't double-seed a slot from
        a stale snapshot. An expired handle is a miss (→ 404 → the
        router's fallback cue)."""
        with self._lock:
            self._kv_expire_locked()
            entry = self._kv_store.pop(handle, None)
            if entry is None:
                return None
            self._kv_store_bytes -= entry[2]
            return entry[:3]

    def _kv_restore(self, handle: str, meta: dict, leaves,
                    nbytes: int) -> None:
        """Re-insert a popped handle whose admission never happened
        (transient Overloaded/draining) — the snapshot is intact, so a
        retried decode call must not cost a full re-prefill. Does NOT
        recount kv_received."""
        self._kv_insert(handle, meta, leaves, nbytes)

    def _kv_store_stats(self) -> dict:
        with self._lock:
            self._kv_expire_locked()
            out = {
                "handles": len(self._kv_store),
                "bytes_held": self._kv_store_bytes,
                "received": self.kv_received,
                "bytes_in": self.kv_bytes_in,
                "pushed": self.kv_pushed,
                "push_failures": self.kv_push_failures,
                "bytes_out": self.kv_bytes_out,
            }
            if self.migration:
                # only when migration is on: no-migration fleets keep
                # the pre-migration kv key set byte-identical
                out["migration_expired"] = self.kv_migration_expired
            return out

    # -- live migration + prefix directory --------------------------------

    def _migration_stats(self) -> dict:
        """The healthz ``migration`` block: mirror/drain/resume
        counters plus this replica's prefix-directory advertisement
        (the digests its local prefix LRU holds) — the router's poll
        aggregates these into the fleet-wide directory."""
        eng = self.engine
        with self._lock:
            out = {
                "mirrors_out": self.mirrors_out,
                "migrated_out": self.migrated_out,
                "migrated_in": self.migrated_in,
                "migration_expired": self.kv_migration_expired,
            }
        out["prefix_len"] = int(getattr(eng, "_prefix_len", 0) or 0)
        keys_fn = getattr(eng, "prefix_keys", None)
        out["prefix_keys"] = list(keys_fn()) if callable(keys_fn) else []
        return out

    def _push_kv(self, target: str, handle: str, kv: dict):
        """POST one packed export into ``target``'s handle store;
        returns ``(ok, nbytes, err)``. Shared by the mirror and drain
        paths — the migration-specific counters are the caller's, but
        the bytes ride the same kv push ledger as disagg handoffs."""
        meta = {k: v for k, v in kv.items() if k != "leaves"}
        meta["handle"] = handle
        body = kv_transfer.pack_kv(meta, kv.get("leaves") or [])
        try:
            req = urllib.request.Request(
                target.rstrip("/") + f"/v1/kv/{handle}", data=body,
                headers={"Content-Type": "application/octet-stream"})
            with urllib.request.urlopen(
                    req, timeout=self.kv_push_timeout) as resp:
                if resp.status != 200:
                    raise RuntimeError(f"kv push HTTP {resp.status}")
        except Exception as e:  # noqa: BLE001 - any failure reported
            with self._lock:
                self.kv_push_failures += 1
            return False, len(body), str(e)
        with self._lock:
            self.kv_pushed += 1
            self.kv_bytes_out += len(body)
        return True, len(body), ""

    def _migrate_on_peer(self, target: str, handle: str):
        """Blocking ``POST /v1/migrate/{handle}`` on the peer; returns
        ``(payload, err)`` — payload None on any failure."""
        try:
            req = urllib.request.Request(
                target.rstrip("/") + f"/v1/migrate/{handle}", data=b"",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout) as resp:
                if resp.status != 200:
                    raise RuntimeError(f"migrate HTTP {resp.status}")
                return json.loads(resp.read()), ""
        except Exception as e:  # noqa: BLE001
            return None, str(e)

    def drain_migrate(self, targets) -> dict:
        """Source side of ``router.drain_replica``: export every
        SLOTTED in-flight request (remove=True — the slot frees as the
        export leaves) and hand it to a peer; the ORIGINAL waiter
        resolves with the peer's bit-identical full token list, so the
        client never observes the move. Per-request failure ladder:
        peer push/resume failed → re-import the export LOCALLY under a
        fresh rid aliased back to the original waiter (zero recompute —
        the export still holds the KV rows); local re-import also
        impossible → fail the waiter with RuntimeError rather than
        hang it. Requests still queued or mid-prefill export ``None``
        and simply finish here — the drain moves decode streams and
        never re-prefills anything."""
        export = getattr(self.engine, "export_slot", None)
        out = {"migrated": 0, "failed": 0, "skipped": 0}
        if not callable(export) or not targets:
            return out
        with self._lock:
            rids = list(self._waiters)
        for i, rid in enumerate(rids):
            try:
                kv = export(rid, remove=True)
            except ValueError:
                kv = None
            if kv is None:
                out["skipped"] += 1   # unslotted: finishes locally
                continue
            handle = f"drain-{self.port}-{rid}"
            target = targets[i % len(targets)]
            ok, _, err = self._push_kv(target, handle, kv)
            payload = None
            if ok:
                payload, err = self._migrate_on_peer(target, handle)
            if payload is not None and payload.get("tokens") is not None:
                with self._lock:
                    ev = self._waiters.pop(rid, None)
                    if ev is not None:
                        self._results[rid] = _Result(
                            np.asarray(payload["tokens"], np.int32),
                            float(payload.get("ttft_s", 0.0)),
                            float(payload.get("itl_ms", 0.0)),
                            spans=dict(payload.get("spans") or {}))
                        self.served += 1
                        ev.set()
                    self.migrated_out += 1
                out["migrated"] += 1
                continue
            try:
                with self._lock:
                    rid2 = self.engine.submit_with_kv(
                        kv, int(kv.get("budget", 0)) + 1)
                    self._aliases[rid2] = rid
                self._work.set()
            except Exception as e:  # noqa: BLE001 - double failure
                with self._lock:
                    ev = self._waiters.pop(rid, None)
                    if ev is not None:
                        self._results[rid] = RuntimeError(
                            f"drain migration failed both ways: "
                            f"peer: {err}; local: {e}")
                        ev.set()
            out["failed"] += 1
        return out

    def _maybe_fetch_prefix(self, prompt, peer: str) -> None:
        """Prefix-directory fetch (best-effort): when the router says
        ``peer`` holds this prompt's shared-prefix snapshot and the
        local LRU misses, pull it over ``GET /v1/prefix/{digest}`` and
        install it before prefill — the fleet-wide hit path. Any
        failure degrades to computing the prefix locally, exactly as
        if the directory had never spoken."""
        eng = self.engine
        digest_fn = getattr(eng, "prefix_digest", None)
        has = getattr(eng, "has_prefix", None)
        install = getattr(eng, "install_prefix", None)
        if not (callable(digest_fn) and callable(has)
                and callable(install)):
            return
        try:
            digest = digest_fn(prompt)
            if not digest or has(digest):
                return
            with urllib.request.urlopen(
                    peer.rstrip("/") + f"/v1/prefix/{digest}",
                    timeout=self.prefix_fetch_timeout) as resp:
                body = resp.read()
            meta, leaves = kv_transfer.unpack_kv(body)
            if (meta or {}).get("kind") != "prefix":
                return
            install(meta, leaves)
            eng.stats["prefix_remote_hits"] = \
                eng.stats.get("prefix_remote_hits", 0) + 1
            from k8s_tpu.controller import metrics as M

            M.SERVING_PREFIX_REMOTE_HITS.inc()
        except Exception:   # noqa: BLE001 - telemetry-grade best effort
            return

    def prefill_and_push(self, prompt, max_new_tokens: int,
                         kv_target: str, handle: str,
                         prefix_from: str = ""):
        """The prefill leg, end to end: chunked prefill to completion,
        then stream the finished KV to ``kv_target``'s
        ``/v1/kv/{handle}`` (crc32-framed, the peer-shard-wire idiom).
        Returns ``(http_code, payload)``:

        - push landed → ``{"kv_pushed": true, handle, kv_bytes,
          first_token, ttft_s, spans{engine_queue_s, prefill_s,
          kv_transfer_s}}`` — the router then runs the decode leg.
        - push failed (dead/slow decode peer, crc reject) → the
          LOCAL-PREFILL FALLBACK: the snapshot this worker already
          holds seeds its own decode slot and the complete generation
          returns with ``{"local_fallback": true, tokens, ...}`` — a
          lost transfer degrades latency, never the request.

        ``prefix_from`` (router-injected): a peer URL advertising this
        prompt's shared-prefix snapshot — fetched and installed before
        prefill when the local LRU misses (migration fleets only)."""
        t_req0 = time.perf_counter()
        if prefix_from and self.migration:
            self._maybe_fetch_prefix(prompt, prefix_from)
        result = self.submit_and_wait_prefill(prompt, max_new_tokens)
        kv = result.kv or {}
        meta = {k: v for k, v in kv.items() if k != "leaves"}
        meta["handle"] = handle
        body = kv_transfer.pack_kv(meta, kv.get("leaves") or [])
        t0 = time.perf_counter()
        pushed, push_err = True, ""
        try:
            req = urllib.request.Request(
                kv_target.rstrip("/") + f"/v1/kv/{handle}", data=body,
                headers={"Content-Type": "application/octet-stream"})
            with urllib.request.urlopen(
                    req, timeout=self.kv_push_timeout) as resp:
                if resp.status != 200:
                    raise RuntimeError(f"kv push HTTP {resp.status}")
        except Exception as e:  # noqa: BLE001 - any failure falls back
            pushed, push_err = False, str(e)
        transfer_s = time.perf_counter() - t0
        with self._lock:
            if pushed:
                self.kv_pushed += 1
                self.kv_bytes_out += len(body)
            else:
                self.kv_push_failures += 1
        spans = {
            "engine_queue_s": round(
                result.spans.get("engine_queue_s", 0.0), 4),
            "prefill_s": round(result.spans.get("prefill_s", 0.0), 4),
            "kv_transfer_s": round(transfer_s, 4),
        }
        if pushed:
            return 200, {
                "kv_pushed": True, "handle": handle,
                "kv_bytes": len(body),
                "first_token": int(kv.get("first_token", 0)),
                "plen": int(kv.get("plen", 0)),
                "ttft_s": round(result.ttft_s, 4),
                "latency_s": round(time.perf_counter() - t_req0, 4),
                "spans": spans,
            }
        # local-prefill fallback: decode HERE from the snapshot we
        # still hold — no recompute, bit-identical tokens
        res2 = self._submit_and_wait(
            lambda: self.engine.submit_with_kv(kv, max_new_tokens))
        spans["decode_s"] = round(
            res2.spans.get("prefill_s", 0.0)
            + res2.spans.get("decode_s", 0.0), 4)
        spans["engine_queue_s"] = round(
            spans["engine_queue_s"]
            + res2.spans.get("engine_queue_s", 0.0), 4)
        return 200, {
            "local_fallback": True, "kv_pushed": False,
            "push_error": push_err, "handle": handle,
            "kv_bytes": len(body),
            "tokens": [int(t) for t in res2.tokens],
            "ttft_s": round(spans["engine_queue_s"]
                            + spans["prefill_s"]
                            + spans["kv_transfer_s"], 4),
            "itl_ms": round(res2.itl_ms, 3),
            # WALL latency incl. the fallback decode: the router
            # derives its own router_s by subtracting this from its
            # measured elapsed — omitting the decode phase here showed
            # up as phantom seconds of "router overhead" per fallback
            "latency_s": round(time.perf_counter() - t_req0, 4),
            "spans": spans,
        }

    # -- pump side ---------------------------------------------------------

    def _resolve_finished(self) -> None:
        done = self.engine.pop_finished()
        if not done:
            return
        with self._lock:
            for rid, req in done.items():
                # a drain hand-off that failed back to a local
                # re-import finished under a NEW engine rid — resolve
                # the ORIGINAL waiter it aliases
                rid = self._aliases.pop(rid, rid)
                ev = self._waiters.pop(rid, None)
                if ev is not None:
                    self.served += 1
                    n = len(req.tokens)
                    # getattr: stub/legacy engines without timing
                    # fields still resolve (timing reads as 0)
                    first = getattr(req, "first_token_at", 0.0)
                    sub = getattr(req, "submitted_at", 0.0)
                    ttft = max(0.0, first - sub)
                    # mean stream cadence after the first token — the
                    # per-request ITL sample the router aggregates
                    # (percentile-grade ITL needs per-chunk walls,
                    # which stay bench-side; docs/SERVING.md)
                    itl_ms = (
                        1e3 * max(
                            0.0, getattr(req, "finished_at", 0.0) - first)
                        / (n - 1) if n > 1 else 0.0)
                    # TTFT decomposition off the engine's own stamps:
                    # queue (submit → scheduler pickup) + prefill
                    # (pickup → first token) == ttft; engines without
                    # the pickup stamp report it all as prefill
                    ps = getattr(req, "prefill_start_at", 0.0)
                    queue_s = max(0.0, ps - sub) if ps else 0.0
                    prefill_s = max(0.0, first - ps) if ps else ttft
                    decode_s = max(
                        0.0, getattr(req, "finished_at", 0.0) - first)
                    self._results[rid] = _Result(
                        np.asarray(req.tokens, np.int32), ttft, itl_ms,
                        spans={"engine_queue_s": queue_s,
                               "prefill_s": prefill_s,
                               "decode_s": decode_s},
                        kv=getattr(req, "kv_result", None))
                    ev.set()
                else:
                    # no waiter ⇒ the client timed out and left: drop
                    # the tokens instead of accumulating them forever —
                    # and don't count undelivered work as served
                    self.abandoned += 1

    def serve(self, should_stop) -> None:
        """Run the pump until ``should_stop()`` — then drain and close.
        Call from the process main thread (the engine's scheduling
        thread); returns only when the engine is fully drained."""
        self._http_thread.start()
        try:
            while not should_stop():
                busy = self.engine.step()
                self._resolve_finished()
                if not busy:
                    # idle: block on the submission poke, not a spin —
                    # 50 ms bounds shutdown-signal latency when no
                    # client ever connects
                    self._work.wait(0.05)
                    self._work.clear()
        finally:
            self.drain()

    def drain(self) -> None:
        """Stop intake, finish in-flight requests, close the engine.
        Idempotent; also releases every still-parked waiter (a request
        that raced the shutdown gets its tokens if the engine finished
        it, a 503 RuntimeError otherwise)."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        self._server.shutdown()
        try:
            while self.engine.step():
                self._resolve_finished()
            self._resolve_finished()
        finally:
            # even if the drain pump raises (e.g. a device error
            # surfacing out of step()), parked handler threads must be
            # released and the engine/listener closed — otherwise each
            # client blocks its full request_timeout and the harvester
            # threads leak past the kubelet grace period
            with self._lock:
                for rid, ev in list(self._waiters.items()):
                    self._results[rid] = RuntimeError(
                        "server draining before request finished")
                    ev.set()
                self._waiters.clear()
            self.engine.close()
            self._server.server_close()
