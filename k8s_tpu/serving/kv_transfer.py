"""KV-handoff wire format for disaggregated prefill/decode serving.

A prefill worker finishes a prompt's chunked prefill holding the
request's working KV cache — the exact-token device object the
shared-prefix snapshot machinery already captures (docs/SERVING.md
"Fleet"). Phase-split serving ships that object to a DECODE worker
over ``POST /v1/kv/{handle}`` on the engine front-end, and this module
is the wire format: the same idiom as the checkpoint peer-shard wire
(:mod:`k8s_tpu.ckpt.peer` — plain bytes over stdlib HTTP, integrity
checked per chunk), shaped for a pytree of cache leaves instead of a
single shard.

Frame layout (all integers little-endian uint32)::

    [manifest_len][manifest JSON utf-8]
    repeat per chunk: [chunk_len][crc32][chunk bytes]

The manifest carries the handle metadata (``plen``, ``rows``,
``first_token``, the prompt token ids) plus per-leaf ``shape``/
``dtype`` specs in CACHE-TREE FLATTEN ORDER — both ends run the same
model config, so ``jax.tree_util`` flattening orders the leaves
identically and no treedef crosses the wire. Leaf payloads are
concatenated into fixed-size chunks, each with its own crc32 — a
truncated or bit-flipped transfer fails loudly at the receiver (the
sender then takes the local-prefill fallback instead of handing the
decode pool a corrupt cache).

Stdlib + numpy only: this rides in the same ConfigMap-shipped image as
the launcher.

Payload kinds ride as manifest conventions — the frame format itself
is kind-agnostic. ``meta["kind"]`` distinguishes the prefill→decode
handoff (absent/empty, the original payload), a live-migration slot
export (``"migration"``: adds ``tokens`` — the full streamed list
ending at the un-fed boundary token — plus ``budget`` and
``max_new_tokens``; docs/SERVING.md "Live migration"), and a shared-
prefix snapshot (``"prefix"``: ``stage`` + the raw prefix ``tokens``,
served over ``GET /v1/prefix/{digest}``). Receivers dispatch on the
kind and reject mismatches with 400 — the same fail-loud contract as
a crc mismatch.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Dict, List, Tuple

import numpy as np

# 1 MiB chunks: big enough that framing overhead vanishes, small
# enough that a mid-transfer kill is detected within one crc window
DEFAULT_CHUNK_BYTES = 1 << 20

_U32 = struct.Struct("<I")


def _dtype_of(name: str) -> np.dtype:
    """Resolve a dtype NAME, falling back to the ml_dtypes extension
    types (bfloat16 etc.) numpy proper doesn't know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def pack_kv(meta: Dict, leaves: List[np.ndarray],
            chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> bytes:
    """Serialize ``meta`` + cache leaves into one framed body.
    ``meta`` must be JSON-serializable; ``leaves`` are the host-side
    cache arrays in tree-flatten order.

    Chunks frame directly from each leaf's contiguous byte view — a
    handoff is potentially hundreds of MB, so intermediate
    whole-payload copies (leaf → payload buffer → output) would spike
    host memory ~3x per push on a pod already holding the model;
    the single copy here is the append into the output buffer."""
    specs = []
    flats = []
    total = 0
    for leaf in leaves:
        a = np.ascontiguousarray(leaf)
        # dtype by NAME, not .str: extension dtypes (bfloat16 via
        # ml_dtypes — the serving cache's common dtype) stringify to
        # an opaque void spec under .str and would not round-trip
        specs.append({"shape": list(a.shape), "dtype": a.dtype.name})
        flat = a.reshape(-1).view(np.uint8)
        flats.append(flat)
        total += flat.size
    manifest = dict(meta)
    manifest["leaves"] = specs
    manifest["total_bytes"] = total
    mbytes = json.dumps(manifest).encode()
    out = bytearray()
    out += _U32.pack(len(mbytes))
    out += mbytes
    wrote = 0
    for flat in flats:
        for off in range(0, flat.size, chunk_bytes):
            chunk = flat[off:off + chunk_bytes]
            out += _U32.pack(chunk.size)
            out += _U32.pack(zlib.crc32(chunk) & 0xFFFFFFFF)
            out += memoryview(chunk)
            wrote += chunk.size
    if wrote == 0:
        # zero-byte payloads still get one (empty) framed chunk so the
        # receiver's loop shape is uniform
        out += _U32.pack(0)
        out += _U32.pack(zlib.crc32(b"") & 0xFFFFFFFF)
    return bytes(out)


def unpack_kv(body: bytes) -> Tuple[Dict, List[np.ndarray]]:
    """Parse one framed body back into ``(meta, leaves)``. Raises
    ``ValueError`` on any framing/crc/shape inconsistency — the
    receiver maps that to HTTP 400 and the sender falls back."""
    if len(body) < _U32.size:
        raise ValueError("kv transfer: truncated (no manifest length)")
    (mlen,) = _U32.unpack_from(body, 0)
    off = _U32.size
    if off + mlen > len(body):
        raise ValueError("kv transfer: truncated manifest")
    try:
        manifest = json.loads(body[off:off + mlen])
    except Exception as e:
        raise ValueError(f"kv transfer: bad manifest: {e}")
    off += mlen
    # walk the frames verifying crcs against VIEWS of the body (no
    # payload-wide copy — the sender-side rationale in pack_kv), and
    # record each chunk's (start, len) range for the fill pass below
    view = memoryview(body)
    ranges = []
    total_seen = 0
    while off < len(body):
        if off + 2 * _U32.size > len(body):
            raise ValueError("kv transfer: truncated chunk header")
        (clen,) = _U32.unpack_from(body, off)
        (crc,) = _U32.unpack_from(body, off + _U32.size)
        off += 2 * _U32.size
        if off + clen > len(body):
            raise ValueError("kv transfer: truncated chunk body")
        if zlib.crc32(view[off:off + clen]) & 0xFFFFFFFF != crc:
            raise ValueError("kv transfer: chunk crc32 mismatch")
        ranges.append((off, clen))
        total_seen += clen
        off += clen
    total = int(manifest.get("total_bytes", -1))
    if total != total_seen:
        raise ValueError(
            f"kv transfer: payload {total_seen} bytes != manifest "
            f"total {total}")
    specs = manifest.pop("leaves", [])
    leaves: List[np.ndarray] = []
    # fill pass: ONE copy, body ranges → each leaf's own buffer (a
    # leaf may span chunk boundaries; a chunk never spans leaves the
    # way pack_kv frames, but tolerating it here keeps the format
    # boundary-agnostic)
    ri, rpos = 0, 0
    for spec in specs:
        dt = _dtype_of(spec["dtype"])
        shape = tuple(int(d) for d in spec["shape"])
        n = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        arr = np.empty(shape, dt)
        flat = arr.reshape(-1).view(np.uint8)
        pos = 0
        while pos < n:
            if ri >= len(ranges):
                raise ValueError("kv transfer: leaf overruns payload")
            start, clen = ranges[ri]
            take = min(n - pos, clen - rpos)
            flat[pos:pos + take] = np.frombuffer(
                view[start + rpos:start + rpos + take], np.uint8)
            pos += take
            rpos += take
            if rpos == clen:
                ri, rpos = ri + 1, 0
        leaves.append(arr)
    while ri < len(ranges) and ranges[ri][1] == rpos:
        ri, rpos = ri + 1, 0  # fully-consumed / empty trailing frames
    if ri < len(ranges):
        raise ValueError("kv transfer: trailing payload bytes")
    return manifest, leaves
