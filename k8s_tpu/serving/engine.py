"""Continuous-batching serving engine — TPU-shaped.

GPU serving stacks (vLLM-style) get request-level elasticity from
*paged* KV caches: per-request block tables resolved by the kernel at
runtime. On TPU that indirection fights the hardware — Mosaic wants
static shapes and contiguous slabs. The TPU-native shape of the same
idea is **slot-based ragged batching**:

- ONE static decode batch of ``max_slots`` rows, compiled once. Every
  row ("slot") holds one in-flight request at its own cache depth.
- The fused decode kernel appends/attends at a **per-row** position
  (``pos`` is a scalar-prefetch vector — `ops/attention.py`), so one
  kernel launch serves all slots regardless of how ragged they are.
- Arrivals don't recompile anything: a free slot is filled by a
  batch-1 **prefill** in bounded CHUNKS (each padded to a static
  chunk bucket) accumulated in a persistent batch-1 working cache,
  then scattered into the big cache at the slot index via donated
  ``dynamic_update_slice`` (in-place, no cache copy). A **token
  budget** (``max_tokens_per_round``) caps the prefill tokens spent
  per pump round after decode rows claim theirs, so a long prompt
  never parks decode behind more than one bounded chunk — the
  chunked-prefill scheduling that production stacks (Sarathi/vLLM)
  use, in TPU static-shape form. (``chunked_prefill=False`` keeps the
  legacy one-shot-per-prompt prefill for A/B measurement.)
- Decode runs in **chunks of K steps inside one jit** (`lax.scan`):
  EOS/budget deactivation happens on-device, so the host syncs once
  per K tokens, not per token — load-bearing over a remote-tunnel
  PJRT transport where every host sync is a round-trip.

Inactive slots still compute (static shapes — that's the TPU trade):
their writes land on a frozen, masked cache row and their outputs are
dropped. Utilization therefore degrades gracefully with load instead
of recompiling with it.

The reference has no serving analogue (training-only operator,
SURVEY.md §0). Oracle for correctness: each request's tokens must
equal a solo :func:`k8s_tpu.models.llama.generate` run with the same
params (pinned by ``tests/test_serving.py``).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import itertools
import queue
import threading
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from k8s_tpu.models.llama import LlamaForCausalLM, _pick_token


@dataclasses.dataclass
class Request:
    """One generation request. ``tokens`` accumulates the output
    (first token from prefill + decoded tokens, prompt excluded)."""

    rid: int
    prompt: np.ndarray  # [plen] int32
    max_new_tokens: int
    tokens: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    submitted_at: float = 0.0   # time.perf_counter at submit()
    finished_at: float = 0.0    # ... at attribution of the last token
    first_token_at: float = 0.0  # ... at attribution of the first token
    # ... when the scheduler first picked this request up (left the
    # admission queue): splits TTFT into engine-queue vs prefill — the
    # request-path span decomposition (docs/OBSERVABILITY.md)
    prefill_start_at: float = 0.0
    prefill_done: int = 0       # real prompt tokens prefilled so far
    # (attribution wall time, tokens attributed) per harvested chunk —
    # the raw material for TTFT / inter-token percentiles; bounded by
    # ceil(max_new / decode_chunk) entries per request
    token_times: List = dataclasses.field(default_factory=list)
    # Disaggregated serving (docs/SERVING.md "Disaggregation"):
    # prefill_only requests run chunked prefill to completion, then
    # finish with kv_result = the working-cache KV snapshot + first
    # token (never touching a decode slot); kv_seed requests carry a
    # received snapshot that scatters straight into a slot, skipping
    # prefill compute entirely — the two halves of a KV handoff.
    prefill_only: bool = False
    kv_seed: Optional[dict] = None
    kv_result: Optional[dict] = None


def _next_chunk(chunk_buckets: Sequence[int], offset: int, plen: int,
                allowed: int, max_seq: int):
    """Plan ONE prefill chunk for a prompt with ``offset`` tokens
    already written: returns ``(bucket, take, final)`` or None when no
    chunk fits the ``allowed`` token budget this round.

    Invariants (validated at engine init): every bucket is a multiple
    of the smallest bucket g, and ``max_seq % g == 0`` — so an
    in-range bucket always exists once ``allowed >= g``, and a chunk's
    DUS write ``offset + bucket`` never exceeds ``max_seq`` (clamped
    DUS writes would silently corrupt neighbor rows).

    Intermediate chunks are always FULL (take == bucket): the working
    cache's write offset then equals the count of real tokens, and
    only the final chunk pads (pad rows land above the prompt where
    they stay masked until decode overwrites them)."""
    r = plen - offset
    fin = [b for b in chunk_buckets
           if r <= b <= allowed and offset + b <= max_seq]
    if fin:
        return min(fin), r, True
    full = [b for b in chunk_buckets
            if b <= min(allowed, r) and offset + b <= max_seq]
    if not full:
        return None
    return max(full), max(full), False


def _tree_scatter_slot(cache, small, slot, plen_b: int):
    """Scatter a batch-1 prefill cache into row ``slot`` of the big
    cache. Only the first ``plen_b`` rows (the padded prompt) are
    copied — pad rows land too but stay masked until overwritten by
    the slot's own decode appends. Leaf layouts (by name):

    - ``cached_key``/``cached_value``: [B, Hkv, S, D], rows on axis 2
    - ``key_scale``/``value_scale`` (int8-KV): [B, Hkv, 1, S], rows on
      axis 3

    Leaves may carry a leading scan-stacked layer axis
    (``scan_layers=True``: [L, B, ...]) — the batch axis is located
    from the END of the shape, so both layouts scatter identically.
    """

    def one(path, big, small_leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("cached_key", "cached_value"):
            rows_axis = big.ndim - 2          # ...the S axis
        elif name in ("key_scale", "value_scale"):
            rows_axis = big.ndim - 1          # scales: S is last
        else:
            raise ValueError(f"unknown cache leaf {name!r} (ragged "
                             "caches carry no cache_index)")
        rows = jax.lax.slice_in_dim(small_leaf, 0, plen_b, axis=rows_axis)
        batch_axis = big.ndim - 4             # [L?] B Hkv . .
        start = [jnp.int32(0)] * big.ndim
        start[batch_axis] = slot
        return jax.lax.dynamic_update_slice(big, rows, tuple(start))

    return jax.tree_util.tree_map_with_path(one, cache, small)


def _lm_head_logits(params, hidden, quant: str):
    """Head logits for a [*, E] hidden slice — prefill computes hidden
    for the whole padded prompt but only needs logits at the last REAL
    token, so the head runs on the gathered row, never on [P, V]."""
    if quant == "int8_serving":
        from k8s_tpu.ops.quant import int8_serving_matmul

        lm = params["lm_head"]
        return int8_serving_matmul(
            hidden.astype(jnp.float32), lm["kernel_q"], lm["scale"], 1
        )
    return hidden.astype(jnp.float32) @ params["lm_head"][
        "kernel"
    ].astype(jnp.float32)


# Module-level jits (llama.py house rule): defining these inside the
# engine would make every engine a fresh function object -> full
# recompile per instance; params/cache stay ARGUMENTS so weights are
# never baked into the HLO as constants.
@functools.partial(
    jax.jit,
    static_argnames=("model", "plen_b", "temperature"),
    donate_argnums=(2,),
)
def _prefill_insert(model, params, cache, slot, prompt_pb, plen, rng,
                    *, plen_b: int, temperature: float):
    """Batch-1 prefill of a padded prompt + scatter into ``slot`` of
    the (donated) big cache. Returns (cache', first_token)."""
    positions = jnp.broadcast_to(jnp.arange(plen_b), (1, plen_b))
    hidden, mut = model.apply(
        {"params": params}, prompt_pb, positions=positions,
        return_hidden=True, mutable=["cache"],
    )
    # last REAL token's hidden row (pads sit after it; causal attention
    # means they never influence it)
    h_last = jax.lax.dynamic_index_in_dim(
        hidden[0], plen - 1, axis=0, keepdims=False
    )
    logits = _lm_head_logits(params, h_last[None], model.config.quant)
    tok = _pick_token(logits, rng, temperature)[0]
    cache = _tree_scatter_slot(cache, mut["cache"], slot, plen_b)
    return cache, tok


@functools.partial(
    jax.jit,
    static_argnames=("model", "chunk_b", "temperature", "final"),
    donate_argnums=(2,),
)
def _prefill_chunk(model, params, pcache, ids_pb, offset, last_idx, rng,
                   *, chunk_b: int, temperature: float, final: bool):
    """One chunked-prefill step into the (donated) batch-1 working
    cache: writes rows [offset, offset+chunk_b) via the model's ragged
    continuation path (positions carry the append offset per row; the
    per-row position mask keeps the chunk causal against cache rows
    < offset — rows above, stale from a previous prompt, stay
    invisible). Only the ``final`` variant runs the lm_head, on the
    last REAL token's hidden row (``last_idx`` within this chunk);
    intermediate chunks return a dummy token that is never read.
    Compile keys: one per (chunk bucket, final?) pair."""
    positions = offset + jnp.broadcast_to(
        jnp.arange(chunk_b), (1, chunk_b)
    )
    hidden, mut = model.apply(
        {"params": params, "cache": pcache}, ids_pb,
        positions=positions, return_hidden=True, mutable=["cache"],
    )
    if final:
        h_last = jax.lax.dynamic_index_in_dim(
            hidden[0], last_idx, axis=0, keepdims=False
        )
        logits = _lm_head_logits(params, h_last[None], model.config.quant)
        tok = _pick_token(logits, rng, temperature)[0]
    else:
        tok = jnp.zeros((), jnp.int32)
    return mut["cache"], tok


@functools.partial(
    jax.jit, static_argnames=("rows_b",), donate_argnums=(0,)
)
def _scatter_slot_rows(cache, pcache, slot, *, rows_b: int):
    """Scatter the first ``rows_b`` rows of the prefill working cache
    into row ``slot`` of the (donated) big cache — the chunked path's
    one touch of decode state per prompt. ``rows_b`` is rounded up to
    a chunk multiple by the caller so the jit key count stays bounded
    at max_seq / prefill_chunk; rows between the prompt's real length
    and ``rows_b`` are stale working-cache garbage, which is safe: a
    slot row is only ever visible at positions <= the slot's length,
    and decode overwrites row p before the first read at position p."""
    return _tree_scatter_slot(cache, pcache, slot, rows_b)


@functools.partial(
    jax.jit,
    static_argnames=("model", "n_steps", "temperature", "eos_id"),
    donate_argnums=(2, 3, 4, 5, 6),
)
def _decode_chunk(model, params, cache, tok, lengths, active, budget,
                  rng, *, n_steps: int, temperature: float,
                  eos_id: Optional[int]):
    """K ragged decode steps in one jit. Per step, every slot advances
    iff active; EOS/budget/cache-full deactivation happens ON DEVICE.

    Returns ``(state..., packed)`` where ``packed`` is ONE int32
    array [2K+4, B] — the only thing the host ever fetches:

    - row 0: the chunk's INPUT tokens (how a freshly-prefilled slot's
      first token reaches the host without its own transfer)
    - rows 1..K: emitted tokens per step
    - rows K+1..2K: validity (1 = slot was active at step entry)
    - rows 2K+1..2K+3: final active / budget / lengths

    One packed fetch per chunk matters because serving runs over a
    remote-tunnel PJRT transport here: every separate device→host read
    is a full round-trip (~70-100 ms measured — 20-30 decode steps'
    worth), which round-tripping 6 small arrays per chunk turned into
    an 8x throughput hole. All scheduling state stays device-resident
    between chunks (the engine passes the returned arrays straight
    back in; donation keeps them in place)."""
    max_seq = model.config.max_seq_len
    tok_in = tok

    def step(carry, _):
        cache, tok, lengths, active, budget, rng = carry
        rng, r = jax.random.split(rng)
        pos = jnp.minimum(lengths, max_seq - 1)
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            tok[:, None], positions=pos[:, None], mutable=["cache"],
        )
        nxt = _pick_token(logits[:, -1], r, temperature)  # [B]
        emitted_by = active
        nxt = jnp.where(active, nxt, tok)  # freeze inactive slots
        budget = jnp.where(active, budget - 1, budget)
        lengths = jnp.where(active, jnp.minimum(lengths + 1, max_seq),
                            lengths)
        hit_eos = (
            (nxt == eos_id) & emitted_by
            if eos_id is not None
            else jnp.zeros_like(active)
        )
        active = active & (budget > 0) & ~hit_eos & (lengths < max_seq)
        return (mut["cache"], nxt, lengths, active, budget, rng), (
            nxt, emitted_by,
        )

    carry, (toks, valid) = jax.lax.scan(
        step, (cache, tok, lengths, active, budget, rng), None,
        length=n_steps,
    )
    cache, tok, lengths, active, budget, rng = carry
    packed = jnp.concatenate([
        tok_in[None], toks, valid.astype(jnp.int32),
        active.astype(jnp.int32)[None], budget[None], lengths[None],
    ], axis=0)
    return cache, tok, lengths, active, budget, rng, packed


@functools.partial(
    jax.jit, donate_argnums=(0, 1, 2, 3), static_argnames=("eos_id",)
)
def _set_slot(tok_v, lengths_v, active_v, budget_v, slot, tok_new,
              plen, max_new, *, eos_id: Optional[int]):
    """Activate ``slot`` after its prefill — ON DEVICE, including the
    finished-at-first-token check (the host never sees the prefill
    token until the next chunk's packed fetch)."""
    tok_v = tok_v.at[slot].set(tok_new)
    lengths_v = lengths_v.at[slot].set(plen)
    budget0 = max_new - 1
    fin = budget0 <= 0
    if eos_id is not None:
        fin = fin | (tok_new == eos_id)
    active_v = active_v.at[slot].set(~fin)
    budget_v = budget_v.at[slot].set(budget0)
    return tok_v, lengths_v, active_v, budget_v


@functools.partial(jax.jit, static_argnames=("model",),
                   donate_argnums=(2,))
def _verify_chunk(model, params, cache, x, positions):
    """Self-speculative decode's verify step: ONE ragged forward over
    ``x`` [B, K+1] = per row ``[last_token, draft_1..draft_K]`` at
    per-row positions ``lengths + arange(K+1)``. The model's warm-cache
    continuation path (the chunked-prefill machinery) writes all K+1
    KV rows at each row's own offset and masks causally per row, so
    the returned greedy tokens ``t_j`` are EXACTLY what sequential
    decode would emit after ``x[:, :j+1]`` — the accept-prefix rule
    (host-side) then keeps ``d_i`` iff ``d_i == t_{i-1}``, plus the
    bonus correction ``t_a``. Rejected drafts' KV rows sit above the
    accepted length where the per-row position mask hides them, and
    decode overwrites row p before the first read at position p — the
    same garbage-tolerance contract as ``_scatter_slot_rows``.

    Greedy only (``jnp.argmax`` mirrors ``_pick_token`` at
    temperature 0): acceptance must be bit-identical to the plain
    decode path, which sampling can't be."""
    logits, mut = model.apply(
        {"params": params, "cache": cache}, x,
        positions=positions, mutable=["cache"],
    )
    toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]
    return mut["cache"], toks


def _ngram_draft(ctx: np.ndarray, k: int, n: int) -> np.ndarray:
    """Prompt-lookup drafting (the model's own n-gram cache): find the
    most recent PREVIOUS occurrence of the context's trailing n-gram
    and propose the up-to-k tokens that followed it. Cheap, exact-
    arithmetic, and surprisingly effective on repetitive continuations;
    a miss returns an empty draft — the verify step then degenerates to
    one plain greedy step (never slower than no speculation by more
    than the batched verify's padding)."""
    L = int(ctx.size)
    if L <= n:
        return ctx[:0]
    tail = ctx[L - n:]
    # vectorized most-recent-match: one C-level comparison over all
    # windows ending before the tail itself — a Python-level backward
    # scan costs O(L) numpy calls per slot per round, which on a long
    # non-repetitive context can exceed the verify forward it feeds
    wins = np.lib.stride_tricks.sliding_window_view(ctx, n)[:L - n]
    hits = np.nonzero((wins == tail).all(axis=1))[0]
    if hits.size == 0:
        return ctx[:0]
    s = int(hits[-1])
    return ctx[s + n:s + n + k]


def _harvest_loop(fetchq: "queue.Queue", readyq: "queue.Queue") -> None:
    """Harvester thread: materializes chunks' packed arrays.
    ``np.asarray`` blocks for a full transport round-trip, so it lives
    here, off the dispatch path; attribution stays in the pump thread
    (scheduling state is single-threaded). JAX defers async dispatch
    errors to exactly this materialization point, so failures are
    shipped to the pump as ("error", ...) items — a dead harvester
    would otherwise deadlock the engine silently."""
    while True:
        item = fetchq.get()
        if item is None:
            return
        seq, packed, fills, snapshot, t0 = item
        try:
            readyq.put((seq, np.asarray(packed), fills, snapshot, t0))
        except Exception as e:  # noqa: BLE001 - crossing threads
            readyq.put((seq, e, fills, snapshot, t0))


@functools.partial(jax.jit, static_argnames=("model", "max_slots"))
def _init_cache(model, params, max_slots: int):
    """Allocate the big ragged cache: one throwaway single-token apply
    creates zero-filled cache variables for all slots (the garbage row
    each slot writes at position 0 is overwritten by its first
    prefill insert and masked until then)."""
    dummy = jnp.zeros((max_slots, 1), jnp.int32)
    _, mut = model.apply(
        {"params": params}, dummy,
        positions=jnp.zeros((max_slots, 1), jnp.int32),
        mutable=["cache"],
    )
    return mut["cache"]


class ContinuousBatchingEngine:
    """Slot-based continuous batching over a ragged-decode model.

    Parameters
    ----------
    model:
        ``LlamaForCausalLM`` with ``decode=True, ragged_decode=True``
        (``scan_layers=False`` recommended — the unrolled decode layout
        is the measured-fast one, docs/BENCHMARKS.md).
    params:
        Canonical (or serving-transformed) parameter tree.
    max_slots:
        Static decode batch width = max concurrent requests in flight.
    prompt_buckets:
        Static prefill lengths; a prefill chunk compiles at the
        smallest bucket that fits, so distinct prompt lengths cost at
        most ``len(prompt_buckets)`` chunk compilations, ever (only
        buckets <= ``prefill_chunk`` are used as chunk shapes).
    decode_chunk:
        Decode steps per host round-trip (and per scheduling
        opportunity — each pump round is one decode chunk plus at most
        a budget's worth of prefill). Default 32: measured on a tunnel
        transport, 16-32 amortizes the per-chunk RTT to under 10% of
        chunk compute while keeping admission/prefill-interleave
        latency at a few hundred ms; 64 squeezed out ~2% more
        throughput but doubled the scheduling quantum (TTFT and the
        inter-token spike a newly admitted prompt can cause), which
        the chunked-prefill scheduler exists to keep small. Raise it
        only when RTT, not latency, dominates.
    chunked_prefill:
        True (default): prompts prefill in bounded chunks under the
        per-round token budget — decode never waits behind more than
        ~``max_tokens_per_round`` padded prefill tokens, and prompts
        may be as long as ``max_seq_len - max_new_tokens``. False:
        legacy one-shot prefill (whole prompt, one bucket, admission
        blocks decode for the full prompt; prompts capped at the
        largest bucket) — kept for A/B measurement.
    prefill_chunk:
        Upper bound on a single prefill chunk's padded length; the
        effective chunk shapes are the prompt buckets <= this value.
    max_tokens_per_round:
        Per-pump-round token budget. Decode rows claim theirs first
        (active_rows * decode_chunk); the remainder goes to the oldest
        partially-prefilled prompt's next chunk(s). Default:
        ``prefill_chunk + max_slots * decode_chunk`` — under full
        decode load exactly one full chunk still fits per round.
        When nothing is decoding the budget floor is one full chunk,
        so prefill always makes progress.
    prefix_cache_tokens:
        Shared-prefix KV reuse (docs/SERVING.md "Fleet"): > 0 caches
        the working-cache KV of each distinct prompt prefix of this
        many tokens (rounded DOWN to the chunk-bucket grid), keyed by
        the exact token bytes. A later prompt sharing that prefix
        skips re-prefilling it: the snapshot seeds the working cache
        and chunking resumes at the prefix boundary — the repeated-
        system-prompt case a prefix-affinity router steers here.
        Tokens are bit-identical to the uncached path (the snapshot
        IS what prefilling those tokens produces). 0 = off; requires
        ``chunked_prefill``.
    prefix_cache_max:
        LRU capacity (distinct prefixes held on device). Each entry
        costs one stage-sized batch-1 KV cache.
    spec_decode_k:
        Self-speculative decode (docs/SERVING.md "Disaggregation"):
        > 0 replaces the K-step decode chunk with draft-k/verify
        rounds — an n-gram drafter proposes up to this many tokens
        per round and ONE ragged verify step accepts the matching
        prefix (+ the bonus correction), bit-identically to greedy.
        Requires temperature=0. The pump runs synchronously in this
        mode (one device round-trip per verify), which the multi-token
        rounds amortize; rows within k+1 of the cache end fall back
        to plain chunks.
    spec_ngram:
        Drafting n-gram length (the context suffix matched against
        earlier context). 2 is the prompt-lookup default.
    """

    def __init__(
        self,
        model: LlamaForCausalLM,
        params,
        *,
        max_slots: int = 8,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        decode_chunk: int = 32,
        prompt_buckets: Optional[Sequence[int]] = None,
        rng: Optional[jax.Array] = None,
        pipeline_depth: int = 2,
        chunked_prefill: bool = True,
        prefill_chunk: int = 256,
        max_tokens_per_round: Optional[int] = None,
        prefix_cache_tokens: int = 0,
        prefix_cache_max: int = 8,
        spec_decode_k: int = 0,
        spec_ngram: int = 2,
    ):
        cfg = model.config
        if not (cfg.decode and cfg.ragged_decode):
            raise ValueError(
                "engine needs LlamaConfig(decode=True, ragged_decode=True)"
            )
        self.model = model
        self.params = params
        self.max_slots = int(max_slots)
        self.max_seq = int(cfg.max_seq_len)
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.decode_chunk = int(decode_chunk)
        # chunks dispatched ahead of the oldest un-harvested one: the
        # packed fetch of chunk N then overlaps chunk N+1's execution,
        # hiding the transport round-trip entirely (1 = fetch blocks
        # the device; 2 is enough to cover one RTT)
        self.pipeline_depth = max(1, int(pipeline_depth))
        if prompt_buckets is None:
            prompt_buckets = [
                b for b in (128, 256, 512, 1024, 2048, 4096, 8192)
                if b < self.max_seq
            ]
        self.prompt_buckets = sorted(int(b) for b in prompt_buckets)
        if not self.prompt_buckets:
            raise ValueError("need at least one prompt bucket < max_seq_len")
        if self.prompt_buckets[-1] >= self.max_seq:
            # a bucket >= max_seq_len would accept prompts whose prefill
            # then fails at trace time with an opaque
            # dynamic_update_slice shape error — refuse loudly instead
            raise ValueError(
                f"prompt bucket {self.prompt_buckets[-1]} >= max_seq_len "
                f"{self.max_seq}: every bucket must leave room for at "
                "least one generated token"
            )
        self.chunked_prefill = bool(chunked_prefill)
        self._chunk_buckets = [b for b in self.prompt_buckets
                               if b <= int(prefill_chunk)]
        if (self.chunked_prefill and self._chunk_buckets
                and int(prefill_chunk) not in self._chunk_buckets
                and int(prefill_chunk) < self.max_seq):
            # the requested chunk size is itself a chunk shape when it
            # fits the grid — otherwise an explicit prefill_chunk=64
            # over buckets (…, 32, 512) would silently clamp to 32.
            # An off-grid request (100 over buckets starting at 8) is
            # refused loudly rather than silently clamped: the clamp
            # would change the dispatch count and the budget default
            # behind the operator's back. (prefill_chunk >= max_seq —
            # the cross-scale default — still clamps to the largest
            # bucket, which is the intended auto-sizing.)
            if int(prefill_chunk) % self._chunk_buckets[0]:
                raise ValueError(
                    f"prefill_chunk {prefill_chunk} is not a multiple "
                    f"of the smallest prompt bucket "
                    f"{self._chunk_buckets[0]}; pick a multiple (or a "
                    "value >= max_seq_len to use the largest bucket)"
                )
            self._chunk_buckets.append(int(prefill_chunk))
        if self.chunked_prefill:
            if not self._chunk_buckets:
                raise ValueError(
                    f"prefill_chunk {prefill_chunk} < smallest prompt "
                    f"bucket {self.prompt_buckets[0]}: no chunk shape "
                    "fits the budget"
                )
            g = self._chunk_buckets[0]
            bad = [b for b in self._chunk_buckets if b % g]
            if bad:
                # the chunk planner's liveness proof (an in-range
                # bucket always exists, DUS writes never clamp) needs
                # chunk offsets on the smallest-bucket grid, i.e.
                # every chunk bucket a multiple of the smallest
                raise ValueError(
                    f"chunked prefill needs every chunk bucket to be "
                    f"a multiple of the smallest bucket ({g}); "
                    f"offending buckets: {bad}"
                )
            # an off-grid max_seq_len is fine for the engine — only a
            # prompt whose final PADDED chunk would overhang max_seq
            # is inadmissible, enforced per-prompt in submit() (a hard
            # init raise here broke previously-valid configs like
            # max_seq_len=1000 with buckets starting at 16)
            self._chunk_plen_cap = (self.max_seq // g) * g
        self.prefill_chunk = self._chunk_buckets[-1] \
            if self._chunk_buckets else int(prefill_chunk)
        self.max_tokens_per_round = int(
            max_tokens_per_round
            if max_tokens_per_round is not None
            else self.prefill_chunk + self.max_slots * self.decode_chunk
        )
        if self.max_tokens_per_round < 1:
            raise ValueError("max_tokens_per_round must be >= 1")
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        # Chunked-prefill working state: batch-1 caches accumulate the
        # in-progress prompt's chunks, one per STAGE — power-of-two
        # multiples of prefill_chunk, capped at max_seq. A continuation
        # chunk attends against its whole working cache, so a flat
        # max_seq-long cache would cost O(chunk * max_seq) attention
        # per chunk for EVERY prompt (32x the useful work for a
        # 256-token prompt in an 8k cache — measured as a 20%
        # engine-throughput regression); staged caches keep it at
        # O(chunk * visible_prefix), summing to ~the one-shot flash
        # FLOPs. Stage caches and their model views (same params,
        # shorter max_seq_len) are allocated on first use and reused
        # across requests — stale rows are garbage-tolerant, see
        # _scatter_slot_rows. At most one prompt is mid-prefill at a
        # time, holding a reserved slot that activates on the final
        # chunk's scatter; crossing a stage boundary copies the
        # accumulated rows up (geometric, ~plen total rows copied).
        self._pcaches: Dict[int, object] = {}
        self._stage_models: Dict[int, LlamaForCausalLM] = {}
        self._pstage: Optional[int] = None
        self._prefilling: Optional[Request] = None
        self._prefill_slot: Optional[int] = None
        # Shared-prefix KV reuse: exact-token-keyed LRU of working-
        # cache snapshots at the prefix boundary. The boundary is
        # rounded DOWN to the chunk grid so a snapshot is always a
        # legal continuation offset; capture forces a chunk boundary
        # there (see _schedule_prefill). Disabled off the chunked path
        # (the legacy one-shot prefill has no working cache to reuse).
        self.prefix_cache_tokens = int(prefix_cache_tokens)
        self.prefix_cache_max = int(prefix_cache_max)
        self._prefix_len = 0
        if self.chunked_prefill and self.prefix_cache_tokens > 0:
            g0 = self._chunk_buckets[0]
            self._prefix_len = (self.prefix_cache_tokens // g0) * g0
        # key (prefix token bytes) -> (stage, snapshot cache tree)
        self._prefix_cache: "collections.OrderedDict" = \
            collections.OrderedDict()
        # Self-speculative decode (docs/SERVING.md "Disaggregation"):
        # > 0 turns the decode pump into draft-k/verify rounds — the
        # n-gram draft proposes K tokens, ONE ragged verify step
        # checks them all, and the accepted prefix (+ bonus token)
        # lands in one round instead of K. Greedy-only: acceptance is
        # bit-identical to plain decode, which sampling cannot be.
        self.spec_decode_k = int(spec_decode_k)
        self.spec_ngram = max(1, int(spec_ngram))
        if self.spec_decode_k > 0 and float(temperature) != 0.0:
            raise ValueError(
                "spec_decode_k requires temperature=0 (greedy): the "
                "accept-prefix rule is only bit-identical to the "
                "plain decode path under argmax")
        # host mirrors of the device scheduling vectors — authoritative
        # only in spec-decode mode, where every round is synchronous
        self._tok_h = np.zeros(self.max_slots, np.int32)
        self._len_h = np.zeros(self.max_slots, np.int32)
        self._budget_h = np.zeros(self.max_slots, np.int32)
        # slot -> first token of the admission that just filled it
        # (device scalar or host int); consumed by the spec-mode pump,
        # which attributes fills inline instead of via packed row 0
        self._fill_toks: Dict[int, object] = {}
        # key -> device bytes of that snapshot; summed into
        # stats["prefix_cache_bytes"] on every insert/evict so the LRU
        # is bytes-accounted, not just count-bounded — the number fleet
        # capacity planning needs (docs/SERVING.md "Fleet")
        self._prefix_bytes: Dict[bytes, int] = {}
        self._capture_key: Optional[bytes] = None

        # ALL decode state lives on device between chunks; the host
        # holds only a scheduling VIEW refreshed from each chunk's
        # packed fetch (self._active_h). Shipping the [B] vectors back
        # and forth per chunk cost a tunnel round-trip each.
        self._cache = _init_cache(model, params, self.max_slots)
        self._tok = jnp.zeros(self.max_slots, jnp.int32)
        self._lengths = jnp.zeros(self.max_slots, jnp.int32)
        self._active = jnp.zeros(self.max_slots, bool)
        self._budget = jnp.zeros(self.max_slots, jnp.int32)
        self._active_h = np.zeros(self.max_slots, bool)  # host view
        self._slot_req: List[Optional[Request]] = [None] * self.max_slots
        self._queue: collections.deque = collections.deque()
        # Request lifetime: submit() -> _reqs (in flight) -> on the
        # finishing chunk's attribution, moved to _done -> drained by
        # pop_finished()/run(). Nothing is retained after the drain, so
        # a long-lived server's memory is bounded by in-flight work.
        self._reqs: Dict[int, Request] = {}
        self._done: Dict[int, Request] = {}
        self._rid = itertools.count()
        self._closed = False
        # guards the cross-thread mutations: submit()'s closed-check +
        # enqueue vs close(), and the _done insert vs pop_finished()'s
        # swap (submit/pop_finished are documented thread-safe)
        self._lock = threading.Lock()
        # Dispatched chunks flow pump -> _fetchq -> harvester threads
        # (which own the ONLY blocking device→host transfers) ->
        # _readyq -> pump attribution, re-ordered by sequence number.
        # The transfer round-trip is ~120 ms on the tunnel transport —
        # more than a small chunk's compute — so fetches must neither
        # sit on the dispatch path NOR serialize with each other (one
        # harvester capped the whole engine at ~1 chunk per RTT).
        self._fetchq: "queue.Queue" = queue.Queue()
        self._readyq: "queue.Queue" = queue.Queue()
        self._unattributed = 0   # dispatched, not yet attributed
        self._seq = 0            # dispatch order
        self._attr_seq = 0       # next chunk to attribute
        self._ready_held: Dict[int, tuple] = {}  # out-of-order buffer
        # the thread target closes over the QUEUES, not self: a
        # bound-method target would pin the engine (and its device KV
        # cache) for the process lifetime if close() is never called
        self._harvesters = [
            threading.Thread(
                target=_harvest_loop,
                args=(self._fetchq, self._readyq),
                daemon=True, name=f"serving-harvester-{i}")
            for i in range(4)
        ]
        for t in self._harvesters:
            t.start()
        # operational counters (surfaced by the bench and by
        # GET /healthz): prefill_chunks/prefill_tokens count the
        # chunked scheduler's dispatches (padded tokens — what the
        # budget actually spends); queue_depth is a gauge refreshed
        # each pump round; ttft_s_sum/ttft_count accumulate
        # time-to-first-token at attribution (avg = sum/count)
        self.stats = {"prefills": 0, "chunks": 0, "decode_steps": 0,
                      "wasted_slot_steps": 0, "prefill_s": 0.0,
                      "chunk_s": 0.0, "prefill_chunks": 0,
                      "prefill_tokens": 0, "queue_depth": 0,
                      "ttft_s_sum": 0.0, "ttft_count": 0,
                      "prefix_hits": 0, "prefix_misses": 0,
                      "prefix_captures": 0, "prefix_tokens_saved": 0,
                      "prefix_cache_bytes": 0,
                      # disaggregation: prefill-only completions and
                      # KV-seeded slot admissions (docs/SERVING.md)
                      "kv_prefills": 0, "kv_admits": 0,
                      # self-speculative decode: rounds run, draft
                      # tokens proposed, draft tokens accepted, rounds
                      # that fell back to the plain chunk path
                      "spec_decode_rounds": 0, "spec_decode_drafted": 0,
                      "spec_decode_accepted": 0,
                      "spec_decode_fallbacks": 0,
                      # live migration (docs/SERVING.md "Live migration
                      # & prefix directory"): slots exported away /
                      # resumed here / mirrored non-destructively, plus
                      # prefix snapshots fetched from a holding peer
                      # instead of recomputed
                      "migrations_out": 0, "migrations_in": 0,
                      "slot_mirrors": 0, "prefix_remote_hits": 0,
                      "prefix_installs": 0}
        # export_slot() command queue: slot/device state is owned by
        # the pump thread, so front-end handler threads park an export
        # request here and the pump services it at the top of step()
        self._export_q: collections.deque = collections.deque()
        # guards _prefix_cache/_prefix_bytes structure: export_prefix/
        # install_prefix run on handler threads while the pump's
        # capture/hit path mutates the same OrderedDicts (the jax
        # arrays themselves are immutable — only the dicts need it)
        self._prefix_lock = threading.Lock()

    # -- request intake --------------------------------------------------

    def submit(self, prompt, max_new_tokens: int) -> int:
        prompt = self._validate_submit(prompt, max_new_tokens)
        req = Request(next(self._rid), prompt, int(max_new_tokens),
                      submitted_at=time.perf_counter())
        self._enqueue(req)
        return req.rid

    def submit_prefill(self, prompt, max_new_tokens: int) -> int:
        """Disaggregated serving, prefill half: run chunked prefill to
        completion and finish with the first token + a host-side KV
        snapshot (``Request.kv_result``) instead of occupying a decode
        slot. ``max_new_tokens`` is recorded for the handoff metadata
        only — the decode pool spends it."""
        if not self.chunked_prefill:
            raise ValueError(
                "submit_prefill needs chunked_prefill=True: the KV "
                "handoff unit is the chunked-prefill working cache")
        prompt = self._validate_submit(prompt, max_new_tokens)
        req = Request(next(self._rid), prompt, int(max_new_tokens),
                      submitted_at=time.perf_counter(),
                      prefill_only=True)
        self._enqueue(req)
        return req.rid

    def submit_with_kv(self, kv: dict, max_new_tokens: int) -> int:
        """Disaggregated serving, decode half: admit a request whose
        prefill already ran elsewhere. ``kv`` is the unpacked handoff:
        ``plen`` (real prompt tokens), ``rows`` (cache rows carried,
        a chunk-grid multiple >= plen), ``first_token`` (the prefill
        worker's greedy pick), ``leaves`` (host cache arrays in tree-
        flatten order) and optionally ``prompt`` (token ids, kept for
        bookkeeping). The snapshot scatters into a free slot exactly
        like a locally-prefilled working cache; decode then proceeds
        bit-identically to the interleaved path."""
        plen = int(kv["plen"])
        rows = int(kv["rows"])
        if plen < 1 or rows < plen:
            raise ValueError(f"kv seed: bad plen={plen} rows={rows}")
        if rows > self.max_seq:
            raise ValueError(
                f"kv seed: rows {rows} exceed cache size {self.max_seq}")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if plen + max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt {plen} + new {max_new_tokens} exceeds cache "
                f"size {self.max_seq}")
        cache_leaves: List = []
        jax.tree_util.tree_map_with_path(
            lambda p, x: cache_leaves.append((p, x)), self._cache)
        if len(kv["leaves"]) != len(cache_leaves):
            raise ValueError(
                f"kv seed: {len(kv['leaves'])} leaves != engine cache's "
                f"{len(cache_leaves)} (model configs must match across "
                "pools)")
        # validate SHAPES and DTYPES here, on the intake thread — a
        # mismatch surfacing later inside _admit_kv's jitted scatter
        # would raise on the PUMP thread and take the whole replica
        # down with it, instead of 400-ing one request
        for i, ((path, big), leaf) in enumerate(
                zip(cache_leaves, kv["leaves"])):
            name = path[-1].key if hasattr(path[-1], "key") \
                else str(path[-1])
            axis = big.ndim - 2 if name in ("cached_key", "cached_value") \
                else big.ndim - 1
            want = list(big.shape)
            want[big.ndim - 4] = 1      # batch-1 working cache
            want[axis] = rows
            got = np.asarray(leaf)
            if list(got.shape) != want or got.dtype != big.dtype:
                raise ValueError(
                    f"kv seed: leaf {i} ({name}) is "
                    f"{got.dtype}{list(got.shape)}, engine expects "
                    f"{big.dtype}{want} (model configs must match "
                    "across pools)")
        if str(kv.get("kind") or "") == "migration":
            toks = [int(t) for t in (kv.get("tokens") or [])]
            if not toks or toks[-1] != int(kv["first_token"]):
                raise ValueError(
                    "migration seed: tokens[] must end with first_token "
                    "(the un-fed boundary token the resumed decode "
                    "feeds next)")
            if self.eos_id is not None and toks[-1] == int(self.eos_id):
                raise ValueError(
                    "migration seed: boundary token is EOS — the "
                    "source stream had already finished")
            if float(self.temperature) != 0.0:
                raise ValueError(
                    "migration resume requires temperature=0 (greedy): "
                    "the resumed stream must be bit-identical to the "
                    "unmigrated one, which sampling cannot be")
        prompt = np.asarray(
            kv.get("prompt") if kv.get("prompt") is not None
            else np.zeros(plen, np.int32), np.int32).reshape(-1)
        req = Request(next(self._rid), prompt, int(max_new_tokens),
                      submitted_at=time.perf_counter(), kv_seed=kv)
        if str(kv.get("kind") or "") == "migration":
            # resume mid-stream: everything the source already streamed
            # pre-seeds the request, so ONE request object yields the
            # full token list and the boundary token is never
            # double-delivered (_admit_kv skips the fill registration)
            req.tokens = [int(t) for t in kv["tokens"]]
        self._enqueue(req)
        return req.rid

    def _enqueue(self, req: Request) -> None:
        # the closed check and the enqueue must be one atomic unit vs a
        # concurrent close() (submit is documented callable from an
        # arrival thread): after close() the harvesters are gone, so a
        # request slipping past an unsynchronized check would enqueue
        # onto a dead engine and its caller would wait forever
        with self._lock:
            if self._closed:
                raise RuntimeError("engine is closed")
            self._reqs[req.rid] = req
            self._queue.append(req)

    def _validate_submit(self, prompt, max_new_tokens: int) -> np.ndarray:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if not self.chunked_prefill and prompt.size > self.prompt_buckets[-1]:
            # the legacy one-shot prefill runs the whole prompt as one
            # bucketed forward; chunked prefill has no such cap — any
            # prompt that leaves room for max_new_tokens is admissible
            raise ValueError(
                f"prompt len {prompt.size} exceeds the largest bucket "
                f"{self.prompt_buckets[-1]}"
            )
        if self.chunked_prefill and prompt.size > self._chunk_plen_cap:
            # only reachable when max_seq_len is off the smallest-
            # bucket grid: the final padded chunk of a longer prompt
            # would overhang max_seq (a clamped DUS write corrupts
            # neighbor rows, so refuse loudly instead)
            raise ValueError(
                f"prompt len {prompt.size} exceeds the chunkable cap "
                f"{self._chunk_plen_cap} (max_seq_len {self.max_seq} "
                f"is not a multiple of the smallest chunk bucket "
                f"{self._chunk_buckets[0]})"
            )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt {prompt.size} + new {max_new_tokens} exceeds "
                f"cache size {self.max_seq}"
            )
        return prompt

    def queue_depth(self) -> int:
        """LIVE admission-queue depth (requests accepted but not yet
        scheduled) — unlike ``stats["queue_depth"]``, which is a gauge
        refreshed once per pump round, this reads the queue itself, so
        a front-end backpressure check between rounds sees a burst of
        arrivals immediately. Callable from any thread."""
        return len(self._queue)

    # -- scheduling ------------------------------------------------------

    def _bucket_for(self, plen: int) -> int:
        for b in self.prompt_buckets:
            if plen <= b:
                return b
        raise AssertionError  # guarded in submit()

    def _next_rng(self) -> jax.Array:
        self._rng, r = jax.random.split(self._rng)
        return r

    def _fill_free_slots(self) -> Dict[int, int]:
        """Dispatch a prefill+insert for every (free slot, queued
        request) pair — fully async, nothing fetched. Returns
        {slot: rid} of the fills; their first tokens surface in the
        NEXT dispatched chunk's packed row 0."""
        fills: Dict[int, int] = {}
        for slot in range(self.max_slots):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            req = self._queue.popleft()
            if req.kv_seed is not None:
                # KV-seeded admission works on the legacy path too —
                # the scatter/activate machinery is path-independent
                self._admit_kv(req, slot, fills)
                continue
            req.prefill_start_at = time.perf_counter()
            plen = int(req.prompt.size)
            plen_b = self._bucket_for(plen)
            padded = np.zeros((1, plen_b), np.int32)
            padded[0, :plen] = req.prompt
            t0 = time.perf_counter()
            self._cache, tok_new = _prefill_insert(
                self.model, self.params, self._cache,
                jnp.int32(slot), jnp.asarray(padded), jnp.int32(plen),
                self._next_rng(), plen_b=plen_b,
                temperature=self.temperature,
            )
            (self._tok, self._lengths, self._active,
             self._budget) = _set_slot(
                self._tok, self._lengths, self._active, self._budget,
                jnp.int32(slot), tok_new, jnp.int32(plen),
                jnp.int32(req.max_new_tokens), eos_id=self.eos_id,
            )
            self.stats["prefills"] += 1
            self.stats["prefill_s"] += time.perf_counter() - t0
            req.prefill_done = plen
            self._slot_req[slot] = req
            self._active_h[slot] = True  # optimistic; fixed at harvest
            fills[slot] = req.rid
            if self.spec_decode_k > 0:
                self._fill_toks[slot] = tok_new
        return fills

    def _free_slot(self) -> Optional[int]:
        for slot in range(self.max_slots):
            if self._slot_req[slot] is None and slot != self._prefill_slot:
                return slot
        return None

    def _stage_for(self, rows: int) -> int:
        L = self.prefill_chunk
        while L < rows:
            L *= 2
        return min(L, self.max_seq)

    def _stage_cache(self, stage: int):
        """Working cache + model view for ``stage``, allocated lazily.
        The model view is the decode model with max_seq_len=stage —
        same params tree, so apply() just sizes the cache variables
        (and the continuation chunk's attention) to the stage."""
        model = self._stage_models.get(stage)
        if model is None:
            model = LlamaForCausalLM(dataclasses.replace(
                self.model.config, max_seq_len=stage))
            self._stage_models[stage] = model
        if stage not in self._pcaches:
            self._pcaches[stage] = _init_cache(model, self.params, 1)
        return model, self._pcaches[stage]

    def _snapshot_kv(self, pcache, rows: int) -> List[np.ndarray]:
        """Host-side copy of the working cache's first ``rows`` rows
        per leaf, in tree-flatten order — the KV handoff payload.
        ``np.array(copy=True)``: on CPU backends ``np.asarray`` is a
        ZERO-COPY view of the device buffer, which the next prompt's
        donated chunk would scribble over (the PR 9 checkpoint-save
        lesson, same bug class)."""

        def one(path, leaf):
            name = path[-1].key if hasattr(path[-1], "key") \
                else str(path[-1])
            if name in ("cached_key", "cached_value"):
                axis = leaf.ndim - 2
            elif name in ("key_scale", "value_scale"):
                axis = leaf.ndim - 1
            else:
                raise ValueError(f"unknown cache leaf {name!r}")
            return np.array(
                jax.lax.slice_in_dim(leaf, 0, rows, axis=axis),
                copy=True)

        return jax.tree_util.tree_leaves(
            jax.tree_util.tree_map_with_path(one, pcache))

    def _snapshot_slot_kv(self, slot: int, rows: int) -> List[np.ndarray]:
        """Host-side copy of ONE decode slot's first ``rows`` cache
        rows per leaf, shaped as a batch-1 working cache — exactly the
        intake shape :meth:`submit_with_kv` validates, so an exported
        slot re-admits on any peer with the same model config. Same
        copy semantics as :meth:`_snapshot_kv` (``np.asarray`` on CPU
        is a zero-copy view the next donated chunk scribbles over)."""

        def one(path, leaf):
            name = path[-1].key if hasattr(path[-1], "key") \
                else str(path[-1])
            if name in ("cached_key", "cached_value"):
                axis = leaf.ndim - 2
            elif name in ("key_scale", "value_scale"):
                axis = leaf.ndim - 1
            else:
                raise ValueError(f"unknown cache leaf {name!r}")
            x = jax.lax.slice_in_dim(
                leaf, slot, slot + 1, axis=leaf.ndim - 4)
            x = jax.lax.slice_in_dim(x, 0, rows, axis=axis)
            return np.array(x, copy=True)

        return jax.tree_util.tree_leaves(
            jax.tree_util.tree_map_with_path(one, self._cache))

    # -- live migration (docs/SERVING.md "Live migration") ---------------

    def export_slot(self, request_id: int, *, remove: bool = True,
                    timeout: float = 30.0) -> Optional[dict]:
        """Thread-safe export of a mid-stream request's full resumable
        state (a ``kind="migration"`` handoff dict admissible via
        :meth:`submit_with_kv` on a peer). Slot/device state is owned
        by the pump thread, so this parks a command the pump services
        at the top of its next :meth:`step` and waits for the result.
        Returns ``None`` when the request is not exportable (queued,
        mid-prefill, finished, token-less, or on timeout). With
        ``remove=False`` the request keeps decoding locally and the
        export is a consistent point-in-time MIRROR."""
        done = threading.Event()
        box: List[Optional[dict]] = [None]
        with self._lock:
            if self._closed:
                raise RuntimeError("engine is closed")
            self._export_q.append((int(request_id), bool(remove),
                                   done, box))
        if not done.wait(timeout):
            return None
        return box[0]

    def _service_exports(self) -> None:
        while True:
            try:
                rid, remove, done, box = self._export_q.popleft()
            except IndexError:
                return
            try:
                box[0] = self.export_slot_now(rid, remove=remove)
            finally:
                done.set()

    def export_slot_now(self, request_id: int,
                        remove: bool = True) -> Optional[dict]:
        """Pump-thread half of :meth:`export_slot` — callers driving
        :meth:`step` directly (tests, single-threaded harnesses) may
        call it between rounds. Quiesces in-flight chunks first so the
        host token list and the device vectors describe the same point
        in the stream, then packs: slot KV rows (chunk-grid rounded),
        prompt + every token streamed so far, the un-fed boundary
        token, and the remaining budget. Resume math: after ``g``
        emitted tokens the slot sits at ``lengths = plen0 + g - 1``
        with rows ``[0, lengths)`` written and ``tokens[-1]`` not yet
        fed — identical to a fresh KV handoff of a ``lengths``-token
        prompt whose prefill just picked ``tokens[-1]``, which is why
        the peer-side admission is bit-identical under greedy."""
        if float(self.temperature) != 0.0:
            raise ValueError(
                "live migration requires temperature=0 (greedy): the "
                "resumed decode must be bit-identical across hosts")
        while self._unattributed:
            self._attribute(block=True)
        slot, req = None, None
        for i, r in enumerate(self._slot_req):
            if r is not None and r.rid == request_id:
                slot, req = i, r
                break
        if slot is None or req.done or not req.tokens:
            return None
        plen0 = int(req.prompt.size)
        g = len(req.tokens)
        lengths = plen0 + g - 1
        budget = int(req.max_new_tokens) - g
        if budget <= 0:
            return None  # finishing this round anyway — nothing to move
        rows_b = min(self.max_seq,
                     -(-lengths // self.prefill_chunk)
                     * self.prefill_chunk)
        kv = {
            "kind": "migration",
            "plen": int(lengths),
            "rows": int(rows_b),
            "first_token": int(req.tokens[-1]),
            "prompt": [int(t) for t in req.prompt],
            "tokens": [int(t) for t in req.tokens],
            "max_new_tokens": int(req.max_new_tokens),
            "budget": int(budget),
            "leaves": self._snapshot_slot_kv(slot, rows_b),
        }
        if remove:
            # freeze the slot out of the schedule: budget0=0 deactivates
            # on device, and the request leaves _reqs WITHOUT entering
            # _done — the migration orchestrator resolves its waiter
            (self._tok, self._lengths, self._active,
             self._budget) = _set_slot(
                self._tok, self._lengths, self._active, self._budget,
                jnp.int32(slot), jnp.int32(0), jnp.int32(0),
                jnp.int32(1), eos_id=self.eos_id)
            self._slot_req[slot] = None
            self._active_h[slot] = False
            self._tok_h[slot] = 0
            self._len_h[slot] = 0
            self._budget_h[slot] = 0
            self._fill_toks.pop(slot, None)
            with self._lock:
                self._reqs.pop(req.rid, None)
            self.stats["migrations_out"] += 1
        else:
            self.stats["slot_mirrors"] += 1
        return kv

    # -- fleet-wide prefix directory (docs/SERVING.md) -------------------

    def prefix_digest(self, prompt) -> Optional[str]:
        """sha256 hex of the prompt's prefix-cache key, or ``None``
        when the prefix cache is off / the prompt is too short to have
        one. The digest is the fleet-wide directory key: replicas
        advertise their held digests on /healthz and the router points
        a missing prefill worker at a holding peer."""
        L = self._prefix_len
        p = np.asarray(prompt, np.int32).reshape(-1)
        if not L or p.size <= L:
            return None
        import hashlib

        return hashlib.sha256(p[:L].tobytes()).hexdigest()

    def prefix_keys(self) -> List[str]:
        """Digests of every locally-held prefix snapshot."""
        import hashlib

        with self._prefix_lock:
            keys = list(self._prefix_cache.keys())
        return [hashlib.sha256(k).hexdigest() for k in keys]

    def has_prefix(self, digest: str) -> bool:
        import hashlib

        with self._prefix_lock:
            return any(hashlib.sha256(k).hexdigest() == digest
                       for k in self._prefix_cache)

    def export_prefix(self, digest: str):
        """``(meta, host leaves)`` of the held prefix snapshot whose
        key hashes to ``digest``, or ``None``. ``meta["tokens"]`` is
        the raw prefix so the importer re-derives its own key — the
        digest never needs to be trusted."""
        import hashlib

        with self._prefix_lock:
            entry = None
            for k, (stage, snap) in self._prefix_cache.items():
                if hashlib.sha256(k).hexdigest() == digest:
                    entry = (k, stage, snap)
                    break
        if entry is None:
            return None
        key, stage, snap = entry
        meta = {"kind": "prefix", "stage": int(stage),
                "tokens": [int(t) for t in np.frombuffer(key, np.int32)]}
        leaves = [np.array(x, copy=True)
                  for x in jax.tree_util.tree_leaves(snap)]
        return meta, leaves

    def install_prefix(self, meta: dict, leaves) -> None:
        """Admit a peer-exported prefix snapshot into the local LRU —
        the fetch half of the directory. Validates config compatibility
        (prefix length, stage, leaf shapes/dtypes) on the caller's
        thread; a mismatch must 400 one fetch, not crash the pump."""
        tokens = [int(t) for t in (meta.get("tokens") or [])]
        if len(tokens) != self._prefix_len:
            raise ValueError(
                f"prefix import: {len(tokens)} tokens != this engine's "
                f"prefix length {self._prefix_len} (configs must match "
                "across the fleet)")
        stage = int(meta["stage"])
        if stage < self._prefix_len or stage > self.max_seq:
            raise ValueError(f"prefix import: bad stage {stage}")
        _, pcache = self._stage_cache(stage)
        want = jax.tree_util.tree_leaves(pcache)
        if len(leaves) != len(want):
            raise ValueError(
                f"prefix import: {len(leaves)} leaves != stage cache's "
                f"{len(want)}")
        for i, (w, leaf) in enumerate(zip(want, leaves)):
            got = np.asarray(leaf)
            if tuple(got.shape) != tuple(w.shape) or got.dtype != w.dtype:
                raise ValueError(
                    f"prefix import: leaf {i} is "
                    f"{got.dtype}{list(got.shape)}, stage {stage} "
                    f"expects {w.dtype}{list(w.shape)}")
        treedef = jax.tree_util.tree_structure(pcache)
        snap = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(x) for x in leaves])
        key = np.asarray(tokens, np.int32).tobytes()
        with self._prefix_lock:
            self._prefix_cache[key] = (stage, snap)
            self._prefix_cache.move_to_end(key)
            self._prefix_bytes[key] = sum(
                int(getattr(x, "nbytes", 0) or 0)
                for x in jax.tree_util.tree_leaves(snap))
            while len(self._prefix_cache) > self.prefix_cache_max:
                evicted, _ = self._prefix_cache.popitem(last=False)
                self._prefix_bytes.pop(evicted, None)
            self.stats["prefix_cache_bytes"] = sum(
                self._prefix_bytes.values())
        self.stats["prefix_installs"] += 1

    def _admit_kv(self, req: Request, slot: int,
                  fills: Dict[int, int]) -> None:
        """Scatter a received KV snapshot into ``slot`` and activate it
        — the decode-side half of the handoff. No prefill compute and
        no token budget spent: the scatter is one DUS write, the same
        touch the local final-chunk path pays."""
        kv = req.kv_seed
        req.prefill_start_at = time.perf_counter()
        treedef = jax.tree_util.tree_structure(self._cache)
        ptree = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(x) for x in kv["leaves"]])
        rows = int(kv["rows"])
        self._cache = _scatter_slot_rows(
            self._cache, ptree, jnp.int32(slot), rows_b=rows)
        first = int(kv["first_token"])
        (self._tok, self._lengths, self._active,
         self._budget) = _set_slot(
            self._tok, self._lengths, self._active, self._budget,
            jnp.int32(slot), jnp.int32(first), jnp.int32(kv["plen"]),
            jnp.int32(req.max_new_tokens), eos_id=self.eos_id,
        )
        req.prefill_done = int(kv["plen"])
        self.stats["kv_admits"] += 1
        self._slot_req[slot] = req
        self._active_h[slot] = True  # optimistic; fixed at harvest
        if str(kv.get("kind") or "") == "migration":
            # resumed mid-stream request: tokens[] already carries the
            # streamed prefix and the boundary token rides the slot's
            # tok register. Registering the slot as a FILL would
            # re-append that token (a duplicate in the stream), so
            # attribution starts at the first NEW token instead.
            self.stats["migrations_in"] += 1
            if self.spec_decode_k > 0:
                # spec mode plans rounds from the host mirrors, which
                # normally seed via the fill path we just skipped
                self._tok_h[slot] = first
                self._len_h[slot] = int(kv["plen"])
                self._budget_h[slot] = req.max_new_tokens - 1
        else:
            fills[slot] = req.rid
            if self.spec_decode_k > 0:
                self._fill_toks[slot] = first

    def _admit_prefix(self, req: Request) -> None:
        """Prefix-cache lookup at admission of the next prompt to
        prefill. On a HIT the snapshot seeds the working cache and the
        prompt's first ``_prefix_len`` tokens are marked done — the
        continuation path then appends from the boundary exactly as if
        those chunks had just run. On a MISS (prompt long enough to
        capture) the scheduler arms a capture at the boundary."""
        self._capture_key = None
        L = self._prefix_len
        if not L or int(req.prompt.size) <= L:
            return
        key = req.prompt[:L].tobytes()
        with self._prefix_lock:
            hit = self._prefix_cache.get(key)
            if hit is not None:
                self._prefix_cache.move_to_end(key)
        if hit is not None:
            stage, snap = hit
            self._stage_cache(stage)  # materialize the model view
            # a COPY seeds the live working cache: subsequent chunks
            # donate it, and the snapshot must survive for the next hit
            self._pcaches[stage] = jax.tree_util.tree_map(jnp.copy, snap)
            self._pstage = stage
            req.prefill_done = L
            self.stats["prefix_hits"] += 1
            self.stats["prefix_tokens_saved"] += L
        else:
            self.stats["prefix_misses"] += 1
            self._capture_key = key

    def _schedule_prefill(self) -> Dict[int, int]:
        """Token-budget scheduler (chunked_prefill=True): spend this
        round's remaining budget — after decode rows claim
        ``active * decode_chunk`` — on prefill chunks for the oldest
        admitted prompt, admitting the next queued prompt into a free
        slot whenever the current one finishes and budget remains.
        Returns {slot: rid} for slots ACTIVATED this round (their
        first token rides the next chunk's packed row 0, exactly like
        the legacy fill path)."""
        fills: Dict[int, int] = {}
        n_active = int(self._active_h.sum())
        remaining = self.max_tokens_per_round - n_active * self.decode_chunk
        if n_active == 0:
            # budget floor: with no decode in flight there is no
            # latency to protect — always allow at least one full chunk
            remaining = max(remaining, self.prefill_chunk)
        g = self._chunk_buckets[0]
        while remaining >= g:
            if self._prefilling is None:
                if not self._queue:
                    break
                head = self._queue[0]
                if head.kv_seed is not None:
                    # KV-seeded admission: no prefill compute, no
                    # budget spent — just a slot and one DUS scatter
                    slot = self._free_slot()
                    if slot is None:
                        break
                    self._queue.popleft()
                    self._admit_kv(head, slot, fills)
                    continue
                if head.prefill_only:
                    # prefill-only requests never hold a decode slot:
                    # their product is the working-cache snapshot, not
                    # a decode stream
                    slot = None
                else:
                    slot = self._free_slot()
                    if slot is None:
                        break
                self._prefilling = self._queue.popleft()
                self._prefilling.prefill_start_at = time.perf_counter()
                self._prefill_slot = slot
                self._admit_prefix(self._prefilling)
            req, slot = self._prefilling, self._prefill_slot
            plan = _next_chunk(self._chunk_buckets, req.prefill_done,
                               int(req.prompt.size), remaining,
                               self.max_seq)
            if (plan is not None and self._capture_key is not None
                    and req.prefill_done < self._prefix_len
                    and req.prefill_done + plan[0] > self._prefix_len):
                # force a chunk boundary at the prefix capture point so
                # the snapshot covers EXACTLY the shared tokens (an
                # overshooting bucket would bake request-specific rows
                # into the cached prefix). The boundary is on the chunk
                # grid, so a full in-budget bucket always exists once
                # remaining >= g.
                fit = [b for b in self._chunk_buckets
                       if req.prefill_done + b <= self._prefix_len
                       and b <= remaining]
                plan = (max(fit), max(fit), False) if fit else None
            if plan is None:
                break
            chunk_b, take, final = plan
            offset = req.prefill_done
            padded = np.zeros((1, chunk_b), np.int32)
            padded[0, :take] = req.prompt[offset:offset + take]
            if final and offset == 0 and not req.prefill_only:
                # single-chunk prompt (the common case): the legacy
                # one-shot insert is strictly better — fresh cache
                # rides the flash kernel instead of the warm-cache
                # fallback's O(chunk * stage) f32 scores, and the K/V
                # rows scatter straight into the slot with no
                # working-cache hop
                t0 = time.perf_counter()
                self._cache, tok_new = _prefill_insert(
                    self.model, self.params, self._cache,
                    jnp.int32(slot), jnp.asarray(padded),
                    jnp.int32(take), self._next_rng(),
                    plen_b=chunk_b, temperature=self.temperature,
                )
                (self._tok, self._lengths, self._active,
                 self._budget) = _set_slot(
                    self._tok, self._lengths, self._active,
                    self._budget, jnp.int32(slot), tok_new,
                    jnp.int32(take), jnp.int32(req.max_new_tokens),
                    eos_id=self.eos_id,
                )
                req.prefill_done = take
                remaining -= chunk_b
                self.stats["prefills"] += 1
                self.stats["prefill_chunks"] += 1
                self.stats["prefill_tokens"] += chunk_b
                self.stats["prefill_s"] += time.perf_counter() - t0
                self._slot_req[slot] = req
                self._active_h[slot] = True  # optimistic
                fills[slot] = req.rid
                if self.spec_decode_k > 0:
                    self._fill_toks[slot] = tok_new
                self._prefilling = None
                self._prefill_slot = None
                self._pstage = None
                continue
            stage = self._stage_for(offset + chunk_b)
            smodel, pcache = self._stage_cache(stage)
            if offset and self._pstage is not None \
                    and stage != self._pstage:
                # stage crossing: carry the accumulated rows up into
                # the bigger working cache (whole-source copy — the
                # static row count keeps this one jit per stage pair;
                # rows above the real offset are garbage-tolerant)
                pcache = _scatter_slot_rows(
                    pcache, self._pcaches[self._pstage], jnp.int32(0),
                    rows_b=self._pstage,
                )
            self._pstage = stage
            t0 = time.perf_counter()
            pcache, tok_new = _prefill_chunk(
                smodel, self.params, pcache,
                jnp.asarray(padded), jnp.int32(offset),
                jnp.int32(take - 1), self._next_rng(),
                chunk_b=chunk_b, temperature=self.temperature,
                final=final,
            )
            self._pcaches[stage] = pcache
            req.prefill_done += take
            remaining -= chunk_b
            self.stats["prefill_chunks"] += 1
            self.stats["prefill_tokens"] += chunk_b
            self.stats["prefill_s"] += time.perf_counter() - t0
            if (self._capture_key is not None
                    and req.prefill_done == self._prefix_len):
                # the working cache now holds exactly the shared
                # prefix: snapshot it (a copy — the live cache is
                # donated by the next chunk) into the LRU
                snap = jax.tree_util.tree_map(jnp.copy, pcache)
                with self._prefix_lock:
                    self._prefix_cache[self._capture_key] = (stage, snap)
                    self._prefix_cache.move_to_end(self._capture_key)
                    self._prefix_bytes[self._capture_key] = sum(
                        int(getattr(x, "nbytes", 0) or 0)
                        for x in jax.tree_util.tree_leaves(snap))
                    while len(self._prefix_cache) > self.prefix_cache_max:
                        evicted, _ = self._prefix_cache.popitem(last=False)
                        self._prefix_bytes.pop(evicted, None)
                    self.stats["prefix_cache_bytes"] = sum(
                        self._prefix_bytes.values())
                self.stats["prefix_captures"] += 1
                self._capture_key = None
            if final:
                # round the scatter to a chunk multiple: jit keys stay
                # bounded, and the extra stale rows sit above the
                # prompt where they are never visible (see
                # _scatter_slot_rows)
                rows = offset + chunk_b
                rows_b = min(stage,
                             -(-rows // self.prefill_chunk)
                             * self.prefill_chunk)
                if req.prefill_only:
                    # disaggregation: the finished working cache IS the
                    # product — snapshot it to host (the wire payload)
                    # with the first token, and complete the request
                    # without ever touching a decode slot
                    first = int(tok_new)  # host sync; one per prompt
                    req.kv_result = {
                        "plen": int(req.prompt.size),
                        "rows": rows_b,
                        "first_token": first,
                        "prompt": [int(t) for t in req.prompt],
                        "leaves": self._snapshot_kv(pcache, rows_b),
                    }
                    req.tokens.append(first)
                    now = time.perf_counter()
                    req.first_token_at = now
                    req.finished_at = now
                    req.token_times.append((now, 1))
                    self.stats["ttft_s_sum"] += now - req.submitted_at
                    self.stats["ttft_count"] += 1
                    self.stats["prefills"] += 1
                    self.stats["kv_prefills"] += 1
                    req.done = True
                    with self._lock:
                        self._done[req.rid] = self._reqs.pop(
                            req.rid, req)
                    self._prefilling = None
                    self._prefill_slot = None
                    self._pstage = None
                    continue
                self._cache = _scatter_slot_rows(
                    self._cache, pcache, jnp.int32(slot),
                    rows_b=rows_b,
                )
                (self._tok, self._lengths, self._active,
                 self._budget) = _set_slot(
                    self._tok, self._lengths, self._active, self._budget,
                    jnp.int32(slot), tok_new,
                    jnp.int32(req.prompt.size),
                    jnp.int32(req.max_new_tokens), eos_id=self.eos_id,
                )
                self.stats["prefills"] += 1
                self._slot_req[slot] = req
                self._active_h[slot] = True  # optimistic; fixed at harvest
                fills[slot] = req.rid
                if self.spec_decode_k > 0:
                    self._fill_toks[slot] = tok_new
                self._prefilling = None
                self._prefill_slot = None
                self._pstage = None
        return fills

    def prefill_progress(self) -> Dict[int, Dict[str, int]]:
        """Per-request prefill progress for the in-flight partial
        prompt: {rid: {"done": real tokens prefilled, "total": prompt
        length}} — empty when no prompt is mid-prefill. Surfaced by
        GET /healthz for scheduler observability."""
        req = self._prefilling
        if req is None:
            return {}
        return {req.rid: {"done": int(req.prefill_done),
                          "total": int(req.prompt.size)}}

    # -- the pump --------------------------------------------------------

    def _dispatch_chunk(self, fills: Dict[int, int]) -> None:
        (self._cache, self._tok, self._lengths, self._active,
         self._budget, self._rng, packed) = _decode_chunk(
            self.model, self.params, self._cache, self._tok,
            self._lengths, self._active, self._budget, self._rng,
            n_steps=self.decode_chunk, temperature=self.temperature,
            eos_id=self.eos_id,
        )
        snapshot = [r.rid if r is not None else None
                    for r in self._slot_req]
        self._fetchq.put(
            (self._seq, packed, fills, snapshot, time.perf_counter()))
        self._seq += 1
        self._unattributed += 1
        self.stats["chunks"] += 1
        self.stats["decode_steps"] += self.decode_chunk

    def _next_ready(self, block: bool):
        """Chunk results in DISPATCH order: parallel harvesters finish
        out of order; attribution must not (token order per slot)."""
        while self._attr_seq not in self._ready_held:
            try:
                item = self._readyq.get(block=block)
            except queue.Empty:
                return None
            self._ready_held[item[0]] = item[1:]
        out = self._ready_held.pop(self._attr_seq)
        self._attr_seq += 1
        return out

    def _attribute(self, block: bool) -> bool:
        """Apply one harvested chunk's results via the dispatch-time
        slot→request snapshot — a slot may have been refilled since, so
        current `_slot_req` must not be trusted for old chunks."""
        item = self._next_ready(block)
        if item is None:
            return False
        arr, fills, snapshot, t0 = item
        self._unattributed -= 1
        if isinstance(arr, Exception):
            raise RuntimeError(
                f"decode chunk {self._attr_seq - 1} failed on device"
            ) from arr
        K = self.decode_chunk
        tok_in, toks = arr[0], arr[1:K + 1]
        valid = arr[K + 1:2 * K + 1].astype(bool)
        active_out = arr[2 * K + 1].astype(bool)
        if self.spec_decode_k > 0:
            # spec mode runs synchronously (one chunk ever in flight),
            # so this packed view IS the current device state — refresh
            # the host mirrors the next verify round plans from
            self._tok_h[:] = arr[K]
            self._budget_h[:] = arr[2 * K + 2]
            self._len_h[:] = arr[2 * K + 3]
        self.stats["chunk_s"] += time.perf_counter() - t0
        self.stats["wasted_slot_steps"] += int((~valid).sum())
        now = time.perf_counter()
        for slot, rid in enumerate(snapshot):
            if rid is None:
                continue
            # finished requests leave _reqs at attribution (and may be
            # drained entirely); stale snapshot entries for them skip
            req = self._reqs.get(rid)
            if req is None or req.done:
                continue
            n_before = len(req.tokens)
            if fills.get(slot) == rid:
                # the prefill's token rode in as this chunk's input
                req.tokens.append(int(tok_in[slot]))
            req.tokens.extend(int(t) for t in toks[valid[:, slot], slot])
            n_new = len(req.tokens) - n_before
            if n_new:
                if not req.token_times:
                    req.first_token_at = now
                    self.stats["ttft_s_sum"] += now - req.submitted_at
                    self.stats["ttft_count"] += 1
                req.token_times.append((now, n_new))
            if not active_out[slot]:
                req.done = True
                req.finished_at = time.perf_counter()
                # the insert must be atomic vs pop_finished()'s swap
                # (front-end threads poll it): an unsynchronized write
                # could land in a just-orphaned dict and be lost forever
                with self._lock:
                    self._done[rid] = self._reqs.pop(rid)
                if self._slot_req[slot] is req:
                    self._slot_req[slot] = None
                    self._active_h[slot] = False
        return True

    # -- self-speculative decode (docs/SERVING.md "Disaggregation") ------

    def _push_state(self) -> None:
        """Host mirrors → device vectors. In spec mode the mirrors are
        authoritative between rounds; the device copies only exist for
        the jits (verify, plain fallback chunk, slot admission)."""
        self._tok = jnp.asarray(self._tok_h)
        self._lengths = jnp.asarray(self._len_h)
        self._active = jnp.asarray(self._active_h)
        self._budget = jnp.asarray(self._budget_h)

    def _finish_slot(self, slot: int, req: Request) -> None:
        req.done = True
        req.finished_at = time.perf_counter()
        with self._lock:
            self._done[req.rid] = self._reqs.pop(req.rid, req)
        self._slot_req[slot] = None
        self._active_h[slot] = False

    def _spec_step(self) -> bool:
        """Spec-mode pump round: admissions attribute inline (the host
        mirrors need every first token anyway), then one verify round
        advances every active slot by 1 + accepted-draft tokens."""
        fills = (self._schedule_prefill() if self.chunked_prefill
                 else self._fill_free_slots())
        self.stats["queue_depth"] = len(self._queue)
        now = time.perf_counter()
        for slot, rid in fills.items():
            req = self._reqs.get(rid)
            tok = int(self._fill_toks.pop(slot))
            if req is None or req.done:
                continue
            req.tokens.append(tok)
            req.first_token_at = now
            req.token_times.append((now, 1))
            self.stats["ttft_s_sum"] += now - req.submitted_at
            self.stats["ttft_count"] += 1
            self._tok_h[slot] = tok
            self._len_h[slot] = req.prefill_done
            self._budget_h[slot] = req.max_new_tokens - 1
            alive = self._budget_h[slot] > 0 and (
                self.eos_id is None or tok != self.eos_id)
            self._active_h[slot] = bool(alive)
            if not alive:
                self._finish_slot(slot, req)
        if self._active_h.any():
            self._spec_round()
        return bool(
            self._queue or self._prefilling is not None
            or any(r is not None for r in self._slot_req)
        )

    def _spec_round(self) -> None:
        """Draft-K / verify-once / accept-prefix for every active slot.
        Bit-identical to sequential greedy decode: the verify forward
        runs the SAME warm-cache continuation path at the same
        positions, and only tokens whose entire input prefix matched
        the sequential stream are kept (plus the bonus correction,
        which is itself the sequential next token)."""
        K = self.spec_decode_k
        active_idx = [i for i in range(self.max_slots)
                      if self._active_h[i]]
        if any(int(self._len_h[i]) + K + 1 > self.max_seq
               for i in active_idx):
            # a row too close to the cache end would clamp the verify
            # DUS (corrupting EARLIER rows) — run one plain chunk
            # round instead; rare, and only near end-of-cache
            self.stats["spec_decode_fallbacks"] += 1
            self._plain_sync_round()
            return
        x = np.zeros((self.max_slots, K + 1), np.int32)
        pos = np.broadcast_to(
            np.arange(K + 1, dtype=np.int32),
            (self.max_slots, K + 1)).copy()
        drafted = 0
        for i in active_idx:
            req = self._slot_req[i]
            ctx = np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int32)])
            d = _ngram_draft(ctx, K, self.spec_ngram)
            drafted += int(d.size)
            x[i, 0] = self._tok_h[i]
            x[i, 1:1 + d.size] = d
            pos[i] += self._len_h[i]
            # inactive rows keep pos = arange(K+1) at offset 0: their
            # writes land on rows the next occupant's prefill scatter
            # or decode append overwrites before any read (the
            # engine-wide garbage-tolerance contract)
        self._push_state()
        self._cache, toks = _verify_chunk(
            self.model, self.params, self._cache,
            jnp.asarray(x), jnp.asarray(pos))
        t = np.asarray(toks)  # [B, K+1]; sync fetch — spec mode's RTT
        now = time.perf_counter()
        self.stats["spec_decode_rounds"] += 1
        self.stats["spec_decode_drafted"] += drafted
        self.stats["chunks"] += 1
        self.stats["decode_steps"] += 1
        for i in active_idx:
            req = self._slot_req[i]
            # accept-prefix: draft j survives iff it equals the greedy
            # token after the (already-accepted) prefix before it. A
            # pad that happens to equal the true token is sound to
            # accept — its KV row is then the true token's KV.
            a = 0
            while a < K and x[i, a + 1] == t[i, a]:
                a += 1
            emitted = [int(v) for v in x[i, 1:a + 1]] + [int(t[i, a])]
            m = min(len(emitted), int(self._budget_h[i]))
            emitted = emitted[:m]
            if self.eos_id is not None and self.eos_id in emitted:
                emitted = emitted[:emitted.index(self.eos_id) + 1]
            if not emitted:
                continue
            # the first `a` emitted tokens are accepted DRAFTS; the
            # bonus only rides when nothing truncated it — counting
            # len-1 unconditionally under-reported truncated rounds
            self.stats["spec_decode_accepted"] += min(len(emitted), a)
            req.tokens.extend(emitted)
            req.token_times.append((now, len(emitted)))
            self._budget_h[i] -= len(emitted)
            self._tok_h[i] = emitted[-1]
            # the newest token's position is L + len(emitted) in both
            # cases (bonus kept or cut); when cut, its KV row is
            # already written and the next feed rewrites it
            # idempotently at the same position
            self._len_h[i] += len(emitted)
            hit_eos = (self.eos_id is not None
                       and emitted[-1] == self.eos_id)
            alive = (self._budget_h[i] > 0 and not hit_eos
                     and self._len_h[i] < self.max_seq)
            self._active_h[i] = bool(alive)
            if not alive:
                self._finish_slot(i, req)

    def _plain_sync_round(self) -> None:
        """One plain decode chunk, dispatched and harvested in place —
        the spec pump's end-of-cache fallback. The packed fetch
        refreshes the host mirrors via _attribute's spec-mode hook."""
        self._push_state()
        self._dispatch_chunk({})
        while self._unattributed:
            self._attribute(block=True)

    def step(self) -> bool:
        """One pump round: attribute whatever the harvester finished,
        fill free slots, dispatch. Returns True while work remains."""
        if self._closed:
            raise RuntimeError("engine is closed")
        # parked export_slot() commands run first: they quiesce, so the
        # exported state is exactly the pre-round stream position
        self._service_exports()
        if self.spec_decode_k > 0:
            return self._spec_step()
        while self._attribute(block=False):
            pass
        if self._unattributed >= self.pipeline_depth:
            self._attribute(block=True)
        fills = (self._schedule_prefill() if self.chunked_prefill
                 else self._fill_free_slots())
        self.stats["queue_depth"] = len(self._queue)
        if fills or self._active_h.any():
            self._dispatch_chunk(fills)
        elif self._unattributed:
            self._attribute(block=True)
        return bool(
            self._queue or self._unattributed
            or self._prefilling is not None
            or any(r is not None for r in self._slot_req)
        )

    def pop_finished(self) -> Dict[int, Request]:
        """Drain and return every finished-but-uncollected request.
        Callers driving :meth:`step` directly (a server front-end)
        poll this between rounds; once popped, the engine retains no
        reference to the request. Thread-safe vs the pump's inserts."""
        with self._lock:
            done, self._done = self._done, {}
        return done

    def run(self) -> Dict[int, np.ndarray]:
        """Drain the queue; returns {rid: tokens [n] int32} for every
        request finished since the last drain (prompt excluded) —
        requests already collected by an earlier run()/pop_finished()
        are not re-returned."""
        while self.step():
            pass
        return {rid: np.asarray(r.tokens, np.int32)
                for rid, r in self.pop_finished().items()}

    def close(self) -> None:
        """Stop the harvester threads; subsequent submit()/step()
        raise. Also runs from ``__del__``: since the threads hold only
        the queues, an abandoned engine is collectible, and collection
        shuts its workers down."""
        with self._lock:
            self._closed = True
        # release any parked export_slot() waiters: step() will never
        # run again, so they'd otherwise sit out their full timeout
        while True:
            try:
                _, _, done, _ = self._export_q.popleft()
            except IndexError:
                break
            done.set()
        for _ in self._harvesters:
            self._fetchq.put(None)
        for t in self._harvesters:
            if t is not threading.current_thread():
                t.join(timeout=5)

    def __del__(self):  # best-effort; close() is still the right API
        try:
            for _ in self._harvesters:
                self._fetchq.put(None)
        except Exception:
            pass
