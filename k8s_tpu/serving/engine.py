"""Continuous-batching serving engine — TPU-shaped.

GPU serving stacks (vLLM-style) get request-level elasticity from
*paged* KV caches: per-request block tables resolved by the kernel at
runtime. On TPU that indirection fights the hardware — Mosaic wants
static shapes and contiguous slabs. The TPU-native shape of the same
idea is **slot-based ragged batching**:

- ONE static decode batch of ``max_slots`` rows, compiled once. Every
  row ("slot") holds one in-flight request at its own cache depth.
- The fused decode kernel appends/attends at a **per-row** position
  (``pos`` is a scalar-prefetch vector — `ops/attention.py`), so one
  kernel launch serves all slots regardless of how ragged they are.
- Arrivals don't recompile anything: a free slot is filled by a
  batch-1 **prefill** (one-shot flash over the prompt, padded to a
  small set of static buckets) whose per-layer K/V slab is scattered
  into the big cache at the slot index via donated
  ``dynamic_update_slice`` (in-place, no cache copy).
- Decode runs in **chunks of K steps inside one jit** (`lax.scan`):
  EOS/budget deactivation happens on-device, so the host syncs once
  per K tokens, not per token — load-bearing over a remote-tunnel
  PJRT transport where every host sync is a round-trip.

Inactive slots still compute (static shapes — that's the TPU trade):
their writes land on a frozen, masked cache row and their outputs are
dropped. Utilization therefore degrades gracefully with load instead
of recompiling with it.

The reference has no serving analogue (training-only operator,
SURVEY.md §0). Oracle for correctness: each request's tokens must
equal a solo :func:`k8s_tpu.models.llama.generate` run with the same
params (pinned by ``tests/test_serving.py``).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import itertools
import queue
import threading
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from k8s_tpu.models.llama import LlamaForCausalLM, _pick_token


@dataclasses.dataclass
class Request:
    """One generation request. ``tokens`` accumulates the output
    (first token from prefill + decoded tokens, prompt excluded)."""

    rid: int
    prompt: np.ndarray  # [plen] int32
    max_new_tokens: int
    tokens: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    submitted_at: float = 0.0   # time.perf_counter at submit()
    finished_at: float = 0.0    # ... at attribution of the last token


def _tree_scatter_slot(cache, small, slot, plen_b: int):
    """Scatter a batch-1 prefill cache into row ``slot`` of the big
    cache. Only the first ``plen_b`` rows (the padded prompt) are
    copied — pad rows land too but stay masked until overwritten by
    the slot's own decode appends. Leaf layouts (by name):

    - ``cached_key``/``cached_value``: [B, Hkv, S, D], rows on axis 2
    - ``key_scale``/``value_scale`` (int8-KV): [B, Hkv, 1, S], rows on
      axis 3

    Leaves may carry a leading scan-stacked layer axis
    (``scan_layers=True``: [L, B, ...]) — the batch axis is located
    from the END of the shape, so both layouts scatter identically.
    """

    def one(path, big, small_leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("cached_key", "cached_value"):
            rows_axis = big.ndim - 2          # ...the S axis
        elif name in ("key_scale", "value_scale"):
            rows_axis = big.ndim - 1          # scales: S is last
        else:
            raise ValueError(f"unknown cache leaf {name!r} (ragged "
                             "caches carry no cache_index)")
        rows = jax.lax.slice_in_dim(small_leaf, 0, plen_b, axis=rows_axis)
        batch_axis = big.ndim - 4             # [L?] B Hkv . .
        start = [jnp.int32(0)] * big.ndim
        start[batch_axis] = slot
        return jax.lax.dynamic_update_slice(big, rows, tuple(start))

    return jax.tree_util.tree_map_with_path(one, cache, small)


def _lm_head_logits(params, hidden, quant: str):
    """Head logits for a [*, E] hidden slice — prefill computes hidden
    for the whole padded prompt but only needs logits at the last REAL
    token, so the head runs on the gathered row, never on [P, V]."""
    if quant == "int8_serving":
        from k8s_tpu.ops.quant import int8_serving_matmul

        lm = params["lm_head"]
        return int8_serving_matmul(
            hidden.astype(jnp.float32), lm["kernel_q"], lm["scale"], 1
        )
    return hidden.astype(jnp.float32) @ params["lm_head"][
        "kernel"
    ].astype(jnp.float32)


# Module-level jits (llama.py house rule): defining these inside the
# engine would make every engine a fresh function object -> full
# recompile per instance; params/cache stay ARGUMENTS so weights are
# never baked into the HLO as constants.
@functools.partial(
    jax.jit,
    static_argnames=("model", "plen_b", "temperature"),
    donate_argnums=(2,),
)
def _prefill_insert(model, params, cache, slot, prompt_pb, plen, rng,
                    *, plen_b: int, temperature: float):
    """Batch-1 prefill of a padded prompt + scatter into ``slot`` of
    the (donated) big cache. Returns (cache', first_token)."""
    positions = jnp.broadcast_to(jnp.arange(plen_b), (1, plen_b))
    hidden, mut = model.apply(
        {"params": params}, prompt_pb, positions=positions,
        return_hidden=True, mutable=["cache"],
    )
    # last REAL token's hidden row (pads sit after it; causal attention
    # means they never influence it)
    h_last = jax.lax.dynamic_index_in_dim(
        hidden[0], plen - 1, axis=0, keepdims=False
    )
    logits = _lm_head_logits(params, h_last[None], model.config.quant)
    tok = _pick_token(logits, rng, temperature)[0]
    cache = _tree_scatter_slot(cache, mut["cache"], slot, plen_b)
    return cache, tok


@functools.partial(
    jax.jit,
    static_argnames=("model", "n_steps", "temperature", "eos_id"),
    donate_argnums=(2, 3, 4, 5, 6),
)
def _decode_chunk(model, params, cache, tok, lengths, active, budget,
                  rng, *, n_steps: int, temperature: float,
                  eos_id: Optional[int]):
    """K ragged decode steps in one jit. Per step, every slot advances
    iff active; EOS/budget/cache-full deactivation happens ON DEVICE.

    Returns ``(state..., packed)`` where ``packed`` is ONE int32
    array [2K+4, B] — the only thing the host ever fetches:

    - row 0: the chunk's INPUT tokens (how a freshly-prefilled slot's
      first token reaches the host without its own transfer)
    - rows 1..K: emitted tokens per step
    - rows K+1..2K: validity (1 = slot was active at step entry)
    - rows 2K+1..2K+3: final active / budget / lengths

    One packed fetch per chunk matters because serving runs over a
    remote-tunnel PJRT transport here: every separate device→host read
    is a full round-trip (~70-100 ms measured — 20-30 decode steps'
    worth), which round-tripping 6 small arrays per chunk turned into
    an 8x throughput hole. All scheduling state stays device-resident
    between chunks (the engine passes the returned arrays straight
    back in; donation keeps them in place)."""
    max_seq = model.config.max_seq_len
    tok_in = tok

    def step(carry, _):
        cache, tok, lengths, active, budget, rng = carry
        rng, r = jax.random.split(rng)
        pos = jnp.minimum(lengths, max_seq - 1)
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            tok[:, None], positions=pos[:, None], mutable=["cache"],
        )
        nxt = _pick_token(logits[:, -1], r, temperature)  # [B]
        emitted_by = active
        nxt = jnp.where(active, nxt, tok)  # freeze inactive slots
        budget = jnp.where(active, budget - 1, budget)
        lengths = jnp.where(active, jnp.minimum(lengths + 1, max_seq),
                            lengths)
        hit_eos = (
            (nxt == eos_id) & emitted_by
            if eos_id is not None
            else jnp.zeros_like(active)
        )
        active = active & (budget > 0) & ~hit_eos & (lengths < max_seq)
        return (mut["cache"], nxt, lengths, active, budget, rng), (
            nxt, emitted_by,
        )

    carry, (toks, valid) = jax.lax.scan(
        step, (cache, tok, lengths, active, budget, rng), None,
        length=n_steps,
    )
    cache, tok, lengths, active, budget, rng = carry
    packed = jnp.concatenate([
        tok_in[None], toks, valid.astype(jnp.int32),
        active.astype(jnp.int32)[None], budget[None], lengths[None],
    ], axis=0)
    return cache, tok, lengths, active, budget, rng, packed


@functools.partial(
    jax.jit, donate_argnums=(0, 1, 2, 3), static_argnames=("eos_id",)
)
def _set_slot(tok_v, lengths_v, active_v, budget_v, slot, tok_new,
              plen, max_new, *, eos_id: Optional[int]):
    """Activate ``slot`` after its prefill — ON DEVICE, including the
    finished-at-first-token check (the host never sees the prefill
    token until the next chunk's packed fetch)."""
    tok_v = tok_v.at[slot].set(tok_new)
    lengths_v = lengths_v.at[slot].set(plen)
    budget0 = max_new - 1
    fin = budget0 <= 0
    if eos_id is not None:
        fin = fin | (tok_new == eos_id)
    active_v = active_v.at[slot].set(~fin)
    budget_v = budget_v.at[slot].set(budget0)
    return tok_v, lengths_v, active_v, budget_v


def _harvest_loop(fetchq: "queue.Queue", readyq: "queue.Queue") -> None:
    """Harvester thread: materializes chunks' packed arrays.
    ``np.asarray`` blocks for a full transport round-trip, so it lives
    here, off the dispatch path; attribution stays in the pump thread
    (scheduling state is single-threaded). JAX defers async dispatch
    errors to exactly this materialization point, so failures are
    shipped to the pump as ("error", ...) items — a dead harvester
    would otherwise deadlock the engine silently."""
    while True:
        item = fetchq.get()
        if item is None:
            return
        seq, packed, fills, snapshot, t0 = item
        try:
            readyq.put((seq, np.asarray(packed), fills, snapshot, t0))
        except Exception as e:  # noqa: BLE001 - crossing threads
            readyq.put((seq, e, fills, snapshot, t0))


@functools.partial(jax.jit, static_argnames=("model", "max_slots"))
def _init_cache(model, params, max_slots: int):
    """Allocate the big ragged cache: one throwaway single-token apply
    creates zero-filled cache variables for all slots (the garbage row
    each slot writes at position 0 is overwritten by its first
    prefill insert and masked until then)."""
    dummy = jnp.zeros((max_slots, 1), jnp.int32)
    _, mut = model.apply(
        {"params": params}, dummy,
        positions=jnp.zeros((max_slots, 1), jnp.int32),
        mutable=["cache"],
    )
    return mut["cache"]


class ContinuousBatchingEngine:
    """Slot-based continuous batching over a ragged-decode model.

    Parameters
    ----------
    model:
        ``LlamaForCausalLM`` with ``decode=True, ragged_decode=True``
        (``scan_layers=False`` recommended — the unrolled decode layout
        is the measured-fast one, docs/BENCHMARKS.md).
    params:
        Canonical (or serving-transformed) parameter tree.
    max_slots:
        Static decode batch width = max concurrent requests in flight.
    prompt_buckets:
        Static prefill lengths; a prompt compiles at the smallest
        bucket that fits, so distinct prompt lengths cost at most
        ``len(prompt_buckets)`` prefill compilations, ever.
    decode_chunk:
        Decode steps per host round-trip (and per scheduling
        opportunity): larger amortizes host sync; smaller fills freed
        slots sooner. 16-32 is a good range on a tunnel transport.
    """

    def __init__(
        self,
        model: LlamaForCausalLM,
        params,
        *,
        max_slots: int = 8,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        decode_chunk: int = 64,
        prompt_buckets: Optional[Sequence[int]] = None,
        rng: Optional[jax.Array] = None,
        pipeline_depth: int = 2,
    ):
        cfg = model.config
        if not (cfg.decode and cfg.ragged_decode):
            raise ValueError(
                "engine needs LlamaConfig(decode=True, ragged_decode=True)"
            )
        self.model = model
        self.params = params
        self.max_slots = int(max_slots)
        self.max_seq = int(cfg.max_seq_len)
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.decode_chunk = int(decode_chunk)
        # chunks dispatched ahead of the oldest un-harvested one: the
        # packed fetch of chunk N then overlaps chunk N+1's execution,
        # hiding the transport round-trip entirely (1 = fetch blocks
        # the device; 2 is enough to cover one RTT)
        self.pipeline_depth = max(1, int(pipeline_depth))
        if prompt_buckets is None:
            prompt_buckets = [
                b for b in (128, 256, 512, 1024, 2048, 4096, 8192)
                if b < self.max_seq
            ]
        self.prompt_buckets = sorted(int(b) for b in prompt_buckets)
        if not self.prompt_buckets:
            raise ValueError("need at least one prompt bucket < max_seq_len")
        if self.prompt_buckets[-1] >= self.max_seq:
            # a bucket >= max_seq_len would accept prompts whose prefill
            # then fails at trace time with an opaque
            # dynamic_update_slice shape error — refuse loudly instead
            raise ValueError(
                f"prompt bucket {self.prompt_buckets[-1]} >= max_seq_len "
                f"{self.max_seq}: every bucket must leave room for at "
                "least one generated token"
            )
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)

        # ALL decode state lives on device between chunks; the host
        # holds only a scheduling VIEW refreshed from each chunk's
        # packed fetch (self._active_h). Shipping the [B] vectors back
        # and forth per chunk cost a tunnel round-trip each.
        self._cache = _init_cache(model, params, self.max_slots)
        self._tok = jnp.zeros(self.max_slots, jnp.int32)
        self._lengths = jnp.zeros(self.max_slots, jnp.int32)
        self._active = jnp.zeros(self.max_slots, bool)
        self._budget = jnp.zeros(self.max_slots, jnp.int32)
        self._active_h = np.zeros(self.max_slots, bool)  # host view
        self._slot_req: List[Optional[Request]] = [None] * self.max_slots
        self._queue: collections.deque = collections.deque()
        # Request lifetime: submit() -> _reqs (in flight) -> on the
        # finishing chunk's attribution, moved to _done -> drained by
        # pop_finished()/run(). Nothing is retained after the drain, so
        # a long-lived server's memory is bounded by in-flight work.
        self._reqs: Dict[int, Request] = {}
        self._done: Dict[int, Request] = {}
        self._rid = itertools.count()
        self._closed = False
        # guards the cross-thread mutations: submit()'s closed-check +
        # enqueue vs close(), and the _done insert vs pop_finished()'s
        # swap (submit/pop_finished are documented thread-safe)
        self._lock = threading.Lock()
        # Dispatched chunks flow pump -> _fetchq -> harvester threads
        # (which own the ONLY blocking device→host transfers) ->
        # _readyq -> pump attribution, re-ordered by sequence number.
        # The transfer round-trip is ~120 ms on the tunnel transport —
        # more than a small chunk's compute — so fetches must neither
        # sit on the dispatch path NOR serialize with each other (one
        # harvester capped the whole engine at ~1 chunk per RTT).
        self._fetchq: "queue.Queue" = queue.Queue()
        self._readyq: "queue.Queue" = queue.Queue()
        self._unattributed = 0   # dispatched, not yet attributed
        self._seq = 0            # dispatch order
        self._attr_seq = 0       # next chunk to attribute
        self._ready_held: Dict[int, tuple] = {}  # out-of-order buffer
        # the thread target closes over the QUEUES, not self: a
        # bound-method target would pin the engine (and its device KV
        # cache) for the process lifetime if close() is never called
        self._harvesters = [
            threading.Thread(
                target=_harvest_loop,
                args=(self._fetchq, self._readyq),
                daemon=True, name=f"serving-harvester-{i}")
            for i in range(4)
        ]
        for t in self._harvesters:
            t.start()
        # operational counters (surfaced by the bench / metrics hook)
        self.stats = {"prefills": 0, "chunks": 0, "decode_steps": 0,
                      "wasted_slot_steps": 0, "prefill_s": 0.0,
                      "chunk_s": 0.0}

    # -- request intake --------------------------------------------------

    def submit(self, prompt, max_new_tokens: int) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if prompt.size > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt len {prompt.size} exceeds the largest bucket "
                f"{self.prompt_buckets[-1]}"
            )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt {prompt.size} + new {max_new_tokens} exceeds "
                f"cache size {self.max_seq}"
            )
        req = Request(next(self._rid), prompt, int(max_new_tokens),
                      submitted_at=time.perf_counter())
        # the closed check and the enqueue must be one atomic unit vs a
        # concurrent close() (submit is documented callable from an
        # arrival thread): after close() the harvesters are gone, so a
        # request slipping past an unsynchronized check would enqueue
        # onto a dead engine and its caller would wait forever
        with self._lock:
            if self._closed:
                raise RuntimeError("engine is closed")
            self._reqs[req.rid] = req
            self._queue.append(req)
        return req.rid

    # -- scheduling ------------------------------------------------------

    def _bucket_for(self, plen: int) -> int:
        for b in self.prompt_buckets:
            if plen <= b:
                return b
        raise AssertionError  # guarded in submit()

    def _next_rng(self) -> jax.Array:
        self._rng, r = jax.random.split(self._rng)
        return r

    def _fill_free_slots(self) -> Dict[int, int]:
        """Dispatch a prefill+insert for every (free slot, queued
        request) pair — fully async, nothing fetched. Returns
        {slot: rid} of the fills; their first tokens surface in the
        NEXT dispatched chunk's packed row 0."""
        fills: Dict[int, int] = {}
        for slot in range(self.max_slots):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            req = self._queue.popleft()
            plen = int(req.prompt.size)
            plen_b = self._bucket_for(plen)
            padded = np.zeros((1, plen_b), np.int32)
            padded[0, :plen] = req.prompt
            t0 = time.perf_counter()
            self._cache, tok_new = _prefill_insert(
                self.model, self.params, self._cache,
                jnp.int32(slot), jnp.asarray(padded), jnp.int32(plen),
                self._next_rng(), plen_b=plen_b,
                temperature=self.temperature,
            )
            (self._tok, self._lengths, self._active,
             self._budget) = _set_slot(
                self._tok, self._lengths, self._active, self._budget,
                jnp.int32(slot), tok_new, jnp.int32(plen),
                jnp.int32(req.max_new_tokens), eos_id=self.eos_id,
            )
            self.stats["prefills"] += 1
            self.stats["prefill_s"] += time.perf_counter() - t0
            self._slot_req[slot] = req
            self._active_h[slot] = True  # optimistic; fixed at harvest
            fills[slot] = req.rid
        return fills

    # -- the pump --------------------------------------------------------

    def _dispatch_chunk(self, fills: Dict[int, int]) -> None:
        (self._cache, self._tok, self._lengths, self._active,
         self._budget, self._rng, packed) = _decode_chunk(
            self.model, self.params, self._cache, self._tok,
            self._lengths, self._active, self._budget, self._rng,
            n_steps=self.decode_chunk, temperature=self.temperature,
            eos_id=self.eos_id,
        )
        snapshot = [r.rid if r is not None else None
                    for r in self._slot_req]
        self._fetchq.put(
            (self._seq, packed, fills, snapshot, time.perf_counter()))
        self._seq += 1
        self._unattributed += 1
        self.stats["chunks"] += 1
        self.stats["decode_steps"] += self.decode_chunk

    def _next_ready(self, block: bool):
        """Chunk results in DISPATCH order: parallel harvesters finish
        out of order; attribution must not (token order per slot)."""
        while self._attr_seq not in self._ready_held:
            try:
                item = self._readyq.get(block=block)
            except queue.Empty:
                return None
            self._ready_held[item[0]] = item[1:]
        out = self._ready_held.pop(self._attr_seq)
        self._attr_seq += 1
        return out

    def _attribute(self, block: bool) -> bool:
        """Apply one harvested chunk's results via the dispatch-time
        slot→request snapshot — a slot may have been refilled since, so
        current `_slot_req` must not be trusted for old chunks."""
        item = self._next_ready(block)
        if item is None:
            return False
        arr, fills, snapshot, t0 = item
        self._unattributed -= 1
        if isinstance(arr, Exception):
            raise RuntimeError(
                f"decode chunk {self._attr_seq - 1} failed on device"
            ) from arr
        K = self.decode_chunk
        tok_in, toks = arr[0], arr[1:K + 1]
        valid = arr[K + 1:2 * K + 1].astype(bool)
        active_out = arr[2 * K + 1].astype(bool)
        self.stats["chunk_s"] += time.perf_counter() - t0
        self.stats["wasted_slot_steps"] += int((~valid).sum())
        for slot, rid in enumerate(snapshot):
            if rid is None:
                continue
            # finished requests leave _reqs at attribution (and may be
            # drained entirely); stale snapshot entries for them skip
            req = self._reqs.get(rid)
            if req is None or req.done:
                continue
            if fills.get(slot) == rid:
                # the prefill's token rode in as this chunk's input
                req.tokens.append(int(tok_in[slot]))
            req.tokens.extend(int(t) for t in toks[valid[:, slot], slot])
            if not active_out[slot]:
                req.done = True
                req.finished_at = time.perf_counter()
                # the insert must be atomic vs pop_finished()'s swap
                # (front-end threads poll it): an unsynchronized write
                # could land in a just-orphaned dict and be lost forever
                with self._lock:
                    self._done[rid] = self._reqs.pop(rid)
                if self._slot_req[slot] is req:
                    self._slot_req[slot] = None
                    self._active_h[slot] = False
        return True

    def step(self) -> bool:
        """One pump round: attribute whatever the harvester finished,
        fill free slots, dispatch. Returns True while work remains."""
        if self._closed:
            raise RuntimeError("engine is closed")
        while self._attribute(block=False):
            pass
        if self._unattributed >= self.pipeline_depth:
            self._attribute(block=True)
        fills = self._fill_free_slots()
        if fills or self._active_h.any():
            self._dispatch_chunk(fills)
        elif self._unattributed:
            self._attribute(block=True)
        return bool(
            self._queue or self._unattributed
            or any(r is not None for r in self._slot_req)
        )

    def pop_finished(self) -> Dict[int, Request]:
        """Drain and return every finished-but-uncollected request.
        Callers driving :meth:`step` directly (a server front-end)
        poll this between rounds; once popped, the engine retains no
        reference to the request. Thread-safe vs the pump's inserts."""
        with self._lock:
            done, self._done = self._done, {}
        return done

    def run(self) -> Dict[int, np.ndarray]:
        """Drain the queue; returns {rid: tokens [n] int32} for every
        request finished since the last drain (prompt excluded) —
        requests already collected by an earlier run()/pop_finished()
        are not re-returned."""
        while self.step():
            pass
        return {rid: np.asarray(r.tokens, np.int32)
                for rid, r in self.pop_finished().items()}

    def close(self) -> None:
        """Stop the harvester threads; subsequent submit()/step()
        raise. Also runs from ``__del__``: since the threads hold only
        the queues, an abandoned engine is collectible, and collection
        shuts its workers down."""
        with self._lock:
            self._closed = True
        for _ in self._harvesters:
            self._fetchq.put(None)
        for t in self._harvesters:
            if t is not threading.current_thread():
                t.join(timeout=5)

    def __del__(self):  # best-effort; close() is still the right API
        try:
            for _ in self._harvesters:
                self._fetchq.put(None)
        except Exception:
            pass
