"""Prefix-aware request router over N serving-engine replicas.

The front door of the serving FLEET (ROADMAP item 1, docs/SERVING.md
"Fleet"): one engine replica tops out at one chip's roofline; this
process turns N independent replicas — each a
:class:`~k8s_tpu.serving.server.ServingFrontend` the operator
materialized behind a per-index Service — into one endpoint.

Design, stdlib-only (this ships in the same ConfigMap-shipped image as
the launcher):

- **Discovery is env + polling, not registration.** The operator bakes
  ``KTPU_SERVING_PEERS`` (``"0=http://svc-0:port,1=..."`` — the same
  per-index Service-DNS plumbing the checkpoint peer wire uses) for the
  whole ``maxReplicas`` range; a background poller GETs each replica's
  ``/healthz`` and keeps a live view. A replica that is absent (not yet
  scaled up), mid-restart (connection refused) or flaking its stats
  endpoint is marked ``draining``/``down`` and simply not routed to —
  the poll loop never crashes on an unreachable peer, and scale events
  need no router restart.
- **Scoring.** Each request goes to the replica with the lowest load
  score: ``queue_depth + in_flight + prefill backlog (chunks) +
  requests routed there since its last poll`` (the last term covers
  poll staleness). Ties break on the lower replica index, so routing
  is deterministic for a given stats view.
- **Prefix affinity.** Requests whose first ``prefix_tokens`` tokens
  hash equal (the shared-system-prompt case) stick to the replica that
  served that prefix last — where the engine's shared-prefix KV cache
  (``prefix_cache_tokens``) holds it warm, so the affinity hit skips
  re-prefilling the prefix. Affinity YIELDS to health: a saturated,
  draining or dead affine replica falls back to the score winner (and
  the prefix re-binds there).
- **Retry on peer.** A forward that fails for replica reasons —
  connection refused/reset (crash), 429 (backpressure), 5xx — is
  retried on the next-best replica, each replica tried at most once.
  Generation requests are idempotent, so a killed replica's in-flight
  requests complete on a peer instead of surfacing as client errors;
  the chaos fault ``router-replica-loss`` pins this. Client errors
  (4xx) are returned as-is.
- **SLO aggregation.** Per-request TTFT/ITL samples (returned by the
  replicas since the fleet change) land in a sliding window; the
  ``/healthz`` ``slo`` block exposes their percentiles — the signal
  the reconciler-side :class:`~k8s_tpu.router.autoscaler.SloAutoscaler`
  scales the replica count on.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import struct
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from k8s_tpu.controller import metrics

log = logging.getLogger(__name__)

# replica health states (the router's view, refreshed by the poller)
READY = "ready"
DRAINING = "draining"  # refused/flaked recently, or replica reports draining
DOWN = "down"          # consecutive poll failures >= down_after
UNKNOWN = "unknown"    # never successfully polled

# a replica whose poll just failed once may be mid-restart — stop
# routing immediately (draining), declare it down after this many
# consecutive failures
DEFAULT_DOWN_AFTER = 2


def parse_peers(raw: str) -> Dict[int, str]:
    """``"0=http://svc-0:8000,1=http://svc-1:8000"`` → {index: url}
    (the ``KTPU_SERVING_PEERS`` contract, same shape as the checkpoint
    wire's ``KTPU_CKPT_PEERS``). Malformed entries are skipped."""
    out: Dict[int, str] = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        idx, _, url = part.partition("=")
        try:
            out[int(idx)] = url.rstrip("/")
        except ValueError:
            continue
    return out


def parse_roles(raw: str) -> Dict[int, str]:
    """``"0=prefill,1=decode,2=decode"`` → {index: role} (the
    ``KTPU_SERVING_ROLES`` contract, same shape as the peers env).
    Malformed entries and unknown roles are skipped WITH a warning —
    a silently-dropped role leaves that replica in neither pool
    (unroutable on the happy path), which must at least be visible in
    the router log."""
    out: Dict[int, str] = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        idx, sep, role = part.partition("=")
        role = role.strip().lower()
        if not sep or role not in ("prefill", "decode"):
            log.warning("roles: skipping malformed entry %r (want "
                        "<index>=prefill|decode) — that replica will "
                        "belong to NO pool", part)
            continue
        try:
            out[int(idx)] = role
        except ValueError:
            log.warning("roles: skipping entry %r (non-integer "
                        "index)", part)
            continue
    return out


def prefix_key(prompt, prefix_tokens: int) -> Optional[str]:
    """Affinity key: hash of the first ``prefix_tokens`` token ids.
    Prompts shorter than the prefix get no key (a short prompt carries
    no shared system prefix worth pinning)."""
    if prefix_tokens <= 0 or len(prompt) < prefix_tokens:
        return None
    head = ",".join(str(int(t)) for t in prompt[:prefix_tokens])
    return hashlib.sha1(head.encode()).hexdigest()


def _pct(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    return float(s[int(q * (len(s) - 1))])


@dataclasses.dataclass
class Replica:
    """The router's live view of one engine replica."""

    index: int
    url: str
    state: str = UNKNOWN
    stats: dict = dataclasses.field(default_factory=dict)
    failures: int = 0            # consecutive poll failures
    routed: int = 0              # lifetime requests routed here
    routed_since_poll: int = 0   # staleness compensation (see load())
    last_error: str = ""
    # sticky drain (drain_replica): the ROUTER decided this replica is
    # going away — stays DRAINING across healthy polls until deleted
    drain_requested: bool = False

    def load(self, include_backlog: bool = True) -> float:
        """Score used for routing: lower is better. Derived from the
        last successful poll plus the requests this router sent since
        (the poll view is up to one poll interval stale).
        ``include_backlog=False`` is the DECODE-pool score: a decode
        replica never prefills on the steady path, so the prefill-
        backlog term is meaningless there and would only let a
        fallback-prefill straggler repel its pool's real work."""
        st = self.stats or {}
        inner = st.get("stats") or {}
        # prefer the LIVE top-level queue_depth (reads the queue
        # itself) over the per-pump-round stats gauge: a burst landing
        # between the replica's pump rounds is invisible to the gauge,
        # and routed_since_poll only covers THIS router's own sends
        q = float(st.get("queue_depth",
                         inner.get("queue_depth") or 0) or 0)
        inflight = float(st.get("in_flight") or 0)
        # prefill backlog in chunk units: a half-prefilled 8k prompt is
        # real pending work the queue depth doesn't show
        backlog = 0.0
        if include_backlog:
            chunk = float(
                (st.get("scheduler") or {}).get("prefill_chunk") or 256)
            for p in (st.get("prefill_progress") or {}).values():
                backlog += max(
                    0.0, float(p.get("total", 0) - p.get("done", 0))
                ) / max(1.0, chunk)
        return q + inflight + backlog + self.routed_since_poll


class Router:
    """HTTP front door + stats poller + scoring/affinity policy.

    ``endpoints`` maps replica index → base URL. Every mutation of the
    routing view goes through :meth:`note_stats` /
    :meth:`note_poll_failure`, which the poller drives (and tests may
    drive directly — scoring is then fully deterministic).
    """

    def __init__(
        self,
        endpoints: Dict[int, str],
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        poll_interval: float = 0.5,
        poll_timeout: float = 2.0,
        prefix_tokens: int = 16,
        affinity_max: int = 4096,
        saturation_depth: float = 8.0,
        request_timeout: float = 300.0,
        down_after: int = DEFAULT_DOWN_AFTER,
        slo_window: int = 256,
        roles: Optional[Dict[int, str]] = None,
        migration: bool = False,
        mirror_interval: float = 0.25,
    ):
        self.replicas: Dict[int, Replica] = {
            int(i): Replica(index=int(i), url=u.rstrip("/"))
            for i, u in endpoints.items()
        }
        if not self.replicas:
            raise ValueError("router needs at least one replica endpoint")
        # Disaggregation (docs/SERVING.md "Disaggregation"): with a
        # role map carrying BOTH roles, routing is phase-aware — new
        # requests score against the prefill pool, the finished KV
        # hops to the least-loaded decode replica, and the decode leg
        # streams there. No/partial roles ⇒ today's interleaved
        # routing, bit-identical (the regression guard).
        self.roles: Dict[int, str] = {
            int(i): str(r) for i, r in (roles or {}).items()}
        self.disaggregated = (
            any(r == "prefill" for r in self.roles.values())
            and any(r == "decode" for r in self.roles.values()))
        # lifetime KV-handoff counters (mirrored into ktpu_router_kv_*)
        self.kv_transfers = 0
        self.kv_fallbacks = 0
        self.kv_bytes = 0
        # Live migration (docs/SERVING.md "Live migration & prefix
        # directory"): off by default — when on, a mirror thread
        # checkpoints in-flight decode slots onto peers, drained/dead
        # replicas' streams resume there instead of re-prefilling, and
        # the prefix directory (built from healthz advertisements)
        # points prefill workers at holding peers.
        self.migration = bool(migration)
        self.mirror_interval = float(mirror_interval)
        self.migrations = {"drain": 0, "reactive": 0}
        self.migration_fallbacks = 0
        # trace_id -> {"source": decode idx, "max_new"}: requests
        # currently on a decode leg (mirror candidates)
        self._mig_inflight: Dict[str, dict] = {}
        # trace_id -> {"handle", "target", "source"}: last landed
        # mirror — what the reactive rung resumes from
        self._mig_mirrors: Dict[str, dict] = {}
        # replica idx -> set of advertised prefix digests (the
        # fleet-wide directory), + the advertised engine prefix length
        self._prefix_dir: Dict[int, set] = {}
        self._prefix_len_adv = 0
        self._mirror_thread: Optional[threading.Thread] = None
        self.poll_interval = float(poll_interval)
        self.poll_timeout = float(poll_timeout)
        self.prefix_tokens = int(prefix_tokens)
        self.saturation_depth = float(saturation_depth)
        self.request_timeout = float(request_timeout)
        self.down_after = max(1, int(down_after))
        self._affinity: "OrderedDict[str, int]" = OrderedDict()
        self.affinity_max = int(affinity_max)
        self._lock = threading.Lock()
        self._draining = False
        # lifetime counters (mirrored into ktpu_router_* metrics)
        self.routed_total = 0
        self.retries = 0
        self.rejected = 0       # requests that exhausted every replica
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.affinity_fallbacks = 0
        self._slo: deque = deque(maxlen=int(slo_window))
        # per-request span samples (router_s + the engine-side
        # decomposition) backing the /healthz ``trace`` block — the
        # aggregate view of where TTFT goes (docs/OBSERVABILITY.md)
        self._spans: deque = deque(maxlen=int(slo_window))
        self._stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None

        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # pod-log hygiene
                pass

            def _json(self, code: int, payload: dict, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path == "/metrics":
                    body = metrics.REGISTRY.expose().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path != "/healthz":
                    return self._json(404, {"error": "not found"})
                return self._json(200, router.healthz())

            def do_POST(self):  # noqa: N802
                if self.path.startswith("/v1/drain/"):
                    # operator drain orchestration: migrate replica
                    # N's in-flight streams to peers, then report —
                    # the caller deletes the pod once this returns
                    try:
                        idx = int(self.path[len("/v1/drain/"):])
                    except ValueError:
                        return self._json(
                            400, {"error": "bad replica index"})
                    try:
                        return self._json(200, router.drain_replica(idx))
                    except KeyError:
                        return self._json(
                            404, {"error": f"unknown replica {idx}"})
                if self.path != "/v1/generate":
                    return self._json(404, {"error": "not found"})
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(n)
                    payload = json.loads(body)
                    # coerce here so a non-token prompt (a string, a
                    # list with non-numeric elements) is the CLIENT's
                    # 400 — not a ValueError out of prefix_key that
                    # drops the connection with no response
                    prompt = [int(t) for t in payload["prompt"]]
                except Exception as e:
                    return self._json(400, {"error": f"bad request: {e}"})
                code, out, headers = router.route_and_forward(
                    prompt, body,
                    trace_id=self.headers.get("X-KTPU-Trace-Id", ""))
                return self._json(code, out, headers=headers)

        class Server(ThreadingHTTPServer):
            daemon_threads = True
            # the front door takes the whole fleet's client burst on
            # one listener: the stock backlog of 5 drops SYNs under
            # concurrency and each drop costs a 1s TCP retransmit
            request_queue_size = 128

        self._server = Server((host, port), Handler)
        self.host = host
        self.port = int(self._server.server_address[1])
        self._http_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="router-http")

    # ------------------------------------------------------------ view

    def note_stats(self, index: int, payload: dict) -> None:
        """Record a successful /healthz poll of replica ``index``."""
        with self._lock:
            r = self.replicas.get(index)
            if r is None:
                return
            r.stats = payload or {}
            r.failures = 0
            r.routed_since_poll = 0
            r.last_error = ""
            # a router-requested drain is STICKY: the replica itself
            # still polls healthy right up until the operator deletes
            # it, and un-draining it here would route new work onto a
            # pod that is about to disappear
            r.state = DRAINING if (r.stats.get("draining")
                                   or r.drain_requested) else READY
            if self.migration:
                mig = (payload or {}).get("migration")
                if isinstance(mig, dict):
                    self._prefix_dir[index] = set(
                        str(k) for k in (mig.get("prefix_keys") or ()))
                    plen = int(mig.get("prefix_len") or 0)
                    if plen:
                        self._prefix_len_adv = plen
        self._healthy_gauge()

    def note_poll_failure(self, index: int, err: str) -> None:
        """Record a failed poll: connection refused / timeout / 5xx.
        A replica mid-restart refuses connections for a few seconds —
        it is marked ``draining`` (not routed to) on the FIRST failure
        and ``down`` after ``down_after`` consecutive ones; either way
        the poll loop carries on. (Fix en route: consumers of
        ``HealthServer``-style endpoints used to assume the endpoint
        is always up.)"""
        with self._lock:
            r = self.replicas.get(index)
            if r is None:
                return
            r.failures += 1
            r.last_error = err
            r.state = DOWN if r.failures >= self.down_after else DRAINING
        self._healthy_gauge()

    def _healthy_gauge(self) -> None:
        with self._lock:
            n = sum(1 for r in self.replicas.values() if r.state == READY)
        metrics.ROUTER_REPLICAS_READY.set(float(n))

    def _poll_one(self, idx: int, url: str) -> None:
        try:
            with urllib.request.urlopen(
                    url + "/healthz",
                    timeout=self.poll_timeout) as resp:
                payload = json.loads(resp.read())
            self.note_stats(idx, payload)
        except Exception as e:  # noqa: BLE001 - any failure is a miss
            self.note_poll_failure(idx, str(e))

    def _poll_once(self) -> None:
        # one sweep polls every peer CONCURRENTLY: the peer list spans
        # the whole maxReplicas range, and unscaled/blackholed indices
        # each cost up to poll_timeout — serially that would stretch a
        # sweep to replicas*timeout, lagging DOWN detection and load
        # scores far behind the intended cadence
        threads = [
            threading.Thread(target=self._poll_one, args=(idx, r.url),
                             daemon=True, name=f"router-poll-{idx}")
            for idx, r in list(self.replicas.items())
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.poll_timeout + 1.0)

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._poll_once()
            except Exception:  # the poller must never die
                pass
            self._stop.wait(self.poll_interval)

    # ------------------------------------------------------------ policy

    def _routable(self, r: Replica) -> bool:
        return r.state == READY

    def _saturated(self, r: Replica) -> bool:
        return r.load() >= self.saturation_depth

    def _in_prefill_pool(self, index: int) -> bool:
        """Phase membership for the ADMISSION pool: in disaggregated
        mode only prefill-role replicas take new prompts; otherwise
        every replica does (interleaved fleet)."""
        if not self.disaggregated:
            return True
        return self.roles.get(index) == "prefill"

    def pick_replica(self, prompt) -> Tuple[Optional[int], str]:
        """Pure routing decision: (replica index | None, affinity
        verdict in {"hit", "fallback", "miss", "none"}). Deterministic
        given the current stats view — the unit-test surface. In
        disaggregated mode this picks the PREFILL-leg replica: the
        candidate set is the prefill pool, and prefix affinity both
        binds and honors bindings WITHIN that pool only — affinity to
        a decode replica is dead weight (its prefix KV never warms),
        so a stale cross-pool binding falls back and re-binds."""
        key = prefix_key(prompt, self.prefix_tokens)
        with self._lock:
            ready = [r for r in self.replicas.values()
                     if self._routable(r)
                     and self._in_prefill_pool(r.index)]
            if not ready:
                return None, "none"
            if key is not None:
                bound = self._affinity.get(key)
                if bound is not None:
                    r = self.replicas.get(bound)
                    if r is not None and self._routable(r) \
                            and self._in_prefill_pool(bound) \
                            and not self._saturated(r):
                        self._affinity.move_to_end(key)
                        return bound, "hit"
                    verdict = "fallback"
                else:
                    verdict = "miss"
            else:
                verdict = "none"
            # least-loaded wins; ties break on the LOWER index so the
            # decision is reproducible for a given stats view
            best = min(ready, key=lambda r: (r.load(), r.index))
            if key is not None:
                self._affinity[key] = best.index
                self._affinity.move_to_end(key)
                while len(self._affinity) > self.affinity_max:
                    self._affinity.popitem(last=False)
            return best.index, verdict

    def pick_decode(self, exclude=()) -> Optional[int]:
        """Decode-leg target: the least-loaded READY decode replica,
        scored WITHOUT the prefill-backlog term (meaningless in a pool
        that never prefills on the steady path). Ties break on the
        lower index; ``exclude`` holds indices already tried for this
        request."""
        with self._lock:
            ready = [r for r in self.replicas.values()
                     if self._routable(r)
                     and self.roles.get(r.index) == "decode"
                     and r.index not in exclude]
            if not ready:
                return None
            best = min(ready, key=lambda r: (r.load(include_backlog=False),
                                             r.index))
            return best.index

    def _count_verdict(self, verdict: str) -> None:
        if verdict == "hit":
            self.affinity_hits += 1
            metrics.ROUTER_AFFINITY_HITS.inc()
        elif verdict == "miss":
            self.affinity_misses += 1
        elif verdict == "fallback":
            self.affinity_fallbacks += 1
            metrics.ROUTER_AFFINITY_FALLBACKS.inc()

    # ------------------------------------------------------------ data path

    def _forward(self, url: str, body: bytes, trace_id: str = "",
                 path: str = "/v1/generate"):
        headers = {"Content-Type": "application/json"}
        if trace_id:
            # trace propagation: the replica stamps its spans under
            # the SAME id this router (and its caller) logs
            headers["X-KTPU-Trace-Id"] = trace_id
        req = urllib.request.Request(
            url + path, data=body, headers=headers)
        with urllib.request.urlopen(
                req, timeout=self.request_timeout) as resp:
            return resp.status, json.loads(resp.read())

    def route_and_forward(self, prompt, body: bytes, trace_id: str = ""):
        """Route one request, retrying replica-side failures on peers.
        Returns ``(http code, payload, extra headers)``. The payload
        carries ``trace_id`` + a ``spans`` block decomposing the
        request path: ``router_s`` (time this router spent on scoring,
        forwarding overhead, and any peer retries) over the engine's
        queue → prefill → decode spans (and, in disaggregated mode,
        the ``kv_transfer_s`` leg between them)."""
        if self._draining:
            return 503, {"error": "router draining"}, None
        if not trace_id:
            import uuid

            trace_id = "req-" + uuid.uuid4().hex[:12]
        if self.disaggregated:
            return self._route_disagg(prompt, body, trace_id)
        return self._route_plain(prompt, body, trace_id)

    def _route_plain(self, prompt, body: bytes, trace_id: str,
                     tried: Optional[set] = None,
                     count_affinity: bool = True):
        """The interleaved routing loop (pre-disaggregation behavior,
        byte-identical when no roles are configured). Also the FINAL
        rung of the disaggregated fallback ladder — ``tried`` then
        pre-excludes replicas that already failed this request and
        ``count_affinity=False`` keeps the affinity counters honest
        (the disagg leg already counted its verdict)."""
        t_route0 = time.perf_counter()
        tried = set(tried or ())
        saw_429 = False
        retry_after = "1"
        first_verdict: Optional[str] = None
        while True:
            if count_affinity:
                idx, verdict = self._pick_excluding(prompt, tried)
            else:
                # disagg fallback rung: ANY ready replica may serve
                # the request interleaved — pool restriction and
                # affinity are the happy path's concerns, not the
                # ladder's last rung
                idx, verdict = self._pick_any(tried)
            if first_verdict is None:
                if count_affinity:
                    with self._lock:
                        self._count_verdict(verdict)
                first_verdict = verdict
            if idx is None:
                break
            tried.add(idx)
            r = self.replicas[idx]
            with self._lock:
                r.routed += 1
                r.routed_since_poll += 1
            metrics.ROUTER_REQUESTS.inc({"replica": str(idx)})
            try:
                code, payload = self._forward(r.url, body,
                                              trace_id=trace_id)
            except urllib.error.HTTPError as e:
                try:
                    err_payload = json.loads(e.read())
                except Exception:
                    err_payload = {"error": f"replica {idx}: HTTP {e.code}"}
                if e.code == 429:
                    # honest backpressure — try a less loaded peer
                    saw_429 = True
                    retry_after = e.headers.get("Retry-After") or retry_after
                    self._note_retry(idx)
                    continue
                if e.code >= 500:
                    self._note_retry(idx)
                    continue
                # 4xx: the CLIENT's error — retrying elsewhere would
                # just repeat it
                return e.code, err_payload, None
            except Exception as e:  # connection refused/reset, timeout
                # the replica died under the request (or mid-restart):
                # mark it down and retry the idempotent request on a
                # peer — this is the killed-replica-loses-nothing path
                self.note_poll_failure(idx, str(e))
                self._note_retry(idx)
                continue
            engine_latency = 0.0
            if isinstance(payload, dict):
                engine_latency = float(payload.get("latency_s") or 0.0)
            router_s = max(
                0.0, time.perf_counter() - t_route0 - engine_latency)
            with self._lock:
                self.routed_total += 1
                if isinstance(payload, dict):
                    ttft = payload.get("ttft_s")
                    itl = payload.get("itl_ms")
                    if ttft is not None:
                        self._slo.append(
                            (float(ttft), float(itl or 0.0)))
                    self._spans.append({
                        "router_s": router_s,
                        **{k: float(v) for k, v in
                           (payload.get("spans") or {}).items()},
                    })
            if isinstance(payload, dict):
                payload = dict(payload)
                payload["replica"] = idx
                payload["retries"] = len(tried) - 1
                payload.setdefault("trace_id", trace_id)
                spans = dict(payload.get("spans") or {})
                spans["router_s"] = round(router_s, 4)
                payload["spans"] = spans
            return code, payload, None
        with self._lock:
            self.rejected += 1
        if saw_429:
            return (429, {"error": "all replicas saturated"},
                    {"Retry-After": retry_after})
        return 503, {"error": "no routable replica"}, None

    # ------------------------------------------------- disaggregated path

    def _note_kv_fallback(self) -> None:
        with self._lock:
            self.kv_fallbacks += 1
        metrics.ROUTER_KV_FALLBACKS.inc()

    def _fallback_plain(self, prompt, body: bytes, trace_id: str,
                        tried) -> tuple:
        """Last rung of the disagg ladder: serve the whole request
        interleaved on any ready replica (prefill replicas are full
        engines — the 'local prefill' degradation). Greedy engines are
        deterministic, so the fallback's tokens are bit-identical to
        the phase-split path's."""
        self._note_kv_fallback()
        return self._route_plain(prompt, body, trace_id,
                                 tried=tried, count_affinity=False)

    def _route_disagg(self, prompt, body: bytes, trace_id: str):
        """Phase-split data path: prefill leg → KV push (done by the
        prefill worker, target chosen HERE) → decode leg, composed
        into one response whose spans satisfy
        ``engine_queue_s + prefill_s + kv_transfer_s == ttft_s`` by
        construction. The fallback ladder, in order: retry prefill on
        a pool peer → the prefill worker's own local-prefill fallback
        (push failed) → re-route the whole request interleaved (decode
        leg failed / pools empty). Every rung returns the same
        deterministic tokens; only latency degrades."""
        t_route0 = time.perf_counter()
        try:
            payload_in = json.loads(body)
            max_new = int(payload_in.get("max_new_tokens", 16))
        except Exception:
            max_new = 16
        pre_tried: set = set()
        dec_tried: set = set()
        first_verdict: Optional[str] = None
        saw_429 = False
        retry_after = "1"
        while True:
            idx, verdict = (
                self.pick_replica(prompt) if not pre_tried
                else self._pick_prefill_excluding(pre_tried))
            if first_verdict is None:
                with self._lock:
                    self._count_verdict(verdict)
                first_verdict = verdict
            if idx is None:
                break  # prefill pool exhausted → interleave fallback
            d_idx = self.pick_decode(exclude=dec_tried)
            if d_idx is None:
                break  # decode pool empty → interleave fallback
            import uuid

            handle = "kv-" + uuid.uuid4().hex[:16]
            pre_tried.add(idx)
            p, d = self.replicas[idx], self.replicas[d_idx]
            with self._lock:
                p.routed += 1
                p.routed_since_poll += 1
            metrics.ROUTER_REQUESTS.inc({"replica": str(idx)})
            pre_req = {
                "prompt": [int(t) for t in prompt],
                "max_new_tokens": max_new,
                "kv_target": d.url,
                "handle": handle,
            }
            if self.migration:
                # prefix directory: point the prefill worker at a
                # peer already holding this prompt's shared-prefix
                # snapshot — it fetches on a local LRU miss
                holder = self._prefix_holder_for(prompt, exclude=(idx,))
                if holder:
                    pre_req["prefix_from"] = holder
            pre_body = json.dumps(pre_req).encode()
            try:
                code, pre = self._forward(p.url, pre_body,
                                          trace_id=trace_id,
                                          path="/v1/prefill")
            except urllib.error.HTTPError as e:
                # drain the error body on EVERY path (the plain
                # loop's discipline): an unread HTTPError pins its
                # socket until GC, one per tried replica per shed
                # request under a saturated pool
                try:
                    err_body = e.read()
                except Exception:
                    err_body = b""
                if e.code == 429:
                    saw_429 = True
                    retry_after = e.headers.get("Retry-After") \
                        or retry_after
                    self._note_retry(idx)
                    continue
                if e.code >= 500:
                    self._note_retry(idx)
                    continue
                try:
                    err_payload = json.loads(err_body)
                except Exception:
                    err_payload = {
                        "error": f"replica {idx}: HTTP {e.code}"}
                return e.code, err_payload, None
            except Exception as e:  # refused/reset/timeout: dead worker
                self.note_poll_failure(idx, str(e))
                self._note_retry(idx)
                continue
            if not isinstance(pre, dict):
                break
            spans_pre = pre.get("spans") or {}
            kv_s = float(spans_pre.get("kv_transfer_s") or 0.0)
            kv_bytes = int(pre.get("kv_bytes") or 0)
            if pre.get("local_fallback"):
                # the push died mid-transfer; the prefill worker
                # already served the whole request from its snapshot
                self._note_kv_fallback()
                return self._compose(
                    t_route0, trace_id, pre, spans_pre, kv_s, 0,
                    replica=idx, prefill_replica=idx,
                    retries=len(pre_tried) - 1 + len(dec_tried),
                    local_fallback=True, pre_latency=0.0)
            # decode leg — count the committed work against d's score
            # only NOW: incrementing at pick time accrued phantom load
            # on the least-loaded replica across prefill-leg retries
            # (dec_tried only grows on decode-leg failures) and on
            # local fallbacks that never send it anything
            with self._lock:
                d.routed += 1
                d.routed_since_poll += 1
            metrics.ROUTER_REQUESTS.inc({"replica": str(d_idx)})
            dec_body = json.dumps({
                "handle": handle, "max_new_tokens": max_new}).encode()
            if self.migration:
                # while this request is on its decode leg it is a
                # mirror candidate: the mirror thread checkpoints its
                # slot onto a peer, and a mirrored slot is what the
                # reactive rung resumes from if d dies mid-stream
                with self._lock:
                    self._mig_inflight[trace_id] = {
                        "source": d_idx, "max_new": max_new}
            try:
                dec = None
                for attempt in (0, 1):
                    try:
                        code2, dec = self._forward(d.url, dec_body,
                                                   trace_id=trace_id,
                                                   path="/v1/decode")
                        break
                    except urllib.error.HTTPError as e:
                        try:
                            e.read()  # drain: unread errors pin sockets
                        except Exception:
                            pass
                        if e.code in (429, 503) and attempt == 0:
                            # transient admission rejection: the decode
                            # worker RESTORED the popped handle
                            # expecting exactly this retry — one brief
                            # retry against the SAME replica (the
                            # handle lives there) beats a full
                            # interleaved re-prefill
                            try:
                                ra = float(
                                    e.headers.get("Retry-After") or 0.2)
                            except (TypeError, ValueError):
                                ra = 0.2  # HTTP-date form: back off
                            time.sleep(min(0.5, ra))
                            continue
                        # 404 = handle never arrived / evicted; other
                        # codes = replica-side — the KV is unusable
                        # now: migration rung first (resume from the
                        # mirrored slot), the interleaved rung last
                        self._note_retry(d_idx)
                        dec_tried.add(d_idx)
                        mig = self._migrate_rung(trace_id, t_route0,
                                                 idx, dec_tried)
                        if mig is not None:
                            return mig
                        return self._fallback_plain(prompt, body,
                                                    trace_id, dec_tried)
                    except Exception as e:  # replica died mid-stream
                        self.note_poll_failure(d_idx, str(e))
                        self._note_retry(d_idx)
                        dec_tried.add(d_idx)
                        mig = self._migrate_rung(trace_id, t_route0,
                                                 idx, dec_tried)
                        if mig is not None:
                            return mig
                        return self._fallback_plain(prompt, body,
                                                    trace_id, dec_tried)
                if not isinstance(dec, dict):
                    dec_tried.add(d_idx)
                    mig = self._migrate_rung(trace_id, t_route0, idx,
                                             dec_tried)
                    if mig is not None:
                        return mig
                    return self._fallback_plain(prompt, body, trace_id,
                                                dec_tried)
                with self._lock:
                    self.kv_transfers += 1
                    self.kv_bytes += kv_bytes
                metrics.ROUTER_KV_TRANSFERS.inc()
                metrics.ROUTER_KV_BYTES.inc(by=kv_bytes)
                return self._compose(
                    t_route0, trace_id, dec, spans_pre, kv_s, kv_bytes,
                    replica=d_idx, prefill_replica=idx,
                    retries=len(pre_tried) - 1 + len(dec_tried),
                    pre_latency=float(pre.get("latency_s") or 0.0))
            finally:
                if self.migration:
                    # cleanup AFTER the migration rung read its mirror
                    # — the stream resolved one way or another by now
                    with self._lock:
                        self._mig_inflight.pop(trace_id, None)
                        self._mig_mirrors.pop(trace_id, None)
        if saw_429 and not [
                r for r in self.replicas.values()
                if self._routable(r)
                and self._in_prefill_pool(r.index)
                and r.index not in pre_tried]:
            # the PREFILL pool is saturated (429s, not deaths): shed
            # load honestly. Spilling full interleaved requests onto
            # the decode pool here would silently reintroduce the
            # prefill interference this mode exists to remove AND hide
            # the backpressure signal clients throttle on.
            with self._lock:
                self.rejected += 1
            return (429, {"error": "prefill pool saturated"},
                    {"Retry-After": retry_after})
        # pools unusable (no ready prefill or decode replica): serve
        # interleaved on whatever is still standing — EXCLUDING the
        # replicas that already failed this request (a dead-but-not-
        # yet-DOWN prefill pod would otherwise eat a second connect
        # timeout per request on the fallback rung)
        return self._fallback_plain(prompt, body, trace_id,
                                    pre_tried | dec_tried)

    def _pick_prefill_excluding(self, tried: set):
        with self._lock:
            ready = [r for r in self.replicas.values()
                     if self._routable(r)
                     and self._in_prefill_pool(r.index)
                     and r.index not in tried]
            if not ready:
                return None, "none"
            best = min(ready, key=lambda r: (r.load(), r.index))
            return best.index, "none"

    # ------------------------------------------- live migration (ladder)

    def _migrate_rung(self, trace_id: str, t_route0: float,
                      prefill_replica: int, dec_tried: set):
        """The migration rung of the fallback ladder — ABOVE re-prefill
        (which stays terminal): if this stream's slot was mirrored onto
        a peer before its decode replica failed, resume it there via
        ``POST /v1/migrate/{handle}`` and return the composed response;
        ``None`` means fall down to the next rung. A missing/expired
        mirror, a dead target, or a rejected resume all count as
        migration fallbacks — the request then pays the re-prefill the
        migration would have saved."""
        if not self.migration:
            return None
        with self._lock:
            mirror = self._mig_mirrors.get(trace_id)
        if mirror is None:
            return None
        tgt_idx = int(mirror["target"])
        if tgt_idx in dec_tried:
            return None
        tgt = self.replicas.get(tgt_idx)
        if tgt is None:
            return None
        try:
            req = urllib.request.Request(
                tgt.url + "/v1/migrate/" + mirror["handle"], data=b"",
                headers={"Content-Type": "application/json",
                         "X-KTPU-Trace-Id": trace_id})
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout) as resp:
                payload = json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 - 404/5xx/dead target
            log.warning("migration rung: resume of %s on replica %d "
                        "failed (%s) — falling through to re-prefill",
                        trace_id, tgt_idx, e)
            with self._lock:
                self.migration_fallbacks += 1
            metrics.ROUTER_MIGRATION_FALLBACKS.inc()
            return None
        if not isinstance(payload, dict) \
                or payload.get("tokens") is None:
            with self._lock:
                self.migration_fallbacks += 1
            metrics.ROUTER_MIGRATION_FALLBACKS.inc()
            return None
        router_s = max(
            0.0, time.perf_counter() - t_route0
            - float(payload.get("latency_s") or 0.0))
        spans = {k: float(v)
                 for k, v in (payload.get("spans") or {}).items()}
        spans["router_s"] = round(router_s, 4)
        with self._lock:
            self.migrations["reactive"] += 1
            self.routed_total += 1
            ttft = payload.get("ttft_s")
            if ttft is not None:
                self._slo.append((float(ttft),
                                  float(payload.get("itl_ms") or 0.0)))
            self._spans.append(dict(spans))
        metrics.ROUTER_MIGRATIONS.inc({"reason": "reactive"})
        out = dict(payload)
        out["replica"] = tgt_idx
        out["prefill_replica"] = prefill_replica
        out["retries"] = len(dec_tried)
        out["migrated"] = True
        out["spans"] = spans
        out.setdefault("trace_id", trace_id)
        return 200, out, None

    # ------------------------------------------- live migration (mirror)

    def _pick_mirror_target(self, exclude=()) -> Optional[int]:
        """Where a mirror (or drain hand-off) should land: the least-
        loaded ready DECODE peer, else any ready peer — never the
        source itself."""
        idx = self.pick_decode(exclude=exclude)
        if idx is not None:
            return idx
        with self._lock:
            ready = [r for r in self.replicas.values()
                     if self._routable(r) and r.index not in exclude]
            if not ready:
                return None
            best = min(ready, key=lambda r: (
                r.load(include_backlog=False), r.index))
            return best.index

    def _mirror_once(self) -> None:
        """One mirror sweep: for every request currently on a decode
        leg, ask its source replica to export the slot (remove=False)
        and push the snapshot into a chosen peer's handle store. The
        handle is deterministic per trace (``mig-<trace>``), so each
        sweep OVERWRITES the previous checkpoint — the reactive rung
        always resumes from the freshest mirrored state and replays
        only the tokens since."""
        with self._lock:
            inflight = {t: dict(v)
                        for t, v in self._mig_inflight.items()}
        for trace_id, info in inflight.items():
            src = self.replicas.get(int(info["source"]))
            if src is None:
                continue
            tgt_idx = self._pick_mirror_target(
                exclude=(int(info["source"]),))
            if tgt_idx is None:
                continue
            handle = "mig-" + trace_id
            try:
                req = urllib.request.Request(
                    src.url + "/v1/mirror",
                    data=json.dumps({
                        "trace_id": trace_id,
                        "target": self.replicas[tgt_idx].url,
                        "handle": handle}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(
                        req, timeout=self.request_timeout) as resp:
                    if resp.status != 200:
                        continue
                    resp.read()
            except Exception:  # noqa: BLE001 - a missed tick is fine
                continue
            with self._lock:
                if trace_id in self._mig_inflight:
                    self._mig_mirrors[trace_id] = {
                        "handle": handle, "target": tgt_idx,
                        "source": int(info["source"])}

    def _mirror_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._mirror_once()
            except Exception:  # the mirror must never die
                pass
            self._stop.wait(self.mirror_interval)

    # ------------------------------------------- live migration (drain)

    def _drain_targets(self, index: int) -> List[str]:
        """Scored hand-off targets for draining ``index``: ready
        decode peers first (load order), any ready peer otherwise —
        never the drained replica itself."""
        with self._lock:
            cands = [r for r in self.replicas.values()
                     if r.index != index and self._routable(r)
                     and (not self.disaggregated
                          or self.roles.get(r.index) == "decode")]
            if not cands:
                cands = [r for r in self.replicas.values()
                         if r.index != index and self._routable(r)]
            cands.sort(key=lambda r: (
                r.load(include_backlog=False), r.index))
            return [r.url for r in cands]

    def drain_replica(self, index: int) -> dict:
        """Zero-downtime drain (docs/SERVING.md "Live migration"):
        stop routing NEW work to ``index`` (sticky DRAINING), then ask
        it to hand every in-flight decode stream to a scored peer over
        ``POST /v1/drain_migrate`` — in-flight clients get their full,
        bit-identical token streams from the peers, and the replica is
        safe to delete once this returns. Raises KeyError on an
        unknown index (the HTTP handler's 404)."""
        r = self.replicas[index]
        with self._lock:
            r.drain_requested = True
            if r.state == READY:
                r.state = DRAINING
        self._healthy_gauge()
        targets = self._drain_targets(index)
        out = {"index": index, "targets": targets,
               "migrated": 0, "failed": 0, "skipped": 0}
        if not targets:
            out["error"] = "no ready migration target"
            return out
        try:
            req = urllib.request.Request(
                r.url + "/v1/drain_migrate",
                data=json.dumps({"targets": targets}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout) as resp:
                summary = json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 - replica gone already?
            out["error"] = str(e)
            return out
        for k in ("migrated", "failed", "skipped"):
            out[k] = int(summary.get(k) or 0)
        if out["migrated"]:
            with self._lock:
                self.migrations["drain"] += out["migrated"]
            metrics.ROUTER_MIGRATIONS.inc({"reason": "drain"},
                                          by=float(out["migrated"]))
        if out["failed"]:
            with self._lock:
                self.migration_fallbacks += out["failed"]
            metrics.ROUTER_MIGRATION_FALLBACKS.inc(
                by=float(out["failed"]))
        return out

    # --------------------------------------------------- prefix directory

    def _prefix_holder_for(self, prompt, exclude=()) -> Optional[str]:
        """URL of a READY replica advertising this prompt's prefix
        digest, or None. The digest keyspace is the ENGINE's (sha256
        of the raw little-endian int32 prefix bytes) — NOT this
        router's own ``prefix_key`` affinity hash; ties break on the
        lower index so the choice is deterministic."""
        with self._lock:
            plen = self._prefix_len_adv
            if plen <= 0 or len(prompt) <= plen:
                return None
            head = [int(t) for t in prompt[:plen]]
            digest = hashlib.sha256(
                struct.pack(f"<{plen}i", *head)).hexdigest()
            for i in sorted(self._prefix_dir):
                if i in exclude:
                    continue
                r = self.replicas.get(i)
                if r is None or not self._routable(r):
                    continue
                if digest in self._prefix_dir[i]:
                    return r.url
        return None

    def _compose(self, t_route0: float, trace_id: str, leg: dict,
                 spans_pre: dict, kv_s: float, kv_bytes: int, *,
                 replica: int, prefill_replica: int, retries: int,
                 local_fallback: bool = False,
                 pre_latency: float = 0.0):
        """Merge the two legs into one client payload. TTFT is
        CONSTRUCTED as queue + prefill + kv_transfer — the span-sum
        identity the e2e pins — and the decode leg's whole post-queue
        time folds into ``decode_s`` (its own internal pre-first-chunk
        wait is stream-side latency, not time-to-first-token: the
        first token already exists when the leg starts)."""
        spans_leg = leg.get("spans") or {}
        if local_fallback:
            # the prefill worker served BOTH halves: its spans already
            # combine the legs — don't double-count the queue term
            eq = float(spans_leg.get("engine_queue_s") or 0.0)
            pf = float(spans_leg.get("prefill_s") or 0.0)
            dc = float(spans_leg.get("decode_s") or 0.0)
        else:
            eq = (float(spans_pre.get("engine_queue_s") or 0.0)
                  + float(spans_leg.get("engine_queue_s") or 0.0))
            pf = float(spans_pre.get("prefill_s") or 0.0)
            dc = (float(spans_leg.get("prefill_s") or 0.0)
                  + float(spans_leg.get("decode_s") or 0.0))
        ttft = eq + pf + kv_s
        # BOTH legs' engine wall comes out of the router_s derivation
        # (pre_latency is 0 for local fallback, whose single leg
        # already covers everything) — subtracting only the decode
        # leg reported the whole prefill+push wall as router overhead
        engine_latency = float(leg.get("latency_s") or 0.0) \
            + float(pre_latency)
        router_s = max(
            0.0, time.perf_counter() - t_route0 - engine_latency)
        itl = float(leg.get("itl_ms") or 0.0)
        spans = {
            "engine_queue_s": round(eq, 4),
            "prefill_s": round(pf, 4),
            "kv_transfer_s": round(kv_s, 4),
            "decode_s": round(dc, 4),
            "router_s": round(router_s, 4),
        }
        with self._lock:
            self.routed_total += 1
            self._slo.append((ttft, itl))
            self._spans.append(dict(spans))
        payload = {
            "tokens": leg.get("tokens"),
            "latency_s": round(time.perf_counter() - t_route0, 4),
            "ttft_s": round(ttft, 4),
            "itl_ms": round(itl, 3),
            "trace_id": leg.get("trace_id") or trace_id,
            "replica": replica,
            "prefill_replica": prefill_replica,
            "retries": retries,
            "kv_bytes": kv_bytes,
            "spans": spans,
        }
        if local_fallback:
            payload["local_fallback"] = True
        return 200, payload, None

    def _pick_excluding(self, prompt, tried: set):
        if not tried:
            return self.pick_replica(prompt)
        return self._pick_any(tried)

    def _pick_any(self, tried: set):
        with self._lock:
            ready = [r for r in self.replicas.values()
                     if self._routable(r) and r.index not in tried]
            if not ready:
                return None, "none"
            best = min(ready, key=lambda r: (r.load(), r.index))
            return best.index, "none"

    def _note_retry(self, idx: int) -> None:
        with self._lock:
            self.retries += 1
        metrics.ROUTER_RETRIES.inc({"replica": str(idx)})

    # ------------------------------------------------------------ stats

    def slo_snapshot(self) -> dict:
        with self._lock:
            samples = list(self._slo)
        ttft = [s[0] for s in samples]
        itl = [s[1] for s in samples]
        return {
            "window": len(samples),
            "ttft_p50_ms": round(1e3 * _pct(ttft, 0.5), 3),
            "ttft_p95_ms": round(1e3 * _pct(ttft, 0.95), 3),
            "itl_p50_ms": round(_pct(itl, 0.5), 3),
            "itl_p95_ms": round(_pct(itl, 0.95), 3),
        }

    def trace_snapshot(self) -> dict:
        """Aggregate request-path decomposition over the sliding
        window: where TTFT goes, fleet-wide — router overhead vs
        engine queue vs prefill (docs/OBSERVABILITY.md)."""
        with self._lock:
            samples = list(self._spans)
        out: dict = {"window": len(samples)}
        keys = ["router_s", "engine_queue_s", "prefill_s", "decode_s"]
        if self.disaggregated:
            # the new leg sits between prefill and decode — measured,
            # not guessed (p50/p95 + bytes below in healthz "kv")
            keys.insert(3, "kv_transfer_s")
        for key in keys:
            xs = [s[key] for s in samples if key in s]
            out[f"{key[:-2]}_p50_ms"] = round(1e3 * _pct(xs, 0.5), 3)
            out[f"{key[:-2]}_p95_ms"] = round(1e3 * _pct(xs, 0.95), 3)
        return out

    def healthz(self) -> dict:
        with self._lock:
            replicas = {
                str(r.index): {
                    "url": r.url,
                    "state": r.state,
                    "load": round(r.load(), 3),
                    "routed": r.routed,
                    "failures": r.failures,
                }
                for r in self.replicas.values()
            }
            ready = sum(1 for r in self.replicas.values()
                        if r.state == READY)
            affinity = {
                "prefix_tokens": self.prefix_tokens,
                "size": len(self._affinity),
                "hits": self.affinity_hits,
                "misses": self.affinity_misses,
                "fallbacks": self.affinity_fallbacks,
            }
            counters = {
                "routed": self.routed_total,
                "retries": self.retries,
                "rejected": self.rejected,
            }
            disagg = None
            if self.disaggregated:
                disagg = {
                    "roles": {str(i): r
                              for i, r in sorted(self.roles.items())},
                    "prefill_ready": sum(
                        1 for r in self.replicas.values()
                        if r.state == READY
                        and self.roles.get(r.index) == "prefill"),
                    "decode_ready": sum(
                        1 for r in self.replicas.values()
                        if r.state == READY
                        and self.roles.get(r.index) == "decode"),
                    "kv": {
                        "transfers": self.kv_transfers,
                        "fallbacks": self.kv_fallbacks,
                        "bytes_total": self.kv_bytes,
                    },
                }
            migration = None
            if self.migration:
                migration = {
                    "migrations": dict(self.migrations),
                    "fallbacks": self.migration_fallbacks,
                    "inflight": len(self._mig_inflight),
                    "mirrors": len(self._mig_mirrors),
                    # which decode replicas currently have a mirrored
                    # stream: the chaos/e2e harness picks its SIGKILL
                    # victim from here so a kill deterministically
                    # exercises the reactive rung
                    "mirrored_sources": sorted(
                        {int(m["source"])
                         for m in self._mig_mirrors.values()}),
                    "prefix_replicas": {
                        str(i): len(ks)
                        for i, ks in sorted(self._prefix_dir.items())
                        if ks},
                }
            draining = self._draining
        return {
            "ok": not draining and ready > 0,
            "draining": draining,
            "ready_replicas": ready,
            "replicas": replicas,
            "affinity": affinity,
            # only present in disaggregated mode: the no-disagg healthz
            # stays byte-identical (the regression guard)
            **({"disaggregation": disagg} if disagg else {}),
            # same guard for migration-off fleets
            **({"migration": migration} if migration is not None
               else {}),
            "slo": self.slo_snapshot(),
            "trace": self.trace_snapshot(),
            **counters,
        }

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "Router":
        self._http_thread.start()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, daemon=True, name="router-poller")
        self._poll_thread.start()
        if self.migration:
            self._mirror_thread = threading.Thread(
                target=self._mirror_loop, daemon=True,
                name="router-mirror")
            self._mirror_thread.start()
        return self

    def drain(self) -> None:
        """Stop intake and the poller; idempotent."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5)
        if self._mirror_thread is not None:
            self._mirror_thread.join(timeout=5)

    # alias used by tests/harnesses
    close = drain
