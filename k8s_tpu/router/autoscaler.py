"""SLO autoscaler: scales the serving-replica count on router-observed
TTFT/ITL percentiles.

ParvaGPU (PAPERS.md) is the reference shape — SLO-driven capacity for
large-scale inference. Here the signal is the router's aggregated
``slo`` block (``/healthz``), the actuator is the reconciler mutating
the WORKER replica count within ``[minReplicas, maxReplicas]``
(``k8s_tpu/trainer/training.py``), and this module is the pure DECISION
function between them — fully deterministic under an injected clock, so
tier-1 pins the hysteresis behavior with zero wall-clock sleeps.

Flap damping, two independent mechanisms:

- **Streak hysteresis.** A scale-up needs ``breach_ticks`` CONSECUTIVE
  observations over the SLO; a scale-down needs ``clear_ticks``
  consecutive observations under ``scale_down_margin * SLO``. The band
  between the two thresholds is dead: streaks reset, nothing moves —
  a p95 oscillating around the SLO boundary cannot flap the fleet.
- **Backoff hold-off.** Every scale event arms the PR-1 ``Backoff``
  (the same policy object every retry site uses): further scale events
  are held until the delay elapses, and consecutive events escalate
  the hold geometrically. A long stable period (``reset_after``)
  earns back a fast first reaction.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple

from k8s_tpu.robustness.backoff import Backoff, BackoffPolicy

# deterministic by default (jitter would desync the tier-1 fixtures;
# one autoscaler per job means there is no thundering herd to break up)
DEFAULT_HOLD = BackoffPolicy(
    base=30.0, factor=2.0, cap=600.0, jitter=0.0, reset_after=900.0)


class SloAutoscaler:
    """Decide the desired replica count from one SLO observation.

    Call :meth:`observe` once per reconcile tick with the current
    replica count and the router's ``slo`` block; it returns
    ``(desired, reason)`` — ``desired == current`` means hold.
    """

    def __init__(
        self,
        min_replicas: int,
        max_replicas: int,
        *,
        slo_ttft_ms: float = 0.0,
        slo_itl_ms: float = 0.0,
        breach_ticks: int = 2,
        clear_ticks: int = 4,
        scale_down_margin: float = 0.5,
        hold_policy: Optional[BackoffPolicy] = None,
        seed: Optional[int] = 0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"[{min_replicas}, {max_replicas}]")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.slo_ttft_ms = float(slo_ttft_ms)
        self.slo_itl_ms = float(slo_itl_ms)
        self.breach_ticks = max(1, int(breach_ticks))
        self.clear_ticks = max(1, int(clear_ticks))
        self.scale_down_margin = float(scale_down_margin)
        self._hold = Backoff(hold_policy or DEFAULT_HOLD,
                             seed=seed, clock=clock)
        self._breach_streak = 0
        self._clear_streak = 0
        self.scale_events = 0

    @property
    def enabled(self) -> bool:
        """Autoscaling is live iff an SLO is set AND there is a range
        to move in."""
        return (self.slo_ttft_ms > 0 or self.slo_itl_ms > 0) \
            and self.max_replicas > self.min_replicas

    def _classify(self, slo: dict) -> str:
        """One observation → breach / clear / neutral / no-data."""
        if not slo or not slo.get("window"):
            return "no-data"
        ttft = float(slo.get("ttft_p95_ms") or 0.0)
        itl = float(slo.get("itl_p95_ms") or 0.0)
        breach = (self.slo_ttft_ms > 0 and ttft > self.slo_ttft_ms) or \
                 (self.slo_itl_ms > 0 and itl > self.slo_itl_ms)
        if breach:
            return "breach"
        clear = True
        if self.slo_ttft_ms > 0 and \
                ttft > self.slo_ttft_ms * self.scale_down_margin:
            clear = False
        if self.slo_itl_ms > 0 and \
                itl > self.slo_itl_ms * self.scale_down_margin:
            clear = False
        return "clear" if clear else "neutral"

    def observe(self, current: int, slo: dict) -> Tuple[int, str]:
        """One tick: returns ``(desired replicas, reason)``."""
        if not self.enabled:
            return current, "autoscale disabled"
        verdict = self._classify(slo)
        if verdict == "breach":
            self._breach_streak += 1
            self._clear_streak = 0
        elif verdict == "clear":
            self._clear_streak += 1
            self._breach_streak = 0
        else:
            # neutral band / no data: both streaks reset — this is the
            # hysteresis dead zone that kills boundary flap
            self._breach_streak = 0
            self._clear_streak = 0
            return current, verdict
        hold = self._hold.remaining()
        if self._breach_streak >= self.breach_ticks:
            if current >= self.max_replicas:
                return current, "breach at maxReplicas"
            if hold > 0:
                return current, f"breach held {hold:.1f}s by backoff"
            self._scale_event()
            return current + 1, (
                f"p95 over SLO for {self.breach_ticks} ticks")
        if self._clear_streak >= self.clear_ticks:
            if current <= self.min_replicas:
                return current, "clear at minReplicas"
            if hold > 0:
                return current, f"scale-down held {hold:.1f}s by backoff"
            self._scale_event()
            return current - 1, (
                f"p95 under {self.scale_down_margin:g}x SLO for "
                f"{self.clear_ticks} ticks")
        return current, verdict

    def _scale_event(self) -> None:
        self.scale_events += 1
        self._hold.note_failure()  # arms the hold-off for the NEXT event
        self._breach_streak = 0
        self._clear_streak = 0
