"""Serving fleet: prefix-aware router + SLO autoscaler (ROADMAP item 1).

The subsystem that composes the pieces the repo already had — per-index
Services with stable DNS, ``/healthz`` engine stats, chunked-prefill
working caches — into an autoscaled multi-replica serving fleet:

- :class:`Router` — the HTTP front door: live stats polling, prefix-
  affinity + least-load scoring, retry-on-peer (``router.py``);
- :class:`SloAutoscaler` — the reconciler-side scaling decision against
  TTFT/ITL SLOs, Backoff-damped (``autoscaler.py``);
- :class:`LocalFleet` / :class:`StandinEngine` — the in-process harness
  behind ``serving_bench --fleet``, the router tests, and the
  ``router-*`` chaos faults (``fleet.py``).

Operator wiring lives in ``spec.serving`` (``spec/tpu_job.py``) and
``trainer/replicas.py``; the deployable entrypoint is
``programs/router.py``. docs/SERVING.md "Fleet" is the user story.
"""

from k8s_tpu.router.autoscaler import SloAutoscaler  # noqa: F401
from k8s_tpu.router.fleet import LocalFleet, StandinEngine  # noqa: F401
from k8s_tpu.router.router import (  # noqa: F401
    Replica,
    Router,
    parse_peers,
    parse_roles,
    prefix_key,
)
