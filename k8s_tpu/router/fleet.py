"""In-process serving fleet harness: N replicas behind a Router.

Three consumers, one harness:

- ``serving_bench --fleet N`` measures aggregate throughput and
  TTFT/ITL percentiles through the real router;
- ``tests/test_router.py`` / the ``serving-fleet`` CI stage drive the
  create → route → kill-one → drain sequence;
- the chaos faults ``router-replica-loss`` / ``router-stats-flake``
  (``k8s_tpu/runtime/chaos.py``) operate on it.

Each replica is a real :class:`~k8s_tpu.serving.server.ServingFrontend`
(real HTTP, real backpressure, real drain semantics) over either a real
:class:`~k8s_tpu.serving.engine.ContinuousBatchingEngine` or a
:class:`StandinEngine`. The stand-in keeps the engine's *scheduling*
contract — slots, admission queue, chunked decode cadence, stats block,
deterministic tokens — but replaces device compute with a calibrated
per-round wall. That is the same modeled-baseline methodology the
serving bench already uses for its static server: on a shared-CPU CI
box a single REAL engine saturates the whole machine, so only paced
stand-ins can honestly show what routing N chip-bound replicas buys
(each real chip would pace itself; the stand-in's ``round_wall_s`` is
that pace made explicit).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from k8s_tpu.router.router import Router
from k8s_tpu.serving.server import ServingFrontend


class _Req:
    """Request bookkeeping mirroring the engine's ``Request`` fields
    that the front-end reads at resolution time."""

    def __init__(self, rid, prompt, max_new):
        self.rid = rid
        self.prompt = prompt
        self.max_new = int(max_new)
        self.tokens: List[int] = []
        self.done = False
        self.submitted_at = time.perf_counter()
        self.first_token_at = 0.0
        self.finished_at = 0.0
        self.prefill_start_at = 0.0
        self.prefill_remaining = int(len(prompt))
        self.token_times: List = []
        # disaggregation mirror of the real engine's Request fields
        self.prefill_only = False
        self.kv_result = None


class StandinEngine:
    """Engine-interface stand-in with a virtual chip roofline.

    One :meth:`step` is one pump round: admit queued requests into free
    slots, spend ``round_wall_s`` of wall clock (the modeled compute),
    advance every active slot by up to ``decode_chunk`` tokens — after
    its prompt's prefill chunks are paid down at ``prefill_chunk``
    tokens per round. Tokens are a deterministic function of the prompt
    alone, so a retried request served by a PEER stand-in returns the
    identical stream (the router retry oracle)."""

    def __init__(self, *, max_slots: int = 2, decode_chunk: int = 8,
                 round_wall_s: float = 0.01, prefill_chunk: int = 32,
                 vocab: int = 4093, prefill_wall_factor: float = 0.0,
                 kv_bytes_per_token: int = 256):
        self.max_slots = int(max_slots)
        self.decode_chunk = int(decode_chunk)
        self.round_wall_s = float(round_wall_s)
        self.prefill_chunk = int(prefill_chunk)
        self.chunked_prefill = True
        self.max_tokens_per_round = (
            self.prefill_chunk + self.max_slots * self.decode_chunk)
        self.vocab = int(vocab)
        # prefill interference model (the disagg A/B's honest knob):
        # each prefill chunk paid in a round stretches the round wall
        # by this factor — the real engine's token budget in wall-clock
        # form, so a long-prompt mix visibly stalls co-resident decode
        # rows exactly the way phase-splitting removes. 0 = off (the
        # pre-disagg pacing, which the fleet bench calibrated against).
        self.prefill_wall_factor = float(prefill_wall_factor)
        # modeled KV handoff size (bytes per prompt token): what the
        # stand-in ships on /v1/prefill so the wire, crc framing and
        # bytes accounting are real even when the cache is fake
        self.kv_bytes_per_token = int(kv_bytes_per_token)
        self._lock = threading.Lock()
        self._queue: List[_Req] = []
        self._slots: List[Optional[_Req]] = [None] * self.max_slots
        self._done: Dict[int, _Req] = {}
        self._rid = itertools.count()
        self._closed = False
        self.stats = {"prefills": 0, "chunks": 0, "decode_steps": 0,
                      "prefill_chunks": 0, "prefill_tokens": 0,
                      "queue_depth": 0, "ttft_s_sum": 0.0,
                      "ttft_count": 0, "prefix_hits": 0,
                      "prefix_misses": 0, "prefix_captures": 0,
                      "prefix_tokens_saved": 0,
                      "kv_prefills": 0, "kv_admits": 0,
                      "migrations_out": 0, "migrations_in": 0,
                      "slot_mirrors": 0}

    # -- engine surface ---------------------------------------------------

    def submit(self, prompt, max_new_tokens: int) -> int:
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        with self._lock:
            if self._closed:
                raise RuntimeError("engine is closed")
            req = _Req(next(self._rid), prompt, max_new_tokens)
            self._queue.append(req)
        return req.rid

    def submit_prefill(self, prompt, max_new_tokens: int) -> int:
        """Disagg prefill leg, stand-in flavor: pays the prompt's
        prefill rounds, then finishes with a modeled KV payload
        (bytes ∝ prompt tokens) + the deterministic first token. The
        flag is set INSIDE the enqueue critical section — flagging
        after submit() raced the pump, which could admit the request
        into a slot and run it as a full generate (kv_result=None)."""
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        with self._lock:
            if self._closed:
                raise RuntimeError("engine is closed")
            req = _Req(next(self._rid), prompt, max_new_tokens)
            req.prefill_only = True
            self._queue.append(req)
        return req.rid

    def submit_with_kv(self, kv: dict, max_new_tokens: int) -> int:
        """Disagg decode leg: the prompt rides in the KV meta (the
        stand-in's tokens are a deterministic function of it — the
        cross-path determinism oracle), prefill is already paid, and
        the first token is pre-seeded."""
        prompt = np.asarray(kv["prompt"], np.int64).reshape(-1)
        if int(kv["plen"]) != prompt.size:
            raise ValueError("kv seed: plen != prompt length")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        with self._lock:
            if self._closed:
                raise RuntimeError("engine is closed")
            req = _Req(next(self._rid), prompt, max_new_tokens)
            req.prefill_remaining = 0
            if str(kv.get("kind") or "") == "migration":
                # live-migration resume: the stream so far rides in
                # the manifest; the caller's max_new_tokens is
                # budget+1 (the real engine's convention), so the
                # final count lands exactly on the original request's
                # max_new — bit-identical to the unmigrated stream
                # because _token() is a function of (prompt, position)
                tokens = [int(t) for t in (kv.get("tokens") or ())]
                if not tokens or tokens[-1] != int(kv["first_token"]):
                    raise ValueError(
                        "migration kv: tokens must end at first_token")
                req.tokens = tokens
                req.max_new = len(tokens) + max_new_tokens - 1
                self.stats["migrations_in"] += 1
            else:
                req.tokens = [int(kv["first_token"])]
            self.stats["kv_admits"] += 1
            self._queue.append(req)
        return req.rid

    def export_slot(self, request_id: int, *, remove: bool = True,
                    timeout: float = 30.0) -> Optional[dict]:
        """Migration export, stand-in flavor (the real engine's
        contract, minus the device snapshot): pack a SLOTTED request's
        resumable state as a ``kind="migration"`` kv dict admissible
        via :meth:`submit_with_kv`. Returns ``None`` for requests that
        are queued, mid-prefill, finished, or out of budget — the
        caller then lets them finish locally. Thread-safe (the
        stand-in's state lives under one lock — no pump queue needed,
        so ``timeout`` is accepted for signature parity only)."""
        del timeout
        with self._lock:
            if self._closed:
                return None
            req = None
            slot = None
            for i, r in enumerate(self._slots):
                if r is not None and r.rid == request_id:
                    req, slot = r, i
                    break
            if req is None or req.done or req.prefill_remaining > 0 \
                    or not req.tokens:
                return None
            budget = req.max_new - len(req.tokens)
            if budget < 1:
                return None
            plen = int(req.prompt.size)
            kv = {
                "kind": "migration",
                "plen": plen,
                "rows": plen,
                "first_token": int(req.tokens[-1]),
                "prompt": [int(t) for t in req.prompt],
                "tokens": [int(t) for t in req.tokens],
                "max_new_tokens": int(req.max_new),
                "budget": int(budget),
                "leaves": [np.zeros(
                    plen * self.kv_bytes_per_token, np.uint8)],
            }
            if remove:
                self._slots[slot] = None
                self.stats["migrations_out"] += 1
            else:
                self.stats["slot_mirrors"] += 1
            return kv

    def queue_depth(self) -> int:
        return len(self._queue)

    def prefill_progress(self) -> dict:
        out = {}
        for r in self._slots:
            if r is not None and r.prefill_remaining > 0:
                out[r.rid] = {
                    "done": int(len(r.prompt)) - r.prefill_remaining,
                    "total": int(len(r.prompt))}
        return out

    def _token(self, req: _Req, j: int) -> int:
        return int((int(req.prompt.sum()) * 7919 + 31 * j) % self.vocab)

    def step(self) -> bool:
        if self._closed:
            raise RuntimeError("engine is closed")
        with self._lock:
            for i in range(self.max_slots):
                if self._slots[i] is None and self._queue:
                    self._slots[i] = self._queue.pop(0)
                    self._slots[i].prefill_start_at = time.perf_counter()
                    self.stats["prefills"] += 1
            self.stats["queue_depth"] = len(self._queue)
            active = [r for r in self._slots if r is not None]
        if not active:
            return bool(self._queue)
        # the virtual roofline; prefill chunks stretch the round by
        # prefill_wall_factor each (see __init__) — the interference
        # the disagg A/B measures
        n_pref = sum(1 for r in active if r.prefill_remaining > 0)
        time.sleep(self.round_wall_s
                   * (1.0 + self.prefill_wall_factor * n_pref))
        now = time.perf_counter()
        self.stats["chunks"] += 1
        with self._lock:
            for i in range(self.max_slots):
                req = self._slots[i]
                if req is None:
                    continue
                if req.prefill_remaining > 0:
                    paid = min(self.prefill_chunk, req.prefill_remaining)
                    req.prefill_remaining -= paid
                    self.stats["prefill_chunks"] += 1
                    self.stats["prefill_tokens"] += paid
                    if req.prefill_remaining == 0 and req.prefill_only:
                        # prefill leg complete: first token + modeled
                        # KV payload, slot freed — the handoff's
                        # stand-in half
                        tok0 = self._token(req, 0)
                        req.tokens = [tok0]
                        req.kv_result = {
                            "plen": int(req.prompt.size),
                            "rows": int(req.prompt.size),
                            "first_token": tok0,
                            "prompt": [int(t) for t in req.prompt],
                            "leaves": [np.zeros(
                                int(req.prompt.size)
                                * self.kv_bytes_per_token, np.uint8)],
                        }
                        req.first_token_at = now
                        req.token_times.append((now, 1))
                        self.stats["ttft_s_sum"] += \
                            now - req.submitted_at
                        self.stats["ttft_count"] += 1
                        self.stats["prefills"] += 1
                        self.stats["kv_prefills"] += 1
                        req.done = True
                        req.finished_at = now
                        self._done[req.rid] = req
                        self._slots[i] = None
                    continue
                base = len(req.tokens)
                k = min(self.decode_chunk, req.max_new - base)
                req.tokens.extend(
                    [self._token(req, base + j) for j in range(k)])
                self.stats["decode_steps"] += k
                if not req.token_times:
                    req.first_token_at = now
                    self.stats["ttft_s_sum"] += now - req.submitted_at
                    self.stats["ttft_count"] += 1
                req.token_times.append((now, k))
                if len(req.tokens) >= req.max_new:
                    req.done = True
                    req.finished_at = now
                    self._done[req.rid] = req
                    self._slots[i] = None
            busy = bool(self._queue
                        or any(r is not None for r in self._slots))
        return busy

    def pop_finished(self) -> Dict[int, _Req]:
        with self._lock:
            done, self._done = self._done, {}
        return done

    def run(self):
        while self.step():
            pass
        return {rid: np.asarray(r.tokens)
                for rid, r in self.pop_finished().items()}

    def close(self) -> None:
        with self._lock:
            self._closed = True


class LocalFleet:
    """N in-process replicas + router. ``engines`` may be real
    continuous-batching engines or :class:`StandinEngine`\\ s; each gets
    its own ``ServingFrontend`` on an ephemeral loopback port and a
    dedicated pump thread (the engine's single-scheduler contract)."""

    def __init__(self, engines, *, max_queue_depth: int = 0,
                 router_kwargs: Optional[dict] = None,
                 roles: Optional[List[str]] = None,
                 migration: bool = False,
                 mirror_interval: float = 0.25):
        self.engines = list(engines)
        self.roles = list(roles) if roles else []
        self.migration = bool(migration)
        if self.roles and len(self.roles) != len(self.engines):
            raise ValueError("roles must match engines 1:1")
        self.frontends = [
            ServingFrontend(e, host="127.0.0.1", port=0,
                            max_queue_depth=max_queue_depth,
                            role=(self.roles[i] if self.roles else ""),
                            migration=self.migration)
            for i, e in enumerate(self.engines)
        ]
        self._stops = [threading.Event() for _ in self.engines]
        self._pumps: List[threading.Thread] = []
        self._killed: set = set()
        kwargs = dict(router_kwargs or {})
        kwargs.setdefault("poll_interval", 0.2)
        if self.roles:
            kwargs.setdefault(
                "roles", {i: r for i, r in enumerate(self.roles)})
        if self.migration:
            kwargs.setdefault("migration", True)
            kwargs.setdefault("mirror_interval", mirror_interval)
        self.router = Router(
            {i: f"http://127.0.0.1:{fe.port}"
             for i, fe in enumerate(self.frontends)},
            **kwargs)

    # -- lifecycle --------------------------------------------------------

    def _pump(self, i: int) -> None:
        fe, stop = self.frontends[i], self._stops[i]
        try:
            while not stop.is_set():
                busy = fe.engine.step()
                fe._resolve_finished()
                if not busy:
                    fe._work.wait(0.02)
                    fe._work.clear()
        except Exception:
            # a killed engine raises out of step(); the kill path has
            # already released the waiters
            pass

    def start(self, wait_ready: bool = True) -> "LocalFleet":
        for i, fe in enumerate(self.frontends):
            fe._http_thread.start()
            t = threading.Thread(target=self._pump, args=(i,),
                                 daemon=True, name=f"fleet-pump-{i}")
            t.start()
            self._pumps.append(t)
        self.router.start()
        if wait_ready:
            self.router._poll_once()  # all replicas READY before use
        return self

    def stop(self) -> None:
        self.router.drain()
        for i, fe in enumerate(self.frontends):
            self._stops[i].set()
        for t in self._pumps:
            t.join(timeout=10)
        for i, fe in enumerate(self.frontends):
            if i in self._killed:
                continue
            try:
                fe.drain()
            except Exception:
                pass

    # -- fault surface (chaos + tests) ------------------------------------

    def alive(self) -> List[int]:
        return [i for i in range(len(self.frontends))
                if i not in self._killed]

    def kill_replica(self, i: int) -> None:
        """Crash replica ``i`` abruptly: stop its pump mid-flight,
        close its listener, and fail its parked requests — in-flight
        forwards then see a replica-side error and the router must
        retry them on a peer."""
        if i in self._killed:
            return
        self._killed.add(i)
        fe = self.frontends[i]
        self._stops[i].set()
        fe._server.shutdown()
        fe._server.server_close()
        with fe._lock:
            fe._draining = True
            for rid, ev in list(fe._waiters.items()):
                fe._results[rid] = RuntimeError("chaos: replica killed")
                ev.set()
            fe._waiters.clear()
        try:
            fe.engine.close()
        except Exception:
            pass

    def kill_random_replica(self, rng) -> Optional[int]:
        """Kill one randomly chosen live replica, always leaving at
        least one standing (an empty fleet is the separate
        total-outage scenario)."""
        alive = self.alive()
        if len(alive) <= 1:
            return None
        victim = alive[rng.randrange(len(alive))]
        self.kill_replica(victim)
        return victim

    def kill_random_decode_replica(self, rng) -> Optional[int]:
        """Chaos ``kv-transfer-loss``: kill one live DECODE-pool
        replica (the KV handoff's target side), always leaving at
        least one replica of ANY role standing — the fallback ladder
        needs somewhere to land. Killing the LAST decode replica is
        allowed (and interesting): it forces the interleave-fallback
        rung. No-op on non-disaggregated fleets."""
        if not self.roles:
            return None
        alive = self.alive()
        decode_alive = [i for i in alive if self.roles[i] == "decode"]
        if not decode_alive or len(alive) <= 1:
            return None
        victim = decode_alive[rng.randrange(len(decode_alive))]
        self.kill_replica(victim)
        return victim

    def kill_migration_target(self, rng) -> Optional[int]:
        """Chaos ``decode-migration-loss``: kill a replica currently
        holding a mirrored slot — the migration TARGET, mid-transfer
        from the request's point of view. The next reactive resume
        against it fails and the source request must fall through to
        the next ladder rung: never lost, never double-decoded. No-op
        when no mirror has landed yet or when killing would leave
        nothing standing."""
        router = self.router
        if not getattr(router, "migration", False):
            return None
        with router._lock:
            targets = sorted({int(m["target"])
                              for m in router._mig_mirrors.values()})
        targets = [t for t in targets if t not in self._killed]
        if not targets or len(self.alive()) <= 1:
            return None
        victim = targets[rng.randrange(len(targets))]
        self.kill_replica(victim)
        return victim

    def flake_stats(self, i: int, n: int = 3) -> None:
        self.frontends[i].arm_healthz_faults(n)

    def flake_random_stats(self, rng, n: int = 3) -> Optional[int]:
        alive = self.alive()
        if not alive:
            return None
        victim = alive[rng.randrange(len(alive))]
        self.flake_stats(victim, n)
        return victim

    # -- client helper ----------------------------------------------------

    def generate(self, prompt, max_new_tokens: int, timeout: float = 120.0):
        """POST one request through the router; returns
        ``(status, payload dict)``."""
        import json
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{self.router.port}/v1/generate",
            data=json.dumps({
                "prompt": [int(t) for t in prompt],
                "max_new_tokens": int(max_new_tokens)}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read())
            except Exception:
                return e.code, {"error": str(e)}
