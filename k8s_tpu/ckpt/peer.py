"""Peer-shard transport: how a restarted host reads OTHER hosts' local
tiers.

A replaced pod (fresh node after preemption) has an empty local disk;
under data-parallel replication its shards still exist byte-identical
on peers' local tiers (same global index ⇒ same content — the SPMD
invariant :mod:`k8s_tpu.ckpt.local` keys shard files by). The restore
planner sources missing shards through one of two transports:

- :class:`FilesystemPeerTransport` — peers' ``host-*`` dirs reachable
  on a shared filesystem. The local-harness/e2e path: the kubelet
  simulator's "node-local" disks are sibling dirs of one tmp root. Also
  the right transport for real deployments that mount a fast shared
  scratch tier.
- :class:`RestPeerTransport` + :class:`PeerShardServer` — the
  production-shaped wire: every host serves its local tier over the
  same HTTP/JSON(+bytes) stack the control plane already speaks
  (:mod:`k8s_tpu.api.apiserver` idiom; ``metav1.Status``-style error
  bodies, stdlib client with per-thread kept-alive connections — one
  TCP setup per peer per restore worker, not per shard), and
  restarted pods fetch from the
  per-index Service DNS names the operator already maintains
  (``KTPU_CKPT_PEERS`` env, injected by
  :meth:`k8s_tpu.trainer.replicas.TpuReplicaSet.rendezvous`).

Both expose the same three calls — ``steps()``, ``manifest(step)``,
``fetch(step, leaf, key)`` — and both report per-peer failures as
*misses*, never exceptions: a dead peer must degrade the restore to the
persistent tier, not wedge it.
"""

from __future__ import annotations

import http.client
import io
import json
import logging
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from k8s_tpu.ckpt.local import LocalTier

log = logging.getLogger(__name__)

DEFAULT_TIMEOUT = 10.0


class FilesystemPeerTransport:
    """Read peers' local tiers straight off a shared filesystem root."""

    def __init__(self, root: str, self_host: int):
        self._tier = LocalTier(root, host_id=self_host)
        self.self_host = self_host

    def peers(self) -> List[int]:
        import os

        try:
            names = os.listdir(self._tier.root)
        except OSError:
            return []
        out = []
        for n in names:
            if n.startswith("host-"):
                try:
                    hid = int(n[len("host-"):])
                except ValueError:
                    continue
                if hid != self.self_host:
                    out.append(hid)
        return sorted(out)

    def steps(self) -> Dict[int, List[int]]:
        """Committed steps per peer host."""
        return {h: self._tier.committed_steps(host_id=h) for h in self.peers()}

    def progress(self) -> int:
        """Max recorded train progress across peers (see
        LocalTier.note_progress) — -1 when nobody recorded any."""
        import os

        best = -1
        for h in self.peers():
            hdir = os.path.join(self._tier.root, f"host-{h}")
            try:
                with open(os.path.join(hdir, "progress.json")) as f:
                    best = max(best, int(json.load(f)["step"]))
            except (OSError, ValueError, KeyError):
                continue
        return best

    def manifest(self, step: int, host: int) -> Optional[dict]:
        return self._tier.manifest(step, host_id=host)

    def fetch(self, step: int, leaf: str, key: str,
              host: int) -> Optional[np.ndarray]:
        return self._tier.read_shard(step, leaf, key, host_id=host)


# ---------------------------------------------------------------------------
# REST wire
# ---------------------------------------------------------------------------


class _ShardHandler(BaseHTTPRequestHandler):
    server: "_ShardServer"

    # keep-alive: a parallel restore fetches hundreds of shards from
    # the same few peers — HTTP/1.1 persistent connections turn that
    # into one TCP setup per (peer, client thread) instead of one per
    # shard. Every response already carries Content-Length, which is
    # what makes 1.1 keep-alive legal here.
    protocol_version = "HTTP/1.1"

    def do_GET(self):  # noqa: N802 (http.server API)
        tier: LocalTier = self.server.tier
        parsed = urllib.parse.urlsplit(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        try:
            # /v1/ckpt/steps
            if parts == ["v1", "ckpt", "steps"]:
                return self._json(200, {
                    "host": tier.host_id,
                    "steps": tier.committed_steps(),
                    "progress": tier.progress(),
                })
            # /v1/ckpt/manifest/<step>
            if parts[:3] == ["v1", "ckpt", "manifest"] and len(parts) == 4:
                man = tier.manifest(int(parts[3]))
                if man is None:
                    return self._status(404, "NotFound",
                                        f"step {parts[3]} not committed")
                return self._json(200, man)
            # /v1/ckpt/shard/<step>?leaf=<path>&key=<index>
            if parts[:3] == ["v1", "ckpt", "shard"] and len(parts) == 4:
                q = {k: v[0] for k, v in
                     urllib.parse.parse_qs(parsed.query).items()}
                arr = tier.read_shard(int(parts[3]), q.get("leaf", ""),
                                      q.get("key", ""))
                if arr is None:
                    return self._status(
                        404, "NotFound",
                        f"shard {q.get('leaf')}[{q.get('key')}] "
                        f"@ step {parts[3]} missing or corrupt")
                buf = io.BytesIO()
                np.save(buf, arr)
                body = buf.getvalue()
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            return self._status(404, "NotFound", f"no route {parsed.path}")
        except BrokenPipeError:
            pass
        except Exception as e:  # a bad request must not kill the server
            # headers/partial body may already be on the wire: a 500
            # appended behind them would desynchronize a kept-alive
            # client — close this connection instead of reusing it
            self.close_connection = True
            try:
                self._status(500, "InternalError", str(e))
            except Exception:
                pass

    def _json(self, code: int, obj) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _status(self, code: int, reason: str, message: str) -> None:
        # metav1.Status-shaped failure body — same vocabulary as the
        # local apiserver (api/wire.py:status_body)
        self._json(code, {
            "kind": "Status", "apiVersion": "v1", "status": "Failure",
            "reason": reason, "message": message, "code": code,
        })

    def log_message(self, fmt, *args):
        log.debug("peer-shard: " + fmt, *args)


class _ShardServer(ThreadingHTTPServer):
    daemon_threads = True
    tier: LocalTier


class PeerShardServer:
    """Serves one host's local tier over HTTP. ``port=0`` binds an
    ephemeral port (tests); the bound port is :attr:`port` after
    :meth:`start`."""

    def __init__(self, tier: LocalTier, port: int = 0,
                 host: str = "0.0.0.0"):
        self.tier = tier
        self._server = _ShardServer((host, port), _ShardHandler)
        self._server.tier = tier
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "PeerShardServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"ckpt-peer-{self.tier.host_id}",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


class RestPeerTransport:
    """Fetch peers' shards over the REST wire. ``endpoints`` maps host
    id -> base URL (from ``KTPU_CKPT_PEERS``:
    ``"0=http://svc-0:port,1=http://svc-1:port"``). Every failure is a
    miss; a peer that errors is skipped until the next :meth:`reset`
    (one timeout per dead peer per restore, not one per shard — the
    planner resets at the top of every plan).

    Connections are **kept alive** per (peer, calling thread): a
    parallel restore pulls hundreds of shards from the same few peers,
    and a fresh TCP connection per shard is both slow (handshake per
    fetch) and a SYN-backlog hazard under fan-out (the PR 13 lesson).
    Thread-local pooling makes the transport safe under the restore
    pipeline's worker pool with zero locking on the hot path; error
    bodies are always drained so a 404 miss never poisons the reused
    socket. A stale kept-alive socket (peer restarted, idle close) is
    retried ONCE on a fresh connection before the peer is declared
    dead — refused connections and timeouts fail immediately as
    before. Sockets die with their threads (the pool is per-restore)."""

    # stale-socket error classes worth one fresh-connection retry; a
    # refused connect or a timeout means the peer itself is the problem
    _RETRY_ERRORS = (http.client.BadStatusLine,
                     http.client.CannotSendRequest,
                     http.client.ResponseNotReady,
                     ConnectionResetError, BrokenPipeError)

    def __init__(self, endpoints: Dict[int, str], self_host: int,
                 timeout: float = DEFAULT_TIMEOUT):
        self.endpoints = {
            int(h): u.rstrip("/") for h, u in endpoints.items()
            if int(h) != self_host
        }
        self.self_host = self_host
        self.timeout = timeout
        self._dead: set = set()
        self._local = threading.local()  # per-thread {host: connection}
        self.reused_connections = 0  # requests served over a kept socket
        self._reused_lock = threading.Lock()  # counted from pool workers

    def reset(self) -> None:
        """Forget blacklisted peers (a recovered peer must be reachable
        again on the next restore)."""
        self._dead.clear()

    # -------------------------------------------------- connection pool

    def _conns(self) -> Dict[int, http.client.HTTPConnection]:
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = self._local.conns = {}
        return conns

    def _conn(self, host: int) -> Optional[http.client.HTTPConnection]:
        conns = self._conns()
        c = conns.get(host)
        if c is None:
            parsed = urllib.parse.urlsplit(self.endpoints[host])
            if not parsed.hostname:
                return None
            if parsed.scheme == "https":
                c = http.client.HTTPSConnection(
                    parsed.hostname, parsed.port, timeout=self.timeout)
            else:
                c = http.client.HTTPConnection(
                    parsed.hostname, parsed.port or 80,
                    timeout=self.timeout)
            conns[host] = c
        return c

    def _base_path(self, host: int) -> str:
        """Any path prefix baked into the endpoint URL (a peer behind
        a routing proxy) — prepended to every request path, as the old
        urlopen(url + path) client did."""
        return urllib.parse.urlsplit(self.endpoints[host]).path

    def _drop_conn(self, host: int) -> None:
        c = self._conns().pop(host, None)
        if c is not None:
            try:
                c.close()
            except Exception:
                pass

    @classmethod
    def from_env_value(cls, raw: str, self_host: int,
                       timeout: float = DEFAULT_TIMEOUT
                       ) -> "RestPeerTransport":
        eps: Dict[int, str] = {}
        for part in (raw or "").split(","):
            part = part.strip()
            if not part or "=" not in part:
                continue
            hid, _, url = part.partition("=")
            try:
                eps[int(hid)] = url
            except ValueError:
                continue
        return cls(eps, self_host, timeout=timeout)

    def _get(self, host: int, path: str) -> Optional[bytes]:
        if host in self._dead or host not in self.endpoints:
            return None
        for attempt in (0, 1):
            conn = self._conn(host)
            if conn is None:
                return None
            reused = conn.sock is not None
            try:
                conn.request("GET", self._base_path(host) + path)
                resp = conn.getresponse()
                # ALWAYS drain the body — an unread error body on a
                # kept-alive socket would desynchronize every later
                # request on it
                body = resp.read()
                if reused:
                    with self._reused_lock:
                        self.reused_connections += 1
                if resp.status == 200:
                    return body
                if resp.status == 404:
                    return None  # an honest miss, peer is alive
                self._dead.add(host)
                self._drop_conn(host)
                return None
            except Exception as e:
                self._drop_conn(host)
                if attempt == 0 and reused \
                        and isinstance(e, self._RETRY_ERRORS):
                    continue  # stale kept-alive socket: one fresh retry
                log.warning("peer-shard host %d unreachable (%s); "
                            "skipping for this restore", host, e)
                self._dead.add(host)
                return None
        return None

    def peers(self) -> List[int]:
        return sorted(self.endpoints)

    def steps(self) -> Dict[int, List[int]]:
        out: Dict[int, List[int]] = {}
        for h in self.peers():
            raw = self._get(h, "/v1/ckpt/steps")
            if raw is None:
                continue
            try:
                out[h] = list(json.loads(raw)["steps"])
            except (ValueError, KeyError):
                continue
        return out

    def progress(self) -> int:
        best = -1
        for h in self.peers():
            raw = self._get(h, "/v1/ckpt/steps")
            if raw is None:
                continue
            try:
                best = max(best, int(json.loads(raw).get("progress", -1)))
            except ValueError:
                continue
        return best

    def manifest(self, step: int, host: int) -> Optional[dict]:
        raw = self._get(host, f"/v1/ckpt/manifest/{step}")
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def fetch(self, step: int, leaf: str, key: str,
              host: int) -> Optional[np.ndarray]:
        q = urllib.parse.urlencode({"leaf": leaf, "key": key})
        raw = self._get(host, f"/v1/ckpt/shard/{step}?{q}")
        if raw is None:
            return None
        try:
            return np.load(io.BytesIO(raw))
        except (ValueError, OSError):
            return None
