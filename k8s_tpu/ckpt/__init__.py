"""Multi-tier emergency checkpointing (docs/CHECKPOINT.md).

Local tier (frequent, node-local, two-phase-committed sharded
snapshots) + the persistent orbax tier (rare durable saves) behind one
manager, with a restore planner that picks the newest consistent step
and sources a replaced pod's shards from data-parallel peers before
paying a full persistent-store restore.
"""

from k8s_tpu.ckpt.local import (  # noqa: F401
    LocalTier,
    arm_partial_commit,
    compose_shard,
    covering_plan,
    index_key,
    local_shards_of,
    parse_index_key,
    shard_copy_jobs,
    union_covering_plan,
)
from k8s_tpu.ckpt.pipeline import (  # noqa: F401
    InflightGate,
    crc32_array,
    stage_tree,
)
from k8s_tpu.ckpt.peer import (  # noqa: F401
    FilesystemPeerTransport,
    PeerShardServer,
    RestPeerTransport,
)
from k8s_tpu.ckpt.planner import (  # noqa: F401
    SOURCE_LOCAL,
    SOURCE_LOCAL_PEER,
    SOURCE_NONE,
    SOURCE_PERSISTENT,
    RestorePlan,
    RestorePlanner,
)
from k8s_tpu.ckpt.manager import (  # noqa: F401
    CheckpointPolicy,
    GoodputStats,
    MultiTierCheckpointManager,
)
