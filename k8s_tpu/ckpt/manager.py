"""Multi-tier checkpoint manager + goodput accounting.

One object with the same surface the training programs already use
(``save / restore / wait / close / reached_preemption / latest_step``),
composing:

- the **local tier** (:mod:`k8s_tpu.ckpt.local`): cheap per-host
  snapshots every ``local_interval`` steps;
- the **persistent tier** (the existing orbax
  :class:`k8s_tpu.train.checkpoint.CheckpointManager`), demoted to
  low-frequency durable saves every ``persistent_interval`` steps;
- the **restore planner** (:mod:`k8s_tpu.ckpt.planner`): newest
  consistent step across tiers, peer-shard sourcing for replaced pods.

Goodput accounting rides along: every save is timed against loop
wall-clock (checkpoint overhead fraction), every restore records its
source tier and the steps lost since the last recorded progress
(lost-steps-per-restart). The numbers are exported three ways —
``goodput()`` (the ``engine.stats`` analogue), JSON event lines on
stdout (the harness/e2e contract), and the process-global metrics
registry (:mod:`k8s_tpu.controller.metrics`, served by any /metrics
endpoint).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from k8s_tpu.ckpt.local import LocalTier
from k8s_tpu.ckpt.peer import FilesystemPeerTransport, RestPeerTransport
from k8s_tpu.ckpt.planner import (
    SOURCE_NONE,
    RestorePlan,
    RestorePlanner,
)

log = logging.getLogger(__name__)


@dataclass
class CheckpointPolicy:
    """Resolved checkpointPolicy (spec block → env → here). Zero
    intervals disable a tier."""

    local_dir: str = ""
    local_interval_steps: int = 0
    local_max_to_keep: int = 2
    persistent_dir: str = ""
    persistent_interval_steps: int = 0
    peer_fetch: bool = True
    # restore ceiling ("last healthy step"): after a TrainingDiverged
    # verdict the operator injects KTPU_CKPT_RESTORE_MAX_STEP on the
    # restarted gang so planning never targets a NaN checkpoint
    # (docs/OBSERVABILITY.md "Training health", docs/CHECKPOINT.md)
    max_restore_step: Optional[int] = None
    # restore pipeline (docs/CHECKPOINT.md "Restore critical path"):
    # fetch-pool width (1 = the serial schedule, byte-identical either
    # way) and the in-flight host-bytes cap on fetched shard buffers
    restore_parallel: int = 8
    restore_inflight_mb: int = 1024
    # save pipeline (docs/CHECKPOINT.md "Save critical path"):
    # snapshot-pool width (1 = serial device→host copies, byte-
    # identical committed output either way) and the cap on host bytes
    # staged between the snapshot and the background writer
    save_concurrency: int = 8
    save_buffer_bytes: int = 1 << 30

    @classmethod
    def from_env(cls, env=None) -> "CheckpointPolicy":
        env = env if env is not None else os.environ

        def num(name, default):
            try:
                return int(env.get(name, "") or default)
            except ValueError:
                return default

        raw_max = env.get("KTPU_CKPT_RESTORE_MAX_STEP", "")
        try:
            max_restore = int(raw_max) if raw_max else None
        except ValueError:
            max_restore = None
        return cls(
            local_dir=env.get("KTPU_CKPT_LOCAL_DIR", ""),
            local_interval_steps=num("KTPU_CKPT_LOCAL_EVERY", 0),
            local_max_to_keep=num("KTPU_CKPT_LOCAL_KEEP", 2),
            persistent_dir=env.get("KTPU_CKPT_DIR", ""),
            persistent_interval_steps=num("KTPU_CKPT_PERSIST_EVERY", 0),
            peer_fetch=env.get("KTPU_CKPT_PEER_FETCH", "1")
            not in ("0", "false"),
            max_restore_step=max_restore,
            restore_parallel=max(1, num("KTPU_CKPT_RESTORE_PARALLEL", 8)),
            restore_inflight_mb=num("KTPU_CKPT_RESTORE_INFLIGHT_MB", 1024),
            save_concurrency=max(1, num("KTPU_CKPT_SAVE_CONCURRENCY", 8)),
            save_buffer_bytes=num("KTPU_CKPT_SAVE_BUFFER_BYTES", 1 << 30),
        )

    @property
    def enabled(self) -> bool:
        return bool(self.local_dir or self.persistent_dir)


@dataclass
class GoodputStats:
    """Counters the acceptance criteria read. ``lost_steps_last`` /
    ``per restart``: progress the gang had made past the step the
    restart restored — the work a faster local tier exists to shrink."""

    restores: int = 0
    restore_sources: Dict[str, int] = field(default_factory=dict)
    lost_steps_total: int = 0
    lost_steps_last: int = -1  # -1: no restore yet / progress unknown
    # newest step ANY tier committed this process generation (-1 = no
    # save yet): rides the obs heartbeat so the cluster scheduler can
    # price a preemption as progress-past-last-save (docs/SCHEDULER.md)
    last_saved_step: int = -1
    peer_shards_fetched: int = 0
    local_saves: int = 0
    local_save_failures: int = 0
    persistent_saves: int = 0
    persistent_save_failures: int = 0
    # routed saves skipped because the previous one is still committing
    # in the background, by reason — silent goodput loss made visible
    # (a too-tight localIntervalSteps shows up HERE, not as a mystery
    # gap in the committed-steps ladder)
    save_skipped: Dict[str, int] = field(default_factory=dict)
    # save_seconds_total is the STEP-CRITICAL-PATH wall only (the
    # parallel device→host snapshot + routing) — what the overhead
    # fraction prices. The background writer/committer phases land in
    # save_phase_seconds (snapshot_s / serialize_s / commit_s), which
    # overlap training and may sum past save_seconds_total.
    save_seconds_total: float = 0.0
    save_phase_seconds: Dict[str, float] = field(default_factory=dict)
    loop_seconds_total: float = 0.0
    # MTTR accounting (docs/CHECKPOINT.md "Restore critical path"):
    # restart latency in SECONDS, not just lost steps — the quantity
    # the scheduler/resize cost models price a restart at. The phase
    # breakdown (plan_s / fetch_s / device_s) mirrors the planner's
    # pipeline; fetch and device overlap, so phases may sum past the
    # total.
    restore_seconds_total: float = 0.0
    restore_phase_seconds: Dict[str, float] = field(default_factory=dict)

    def overhead_fraction(self) -> float:
        if self.loop_seconds_total <= 0:
            return 0.0
        return min(1.0, self.save_seconds_total / self.loop_seconds_total)

    def lost_steps_per_restart(self) -> float:
        if self.restores == 0:
            return 0.0
        return self.lost_steps_total / self.restores

    def to_dict(self) -> Dict[str, Any]:
        return {
            "restores": self.restores,
            "restore_sources": dict(self.restore_sources),
            "lost_steps_total": self.lost_steps_total,
            "lost_steps_last": self.lost_steps_last,
            "last_saved_step": self.last_saved_step,
            "lost_steps_per_restart": round(self.lost_steps_per_restart(), 3),
            "peer_shards_fetched": self.peer_shards_fetched,
            "local_saves": self.local_saves,
            "local_save_failures": self.local_save_failures,
            "persistent_saves": self.persistent_saves,
            "persistent_save_failures": self.persistent_save_failures,
            # dict() first: the writer/committer threads add phase keys
            # while heartbeat threads serialize this block — a plain
            # sorted(d.items()) could observe the resize mid-iteration
            "save_skipped": dict(self.save_skipped),
            "save_seconds_total": round(self.save_seconds_total, 6),
            "save_phases_s": {
                k: round(v, 6)
                for k, v in sorted(dict(self.save_phase_seconds).items())},
            "ckpt_overhead_fraction": round(self.overhead_fraction(), 5),
            "restore_seconds_total": round(self.restore_seconds_total, 6),
            "restore_phases_s": {
                k: round(v, 6)
                for k, v in sorted(self.restore_phase_seconds.items())},
        }


class MultiTierCheckpointManager:
    """Drop-in for :class:`k8s_tpu.train.checkpoint.CheckpointManager`
    with a local tier in front of it."""

    def __init__(
        self,
        policy: CheckpointPolicy,
        host_id: int = 0,
        barrier=None,
        transport=None,
        consensus=None,
        persistent=None,
        gang_consistent: bool = False,
    ):
        self.policy = policy
        self.host_id = host_id
        self.stats = GoodputStats()
        self._loop_t0 = time.monotonic()
        self._phase_lock = threading.Lock()
        # background persistent committer (docs/CHECKPOINT.md "Save
        # critical path"): ONE long-lived worker owns every orbax save
        # — orbax's async finalize bookkeeping may only be reset by the
        # thread that requested the previous save, so a thread-per-save
        # committer trips `assert self._finalize_thread is None` on the
        # second save. Routed saves hand the worker a staged host copy
        # and return; force/non-stageable saves ride the same worker
        # with the caller blocking on the drain.
        self._persist_lock = threading.Lock()
        self._persist_pending = 0
        self._persist_q = None
        self._persist_worker: Optional[threading.Thread] = None
        self.local: Optional[LocalTier] = None
        if policy.local_dir and policy.local_interval_steps > 0:
            self.local = LocalTier(
                policy.local_dir,
                host_id=host_id,
                max_to_keep=policy.local_max_to_keep,
                barrier=barrier,
                parallel=policy.save_concurrency,
                buffer_bytes=policy.save_buffer_bytes,
                on_phases=self._note_background_phases,
            )
        self.persistent = persistent
        if self.persistent is None and policy.persistent_dir:
            from k8s_tpu.train.checkpoint import CheckpointManager

            self.persistent = CheckpointManager(
                policy.persistent_dir,
                save_interval_steps=max(
                    1, policy.persistent_interval_steps or 1),
            )
        if transport is None and self.local is not None and policy.peer_fetch:
            peers_env = os.environ.get("KTPU_CKPT_PEERS", "")
            if peers_env:
                transport = RestPeerTransport.from_env_value(
                    peers_env, self_host=host_id)
            else:
                # shared-root harness / scratch-tier deployments: sibling
                # host-* dirs ARE the peers' node-local disks
                transport = FilesystemPeerTransport(
                    policy.local_dir, self_host=host_id)
        self.transport = transport
        self.planner = RestorePlanner(
            self.local, self.persistent, transport=transport,
            consensus=consensus, gang_consistent=gang_consistent,
            max_step=policy.max_restore_step,
            parallel=policy.restore_parallel,
            inflight_bytes=max(0, policy.restore_inflight_mb) << 20,
        )
        self.last_restore_plan: Optional[RestorePlan] = None

    @classmethod
    def from_env(cls, host_id: int = 0, env=None, barrier=None,
                 consensus=None, gang_consistent: bool = False,
                 ) -> Optional["MultiTierCheckpointManager"]:
        policy = CheckpointPolicy.from_env(env)
        if not policy.enabled:
            return None
        return cls(policy, host_id=host_id, barrier=barrier,
                   consensus=consensus, gang_consistent=gang_consistent)

    # ------------------------------------------------------------ save

    def save(self, step: int, state: Any, force: bool = False,
             unhealthy=None) -> bool:
        """Tier routing: local every ``local_interval`` steps,
        persistent every ``persistent_interval`` steps; ``force`` writes
        BOTH (the preemption-flush / final-save path must land durably
        AND be the newest local step so the restart restores it fast).

        Routed (non-force) saves are ZERO-STALL (docs/CHECKPOINT.md
        "Save critical path"): the step pays only the parallel
        device→host snapshot — the local tier's writer and the
        persistent tier's committer run in the background over staged
        copies — and a save that arrives while the previous one is
        still committing is a counted skip
        (``ktpu_ckpt_save_skipped_total{reason}``), never a stall.
        ``force`` keeps today's synchronous both-tiers semantics: the
        preempt flush / final save drains the writer and commits before
        the process may exit.

        ``unhealthy`` (optional callable) gates every write: evaluated
        ONLY on steps a tier would actually write (it may sync the
        device — e.g. reading the in-step health block), and a True
        verdict skips BOTH tiers with a ``ckpt_skip_unhealthy`` event.
        A diverged run must never checkpoint its NaN state — retention
        would rotate the healthy snapshots out from under the restart
        (docs/CHECKPOINT.md, "last healthy step"). Owning the gate HERE
        keeps it in lockstep with the routing predicate by
        construction."""
        t0 = time.monotonic()
        wrote = False
        try:
            wants_local = self.local is not None and (
                force or step % self.policy.local_interval_steps == 0
            )
            wants_persistent = self.persistent is not None and (
                force
                or (
                    self.policy.persistent_interval_steps > 0
                    and step % self.policy.persistent_interval_steps == 0
                )
            )
            if ((wants_local or wants_persistent)
                    and unhealthy is not None and unhealthy()):
                print(json.dumps({"event": "ckpt_skip_unhealthy",
                                  "step": step}), flush=True)
                return False
            if wants_local:
                # best-effort: a failed local snapshot (full node disk,
                # chaos partial commit) degrades THIS interval's restart
                # cost, never the training job — the persistent tier is
                # the correctness floor
                try:
                    if self.local.save(step, state, block=force):
                        self.stats.local_saves += 1
                        self._metric("CKPT_LOCAL_SAVES").inc()
                        wrote = True
                        # optimistic for the async local writer (the
                        # established local-tier semantics): a rare
                        # background write failure is already surfaced
                        # via local_save_failures
                        self.stats.last_saved_step = max(
                            self.stats.last_saved_step, step)
                    elif self.local.last_skip_reason == "writer_busy":
                        self._count_skip(step, "writer_busy")
                except Exception as e:
                    self.stats.local_save_failures += 1
                    log.warning(
                        "local checkpoint save failed at step %d (%s: %s); "
                        "local tier degraded this interval",
                        step, type(e).__name__, e)
            if wants_persistent:
                # NB: a STAGED persistent handoff does not advance
                # last_saved_step here — the committer does so only
                # when the orbax write actually lands, so the
                # scheduler's preemption pricing never believes in a
                # checkpoint a store outage swallowed
                wrote = self._save_persistent(step, state, force) or wrote
        finally:
            crit = time.monotonic() - t0
            self.stats.save_seconds_total += crit
            if wrote and not force:
                # the snapshot phase IS the step-critical-path slice of
                # a ROUTED save (everything else runs behind it). A
                # force save's wall includes the drain + synchronous
                # commits — already reported as serialize/commit by the
                # writer — so labeling it "snapshot" would double-count
                # the same seconds under the wrong phase; the full
                # flush wall stays visible in save_seconds_total.
                self._note_save_phase(step, "snapshot", crit)
            self._update_gauges()
        return wrote

    def _save_persistent(self, step: int, state: Any, force: bool) -> bool:
        """Persistent-tier leg of the routing.

        With ``KTPU_SYNC_CHECKPOINT=1`` (the gloo-unsafe-thread escape
        hatch) every save stays on the calling thread — the committer
        worker is never spawned. Otherwise ALL orbax saves run on the
        single committer worker (orbax's async finalize requires one
        save thread): routed saves stage a host copy (the
        step-critical-path slice; NB this is a WHOLE-TREE copy — the
        same peak orbax's own async save always staged, not governed
        by saveBufferBytes, which bounds the local tier's leaf-by-leaf
        staging window) and return immediately; ``force``
        (preempt flush / final save) and non-stageable states
        (multi-host shardings — orbax's collective path must see the
        live arrays, and the caller must not donate them mid-write)
        ride the same worker with the caller BLOCKING until the commit
        landed, preserving today's synchronous semantics."""
        if os.environ.get("KTPU_SYNC_CHECKPOINT", "") == "1":
            if self.persistent.save(step, state, force=force):
                self.stats.persistent_saves += 1
                self.stats.last_saved_step = max(
                    self.stats.last_saved_step, step)
                return True
            return False
        if not force and self._persist_busy():
            self._count_skip(step, "committer_busy")
            return False
        staged = None
        if not force:
            from k8s_tpu.ckpt.pipeline import stage_tree

            staged = stage_tree(state,
                                parallel=self.policy.save_concurrency)
        if staged is not None:
            self._persist_enqueue(step, staged, force=False,
                                  blocking=False)
            # the handoff counts as a write for routing purposes (same
            # optimism as the local tier's async writer);
            # persistent_saves increments when the commit lands
            return True
        box = self._persist_enqueue(step, state, force=force,
                                    blocking=True)
        self._persist_drain()
        err = box.get("err")
        if err is not None:
            raise err  # today's contract: a failed forced flush raises
        if box.get("ok"):
            self.stats.persistent_saves += 1
            self.stats.last_saved_step = max(
                self.stats.last_saved_step, step)
            return True
        return False

    # ---- committer worker plumbing ------------------------------------

    def _persist_busy(self) -> bool:
        with self._persist_lock:
            return self._persist_pending > 0

    def _persist_enqueue(self, step, state, force, blocking) -> Dict:
        from queue import Queue

        with self._persist_lock:
            if self._persist_q is None:
                self._persist_q = Queue()
                t = threading.Thread(
                    target=self._persist_loop, args=(self._persist_q,),
                    daemon=True,
                    name=f"ckpt-persist-{self.host_id}")
                self._persist_worker = t
                t.start()
            self._persist_pending += 1
        box: Dict[str, Any] = {"blocking": blocking}
        self._persist_q.put((step, state, force, box))
        return box

    def _persist_loop(self, q) -> None:
        while True:
            item = q.get()
            if item is None:
                q.task_done()
                return
            step, state, force, box = item
            t0 = time.monotonic()
            try:
                ok = self.persistent.save(step, state, force=force)
                box["ok"] = ok
                if ok:
                    if not box["blocking"]:
                        # blocking callers count on their own thread
                        self.stats.persistent_saves += 1
                    self.stats.last_saved_step = max(
                        self.stats.last_saved_step, step)
                    self._note_save_phase(
                        step, "commit", time.monotonic() - t0)
            except BaseException as e:
                box["err"] = e
                if not box["blocking"]:
                    # degraded-not-fatal, like the local tier: the
                    # force path at preempt/final save re-writes (and
                    # re-raises) synchronously
                    self.stats.persistent_save_failures += 1
                    log.warning(
                        "background persistent checkpoint save failed "
                        "at step %d (%s: %s); persistent tier degraded "
                        "this interval", step, type(e).__name__, e)
            finally:
                with self._persist_lock:
                    self._persist_pending -= 1
                q.task_done()
                self._update_gauges()

    def _persist_drain(self) -> None:
        if self._persist_q is not None:
            self._persist_q.join()

    def _persist_shutdown(self) -> None:
        with self._persist_lock:
            q, self._persist_q = self._persist_q, None
            t, self._persist_worker = self._persist_worker, None
        if q is not None:
            q.put(None)
        if t is not None:
            t.join(timeout=10)

    def _count_skip(self, step: int, reason: str) -> None:
        self.stats.save_skipped[reason] = (
            self.stats.save_skipped.get(reason, 0) + 1)
        self._metric("CKPT_SAVE_SKIPPED").inc({"reason": reason})
        log.warning(
            "checkpoint save skipped at step %d (%s): the previous save "
            "is still committing in the background; tier degraded this "
            "interval — localIntervalSteps/persistentIntervalSteps may "
            "be too tight for the disk/store", step, reason)

    # ------------------------------------------------------------ phases

    def _note_save_phase(self, step: int, phase: str, seconds: float
                         ) -> None:
        """One save phase → goodput accumulation + the
        ktpu_ckpt_save_seconds gauge + a save_<phase> span on the
        process tracer (flight recorder). Called from the step path
        (snapshot) and from the writer/committer threads (serialize /
        commit) — mirrors the restore-side MTTR telemetry."""
        seconds = float(seconds)
        with self._phase_lock:
            key = f"{phase}_s"
            self.stats.save_phase_seconds[key] = (
                self.stats.save_phase_seconds.get(key, 0.0) + seconds)
        self._metric("CKPT_SAVE_SECONDS").set(seconds, {"phase": phase})
        from k8s_tpu.obs.trace import default_tracer

        tracer = default_tracer()
        if tracer is not None:
            tracer.note_span(f"save_{phase}", seconds, step=step)

    def _note_background_phases(self, step: int,
                                phases: Dict[str, float]) -> None:
        """LocalTier writer callback: the background serialize/commit
        legs of a committed local save."""
        for phase in ("serialize", "commit"):
            if phase in phases:
                self._note_save_phase(step, phase, phases[phase])

    def note_step(self, step: int) -> None:
        """Per-step bookkeeping (cheap): progress marker for
        lost-steps accounting + loop-time accumulation for the overhead
        fraction."""
        now = time.monotonic()
        self.stats.loop_seconds_total += now - self._loop_t0
        self._loop_t0 = now
        if self.local is not None:
            self.local.note_progress(step)

    # ------------------------------------------------------------ restore

    def restore(self, state_template: Any,
                step: Optional[int] = None) -> Optional[Any]:
        if step is not None and self.persistent is not None:
            # explicit-step restore bypasses planning (debug surface)
            return self.persistent.restore(state_template, step=step)
        t0 = time.monotonic()
        tree, plan = self.planner.restore(state_template)
        restore_s = time.monotonic() - t0
        phases = dict(getattr(self.planner, "last_restore_stats", {}) or {})
        self.last_restore_plan = plan
        if plan.source != SOURCE_NONE:
            if plan.step is not None:
                # the restored step IS a committed checkpoint: seed the
                # save marker so a freshly-restarted job isn't priced
                # as if all its (replayed) progress were unsaved —
                # that would invert the scheduler's cheapest-victim
                # rule against exactly the jobs that just restored
                self.stats.last_saved_step = max(
                    self.stats.last_saved_step, int(plan.step))
            self.stats.restores += 1
            self.stats.restore_sources[plan.source] = (
                self.stats.restore_sources.get(plan.source, 0) + 1
            )
            self.stats.peer_shards_fetched += plan.peer_fetches
            self._metric("CKPT_RESTORES").inc({"source": plan.source})
            progress = self._best_progress()
            if progress >= 0 and plan.step is not None:
                lost = max(0, progress - plan.step)
                self.stats.lost_steps_last = lost
                self.stats.lost_steps_total += lost
                self._metric("CKPT_LOST_STEPS").inc(by=lost)
            # MTTR: restart latency as a first-class measured quantity
            # — goodput seconds + per-phase gauge + tracer spans that
            # land in the flight recorder next to the step spans
            # (docs/CHECKPOINT.md "Restore critical path")
            phase_s = {k: float(phases.get(k, 0.0))
                       for k in ("plan_s", "fetch_s", "device_s")}
            self.stats.restore_seconds_total += restore_s
            for k, v in phase_s.items():
                self.stats.restore_phase_seconds[k] = (
                    self.stats.restore_phase_seconds.get(k, 0.0) + v)
            gauge = self._metric("CKPT_RESTORE_SECONDS")
            gauge.set(restore_s, {"phase": "total"})
            for k, v in phase_s.items():
                gauge.set(v, {"phase": k[:-2]})
            from k8s_tpu.obs.trace import default_tracer

            tracer = default_tracer()
            if tracer is not None:
                for k, v in phase_s.items():
                    tracer.note_span(
                        f"restore_{k[:-2]}", v,
                        step=plan.step, source=plan.source)
            print(json.dumps({
                "event": "ckpt_restore", "step": plan.step,
                "source": plan.source, "peer_shards": plan.peer_fetches,
                "lost_steps": self.stats.lost_steps_last,
                "seconds": round(restore_s, 6),
                "phases_s": {k: round(v, 6) for k, v in phase_s.items()},
            }), flush=True)
        self._update_gauges()
        return tree

    def _best_progress(self) -> int:
        best = self.local.progress() if self.local is not None else -1
        if self.transport is not None:
            try:
                best = max(best, self.transport.progress())
            except Exception:
                pass
        return best

    # ------------------------------------------------------------ passthrough

    def reached_preemption(self, step: int) -> bool:
        if self.persistent is not None:
            return self.persistent.reached_preemption(step)
        # local-only policy: no orbax manager → no coordination-service
        # consensus poll. Fall back to the launcher's SIGTERM flag: the
        # node drain SIGTERMs every pod of the slice, and a local-tier
        # flush is collective-free (own shards → own disk), so each
        # host flushing at its own step boundary is safe — the restore
        # planner's gang rule reconciles off-by-one commits.
        return os.environ.get("KTPU_PREEMPT_REQUESTED") == "1"

    def latest_step(self) -> Optional[int]:
        steps = []
        if self.local is not None:
            steps.extend(self.local.committed_steps())
        if self.persistent is not None:
            ps = self.persistent.latest_step()
            if ps is not None:
                steps.append(ps)
        return max(steps) if steps else None

    def wait(self) -> None:
        if self.local is not None:
            try:
                self.local.wait()
            except Exception as e:  # async local write failed: degraded,
                self.stats.local_save_failures += 1  # not fatal
                log.warning("local checkpoint flush failed (%s: %s)",
                            type(e).__name__, e)
        # drain the background persistent committer (its own failures
        # were already counted/logged on the committer thread) before
        # orbax's wait, so "wait() returned" still means "every handed-
        # off save is on disk or accounted as failed"
        self._persist_drain()
        if self.persistent is not None:
            self.persistent.wait()

    def close(self) -> None:
        try:
            self.wait()
        finally:
            self._persist_shutdown()
            if self.persistent is not None:
                self.persistent.close()

    # ------------------------------------------------------------ goodput

    def goodput(self) -> Dict[str, Any]:
        return self.stats.to_dict()

    def _metric(self, name: str):
        from k8s_tpu.controller import metrics

        return getattr(metrics, name)

    def _update_gauges(self) -> None:
        from k8s_tpu.controller import metrics

        metrics.CKPT_OVERHEAD_FRACTION.set(self.stats.overhead_fraction())
        metrics.CKPT_LOST_STEPS_PER_RESTART.set(
            self.stats.lost_steps_per_restart())
