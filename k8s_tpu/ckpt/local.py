"""Local checkpoint tier: per-host sharded snapshots on node-local disk.

The cheap, frequent half of the multi-tier model (docs/CHECKPOINT.md).
Every few steps each host snapshots ONLY its addressable shards of the
sharded TrainState — a device→host copy plus a node-local disk write,
orders of magnitude cheaper than a durable-store save — so a gang
restart loses at most ``local_interval`` steps instead of
``persistent_interval``.

The save itself is a **pipeline** (docs/CHECKPOINT.md "Save critical
path"): the step-critical-path slice is ONE parallel device→host
snapshot — per-shard copies fan out across a bounded pool, admitted
leaf-by-leaf against an in-flight-bytes gate so a multi-GB state stages
through bounded host RAM — and everything after it (npy serialization,
streaming crc, manifest, barrier, atomic commit) runs on a background
writer thread that only ever touches the staged copies, never device
views. ``save()`` returns once every copy has completed, so the caller
may donate the live arrays immediately (the donate-after contract); a
``block=False`` caller that finds the previous writer still committing
gets a counted skip instead of a stall.

Crash-safety is a **two-phase commit**:

1. *Write phase*: shards + a per-host manifest land in
   ``step-<N>.pending/``; every shard carries a crc32 recorded in the
   manifest.
2. *Commit phase*: after the (pluggable) gang barrier — no host may
   commit until every host finished writing, or a crash between two
   hosts' saves would leave the newest step half-present — the pending
   dir is atomically renamed to ``step-<N>/`` and a ``COMMIT`` marker
   file is fsynced into it.

A step counts as committed ONLY when the marker exists; a crash at any
point leaves either the previous committed step intact (pending dir is
garbage-collected) or the new one fully committed. The restore planner
(:mod:`k8s_tpu.ckpt.planner`) additionally verifies crcs at read time,
so torn writes that survive the marker protocol (disk corruption) are
detected and routed to a peer or the persistent tier.

Shard files are keyed by their **global index** — the slice tuple of
the global array the shard covers. Under SPMD two devices holding the
same index hold identical bytes (replication invariant), which is what
makes peer-shard restore correct: any host whose local tier holds an
index can serve it to a replaced pod, no matter which mesh axes were
data-parallel.

Chaos hooks (``arm_partial_commit``, ``corrupt_one_shard``,
``drop_host``) are installed by the fault matrix
(:mod:`k8s_tpu.runtime.chaos`) — never in production.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time
import zlib
from queue import Queue
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from k8s_tpu.ckpt.pipeline import InflightGate, crc32_array, est_leaf_bytes

log = logging.getLogger(__name__)

COMMIT_MARKER = "COMMIT"
MANIFEST = "manifest.json"
PENDING_SUFFIX = ".pending"
PROGRESS_FILE = "progress.json"

# Chaos hook: when armed, the next n commits stop after the write phase
# (pending dir on disk, no rename, no marker) and raise — exactly what
# a host crash between phase 1 and phase 2 leaves behind.
_partial_commit_lock = threading.Lock()
_partial_commit_remaining = 0


def arm_partial_commit(n: int) -> None:
    """Make the next ``n`` local-tier commits (process-wide) fail after
    the write phase. ``n=0`` disarms."""
    global _partial_commit_remaining
    with _partial_commit_lock:
        _partial_commit_remaining = n


def _take_partial_commit() -> bool:
    global _partial_commit_remaining
    with _partial_commit_lock:
        if _partial_commit_remaining > 0:
            _partial_commit_remaining -= 1
            return True
    return False


def index_key(idx: Tuple, shape: Tuple[int, ...]) -> str:
    """Serialize a shard's global index (tuple of slices) as
    ``"0:4,8:16"`` — one ``start:stop`` per dim, scalars as ``"-"``."""
    if not shape:
        return "-"
    parts = []
    for s, dim in zip(idx, shape):
        start, stop, _ = s.indices(dim)
        parts.append(f"{start}:{stop}")
    return ",".join(parts)


def parse_index_key(key: str) -> Optional[Tuple[slice, ...]]:
    if key == "-":
        return ()
    out = []
    for part in key.split(","):
        start, _, stop = part.partition(":")
        out.append(slice(int(start), int(stop)))
    return tuple(out)


# ---------------------------------------------------------------------------
# Shard-index geometry: restoring ACROSS layouts (replicated ↔ ZeRO-1 /
# resharded opt state) means the exact index a template asks for may not
# exist in a manifest written under the other layout — but a bigger
# stored shard may CONTAIN it, or a set of smaller stored shards may
# tile it exactly. These helpers answer both without loading payloads.
# ---------------------------------------------------------------------------


def _box(key: str) -> Optional[Tuple[Tuple[int, int], ...]]:
    """Index key → ((start, stop), ...) box; None for the scalar key
    (or a malformed one). Wraps :func:`parse_index_key` so the covering
    geometry can never diverge from the save-side key vocabulary."""
    try:
        slices = parse_index_key(key)
    except ValueError:
        return None
    if not slices:
        return None
    return tuple((s.start, s.stop) for s in slices)


def _box_contains(outer, inner) -> bool:
    return len(outer) == len(inner) and all(
        o[0] <= i[0] and i[1] <= o[1] for o, i in zip(outer, inner)
    )


def _box_volume(b) -> int:
    v = 1
    for lo, hi in b:
        v *= max(0, hi - lo)
    return v


def _boxes_disjoint(a, b) -> bool:
    return any(a[i][1] <= b[i][0] or b[i][1] <= a[i][0]
               for i in range(len(a)))


def _tiles_exactly(want, boxes) -> bool:
    """Do ``boxes`` (each already contained in ``want``) tile it
    exactly? Equal total volume + pairwise disjoint + all inside want
    ⇒ gap-free, overlap-free cover. Shared by the single-manifest and
    union covering plans so the tiling rule cannot diverge."""
    boxes = list(boxes)
    if not boxes:
        return False
    if sum(_box_volume(b) for b in boxes) != _box_volume(want):
        return False
    return all(
        _boxes_disjoint(boxes[i], boxes[j])
        for i in range(len(boxes)) for j in range(i + 1, len(boxes))
    )


def covering_plan(
    want_key: str, have_keys,
) -> Optional[List[str]]:
    """Which stored shard keys rebuild ``want_key``: the exact key, ONE
    containing shard (replicated checkpoint → sharded template), or a
    set of contained shards that tile it exactly (sharded checkpoint →
    replicated/coarser template). None when the manifest cannot cover
    the request. Geometry only — no payload reads."""
    have = list(have_keys)
    if want_key in have:
        return [want_key]
    want = _box(want_key)
    if want is None:
        return None  # scalar: exact key or nothing
    for k in have:
        hb = _box(k)
        if hb is not None and _box_contains(hb, want):
            return [k]
    pieces = [(k, _box(k)) for k in have]
    pieces = [(k, b) for k, b in pieces
              if b is not None and _box_contains(want, b)]
    if not _tiles_exactly(want, [b for _, b in pieces]):
        return None
    return [k for k, _ in pieces]


def union_covering_plan(
    want_key: str, have_by_source,
) -> Optional[List[Tuple[str, Any]]]:
    """:func:`covering_plan` across SEVERAL manifests: rebuild
    ``want_key`` from shards held by different sources (own disk +
    peers). ``have_by_source`` is an ordered ``[(source, keys), ...]``
    — sources earlier in the list are preferred. Returns
    ``[(key, source), ...]`` or None.

    This is what makes a multi-host ZeRO-1 checkpoint restorable into a
    replicated/coarser template: each host's manifest holds only its
    own 1/DP tile, so no SINGLE manifest covers the full leaf — but the
    union does. Single-source plans win first (no cross-host assembly);
    otherwise contained pieces are pooled across sources (first source
    holding a key claims it) and must tile ``want_key`` exactly —
    pairwise-disjoint, gap-free — or the union is no cover either."""
    for src, keys in have_by_source:
        plan = covering_plan(want_key, keys)
        if plan is not None:
            return [(k, src) for k in plan]
    want = _box(want_key)
    if want is None:
        return None  # scalar: exact key or nothing, per source
    pieces: Dict[str, Tuple[Any, Tuple]] = {}
    for src, keys in have_by_source:
        for k in keys:
            if k in pieces:
                continue
            b = _box(k)
            if b is not None and _box_contains(want, b):
                pieces[k] = (src, b)
    if not _tiles_exactly(want, [b for _, b in pieces.values()]):
        return None
    return [(k, src) for k, (src, _) in pieces.items()]


def compose_shard(
    want_key: str, plan: List[str], load,
) -> Optional[np.ndarray]:
    """Assemble the ``want_key`` slice from the shards named by a
    :func:`covering_plan`. ``load(key) -> ndarray | None`` reads one
    stored shard (crc-verified by the caller's loader); any failed load
    fails the composition (caller falls back to a peer / the persistent
    tier)."""
    want = _box(want_key)
    if plan == [want_key] or want is None:
        return load(want_key)
    if len(plan) == 1:  # one containing shard: cut our slice out of it
        outer = _box(plan[0])
        arr = load(plan[0])
        if arr is None:
            return None
        rel = tuple(
            slice(w[0] - o[0], w[1] - o[0]) for w, o in zip(want, outer)
        )
        return np.ascontiguousarray(arr[rel])
    out = None
    for k in plan:
        arr = load(k)
        if arr is None:
            return None
        if out is None:
            out = np.empty(
                tuple(hi - lo for lo, hi in want), dtype=arr.dtype)
        kb = _box(k)
        rel = tuple(
            slice(b[0] - w[0], b[1] - w[0]) for b, w in zip(kb, want)
        )
        out[rel] = arr
    return out


def _leaf_paths(tree) -> List[Tuple[str, Any]]:
    """Stable ``(path-string, leaf)`` pairs: '/'-joined key path of each
    leaf — the manifest vocabulary both save and restore agree on."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        out.append(("/".join(_key_str(k) for k in path), leaf))
    return out


def _key_str(k) -> str:
    # DictKey('params') -> params, SequenceKey(0) -> 0, GetAttrKey -> name
    for attr in ("key", "idx", "name"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def _leaf_dirname(path: str) -> str:
    """Filesystem-safe per-leaf directory: a short hash prefix guards
    against collisions after character replacement."""
    safe = path.replace("/", "__").replace(".", "_")[:120]
    return f"{zlib.crc32(path.encode()) & 0xFFFFFFFF:08x}-{safe}"


def local_shards_of(leaf, devices=None) -> Dict[str, np.ndarray]:
    """This host's shards of a jax array, deduplicated by global index
    (multiple local devices may hold the same replicated shard — one
    copy is enough). Plain numpy/python leaves are treated as one
    fully-replicated shard. ``devices`` narrows "this host" to a device
    subset — how the in-process soak simulates multiple hosts on one
    runtime. Eager spelling of :func:`shard_copy_jobs` (the save
    pipeline's deferred form)."""
    jobs, _ = shard_copy_jobs(leaf, devices=devices)
    return {key: materialize() for key, materialize in jobs}


def shard_copy_jobs(leaf, devices=None):
    """This host's shards of ``leaf`` as DEFERRED copy jobs: a list of
    ``(index_key, materialize)`` pairs plus the estimated host bytes
    the copies will stage. Enumeration reads geometry only, so the
    save pipeline can gate-admit and pool-fan the copies without
    touching payloads on the calling thread.

    Each ``materialize()`` is ``np.array(..., copy=True)`` — save()'s
    contract is that the device→host copy happens before it returns so
    the caller may donate immediately. ``np.asarray`` of a CPU-backend
    jax array can be a ZERO-COPY view of the device buffer, and the
    async writer would then serialize whatever the NEXT (donated) step
    scribbled into it: a crc-consistent garbage checkpoint (found by
    the divergence e2e — restored states differed nondeterministically
    run to run)."""
    addressable = getattr(leaf, "addressable_shards", None)
    if addressable is None:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            shape, dtype = tuple(leaf.shape), leaf.dtype
        else:
            as_np = np.asarray(leaf)
            shape, dtype = as_np.shape, as_np.dtype
        full = index_key(tuple(slice(0, d) for d in shape), shape)
        return ([(full, lambda _l=leaf: np.array(_l, copy=True))],
                est_leaf_bytes(shape, dtype))
    jobs, est, seen = [], 0, set()
    shape = tuple(leaf.shape)
    for sh in addressable:
        if devices is not None and sh.device not in devices:
            continue
        key = index_key(sh.index, shape)
        if key in seen:
            continue
        seen.add(key)
        jobs.append((key, lambda _s=sh: np.array(_s.data, copy=True)))
        sizes = [s.indices(d) for s, d in zip(sh.index, shape)]
        est += est_leaf_bytes(
            tuple(stop - start for start, stop, _ in sizes), leaf.dtype)
    return jobs, est


def required_indices(template_leaf, devices=None) -> List[str]:
    """The shard indices THIS host must source to rebuild its portion
    of ``template_leaf`` (a concrete array or a ShapeDtypeStruct
    carrying a sharding). ``devices`` narrows the host as in
    :func:`local_shards_of`."""
    import jax

    sharding = getattr(template_leaf, "sharding", None)
    shape = tuple(getattr(template_leaf, "shape", ()))
    if sharding is None:
        return [index_key(tuple(slice(0, d) for d in shape), shape)]
    keys = []
    seen = set()
    try:
        imap = sharding.devices_indices_map(shape)
    except Exception:
        return [index_key(tuple(slice(0, d) for d in shape), shape)]
    local = set(jax.local_devices()) if devices is None else set(devices)
    for dev, idx in imap.items():
        if dev not in local:
            continue
        key = index_key(idx, shape)
        if key not in seen:
            seen.add(key)
            keys.append(key)
    return keys


class LocalTier:
    """One host's local snapshot store.

    ``root`` is the node-local directory (emptyDir / local SSD in a real
    pod); this host's snapshots live under ``root/host-<host_id>/``. In
    the test harness every "node" shares one tmp filesystem, so sibling
    ``host-*`` dirs stand in for peers' node-local disks — which is
    exactly what :class:`k8s_tpu.ckpt.peer.FilesystemPeerTransport`
    reads.

    ``barrier(step)`` is the gang-wide commit barrier — in a distributed
    run, a callable that returns only when every host finished its write
    phase (e.g. ``multihost_utils.sync_global_devices``); ``None`` is
    the single-host no-op.
    """

    def __init__(
        self,
        root: str,
        host_id: int = 0,
        max_to_keep: int = 2,
        barrier: Optional[Callable[[int], None]] = None,
        sync: bool = False,
        devices=None,
        parallel: int = 8,
        buffer_bytes: int = 1 << 30,
        on_phases: Optional[Callable[[int, Dict[str, float]], None]] = None,
    ):
        self.root = root
        self.host_id = int(host_id)
        self.max_to_keep = max_to_keep
        self.barrier = barrier
        self.sync = sync
        self.devices = devices  # None = all of this process's devices
        # save pipeline knobs (docs/CHECKPOINT.md "Save critical
        # path"): snapshot-pool width (1 = serial copies, byte-
        # identical committed output either way) and the staged-bytes
        # cap shared between the snapshot and the background writer
        self.parallel = max(1, int(parallel))
        self.buffer_bytes = int(buffer_bytes)
        # called by the WRITER thread after each successful commit with
        # the background phase timings {"serialize": s, "commit": s} —
        # the manager wires it into spans/gauges/goodput
        self.on_phases = on_phases
        # created lazily on first WRITE: instantiating a tier (or a
        # peer transport / read-side probe) must not resurrect a
        # dropped host's dir as an empty husk — chaos drop_host and
        # peer discovery both read the directory layout as truth
        self.host_dir = os.path.join(root, f"host-{self.host_id}")
        self._writer: Optional[threading.Thread] = None
        self._writer_error: Optional[BaseException] = None
        self.saves = 0
        self.commit_failures = 0
        self.skipped_busy = 0
        self.last_skip_reason: Optional[str] = None
        # pipeline evidence of the LAST accepted save (gate peak/waits,
        # snapshot seconds) — what the save bench and tests read
        self.last_save_stats: Dict[str, Any] = {}

    # ------------------------------------------------------------ paths

    def step_dir(self, step: int) -> str:
        return os.path.join(self.host_dir, f"step-{step}")

    def _pending_dir(self, step: int) -> str:
        return self.step_dir(step) + PENDING_SUFFIX

    # ------------------------------------------------------------ save

    def save(self, step: int, tree: Any, block: bool = True) -> bool:
        """Snapshot this host's shards of ``tree`` at ``step``.

        The device→host copies happen NOW — fanned across a bounded
        pool (``parallel``), leaf-admitted against the staged-bytes
        gate (``buffer_bytes``) — and ALL complete before this returns,
        so the caller may donate / mutate the arrays immediately after.
        Serialization, crc, manifest, barrier and the atomic commit run
        on a background writer thread that consumes the staged copies
        leaf-by-leaf (releasing their gate bytes as each leaf lands on
        disk) and never touches a device view.

        ``block=True`` (the default, today's semantics) drains a still-
        running previous writer first. ``block=False`` — the manager's
        zero-stall routed path — returns False with
        ``last_skip_reason="writer_busy"`` instead: a too-tight save
        interval costs a counted skip, never a step stall. Returns
        False (``"already_committed"``) if the step is committed.
        """
        self.last_skip_reason = None
        prev = self._writer
        if prev is not None and prev.is_alive() and not block \
                and self.barrier is None:
            # zero-stall skip is only sound WITHOUT a commit barrier: a
            # barrier-wired gang tier must participate symmetrically in
            # every step's commit (a host that skips while a peer's
            # writer is already blocked in barrier(step) would wedge
            # that writer — and with it every later force/final save)
            # — so barrier'd tiers keep the draining semantics
            self.skipped_busy += 1
            self.last_skip_reason = "writer_busy"
            return False
        # drain the previous in-flight write FIRST (double buffer), so
        # the committed check sees its outcome: a force save at the
        # step the async writer is still committing must be the no-op,
        # not a doomed re-write (rename onto the fresh commit fails and
        # was miscounted as a local_save_failure every final save)
        self.wait()
        if step in self.committed_steps():
            self.last_skip_reason = "already_committed"
            return False
        jobs = []  # (path, est_bytes, [(key, materialize), ...])
        meta: Dict[str, Dict[str, Any]] = {}
        for path, leaf in _leaf_paths(tree):
            shard_fns, est = shard_copy_jobs(leaf, devices=self.devices)
            jobs.append((path, est, shard_fns))
            # NB: getattr with an eager np.asarray default would fetch
            # the GLOBAL array (explodes on multi-host shardings)
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                shape, dtype = leaf.shape, leaf.dtype
            else:
                as_np = np.asarray(leaf)
                shape, dtype = as_np.shape, as_np.dtype
            meta[path] = {"shape": list(shape), "dtype": str(dtype)}
        from concurrent.futures import ThreadPoolExecutor

        gate = InflightGate(self.buffer_bytes)
        abort = threading.Event()
        ready: Queue = Queue()
        phases = {"serialize": 0.0, "commit": 0.0}
        stats: Dict[str, Any] = {"parallel": self.parallel}
        self.last_save_stats = stats
        writer = threading.Thread(
            target=self._write_pipeline,
            args=(step, ready, meta, gate, abort, phases),
            daemon=True,
            name=f"ckpt-local-{self.host_id}",
        )
        self._writer = writer
        writer.start()
        pool = ThreadPoolExecutor(
            max_workers=self.parallel,
            thread_name_prefix=f"ckpt-snap-{self.host_id}")
        snap0 = time.perf_counter()
        all_futs = []
        try:
            for path, est, shard_fns in jobs:
                # leaf-granular admission: with the cap below the tree
                # size the snapshot throttles against the writer's
                # releases — bounded host staging traded for stall,
                # exactly what saveBufferBytes dials
                gate.acquire(est, abort)
                # copies DEPOSIT into a writer-owned dict and the
                # futures resolve to None: a future that returned the
                # array would pin every staged copy until save()
                # dropped it at return, making the gate's cap cosmetic
                # — with the dict, the writer's buffers.clear() after
                # each leaf is the only liveness that matters
                staged: Dict[str, np.ndarray] = {}
                futs = [pool.submit(self._copy_shard, fn, staged, key,
                                    abort)
                        for key, fn in shard_fns]
                all_futs.extend(futs)
                ready.put((path, est, staged, futs))
        finally:
            ready.put(None)
        # donate-after contract: EVERY copy has completed (or died)
        # before save() returns — the writer owns only host buffers
        err: Optional[BaseException] = None
        for f in all_futs:
            try:
                f.result()
            except BaseException as e:
                if err is None:
                    err = e
                abort.set()
        pool.shutdown(wait=True)
        stats["snapshot_s"] = time.perf_counter() - snap0
        stats["peak_staged_bytes"] = gate.peak
        stats["gate_waits"] = gate.waits
        if err is not None:
            # the writer saw abort and dropped the partial dir. ONE
            # failure must surface exactly once: when the WRITER died
            # first (disk full at mkdir) the copies were aborted as a
            # side effect — drain it and raise the root cause here
            # instead of a contentless abort error now and the real
            # one out of the NEXT save's wait()
            try:
                self.wait()
            except BaseException as werr:
                err = werr
            raise err
        if self.sync:
            self.wait()  # deterministic tests/benches: commit, then return
        return True

    @staticmethod
    def _copy_shard(materialize, staged: Dict[str, np.ndarray],
                    key: str, abort: threading.Event) -> None:
        if abort.is_set():
            raise RuntimeError("ckpt save aborted")
        staged[key] = materialize()

    def _write_pipeline(self, step, ready: Queue, meta, gate, abort,
                        phases) -> None:
        """Background writer: staged copies → npy files + streaming crc
        (serialize), then manifest + barrier + atomic rename + marker
        (commit). Gate bytes are released leaf-by-leaf as buffers drop;
        any failure drains the queue (so the snapshot side never wedges
        in ``gate.acquire``) and removes the pending dir."""
        pending = self._pending_dir(step)
        manifest: Dict[str, Any] = {
            "step": step,
            "host": self.host_id,
            "leaves": {},
        }
        failed: Optional[BaseException] = None
        try:
            os.makedirs(self.host_dir, exist_ok=True)
            if os.path.exists(pending):
                shutil.rmtree(pending, ignore_errors=True)
            os.makedirs(pending)
        except BaseException as e:
            failed = e
            abort.set()
        while True:
            item = ready.get()
            if item is None:
                break
            path, est, staged, futs = item
            copies_ok = True
            try:
                for fut in futs:
                    try:
                        fut.result()  # join; arrays live in `staged`
                    except BaseException:
                        # snapshot-side failure: save() raises it on the
                        # calling thread — not a writer error too
                        abort.set()
                        copies_ok = False
                        break
                if copies_ok and failed is None and not abort.is_set():
                    t0 = time.perf_counter()
                    self._write_leaf(pending, path, meta[path], staged,
                                     manifest)
                    phases["serialize"] += time.perf_counter() - t0
            except BaseException as e:  # the WRITE died: writer-owned
                if failed is None:
                    failed = e
                abort.set()
            finally:
                # drop the staged copies BEFORE releasing their bytes —
                # the gate models host RAM, not queue slots (and this
                # dict is the ONLY strong reference to the copies)
                staged.clear()
                gate.release(est)
        if abort.is_set() or failed is not None:
            shutil.rmtree(pending, ignore_errors=True)
            if failed is not None:
                self._writer_error = failed
            return
        try:
            t0 = time.perf_counter()
            self._commit(step, pending, manifest)
            phases["commit"] += time.perf_counter() - t0
        except BaseException as e:  # surfaced by the next wait()/save()
            self._writer_error = e
            return
        if self.on_phases is not None:
            try:
                self.on_phases(step, dict(phases))
            except Exception:
                log.warning("ckpt save phase callback failed",
                            exc_info=True)

    def _write_leaf(self, pending, path, entry_meta, buffers,
                    manifest) -> None:
        leaf_dir = os.path.join(pending, _leaf_dirname(path))
        os.makedirs(leaf_dir, exist_ok=True)
        entry = dict(entry_meta)
        entry["shards"] = {}
        for key, arr in buffers.items():
            fname = key.replace(":", "_").replace(",", "+") or "scalar"
            fpath = os.path.join(leaf_dir, fname + ".npy")
            with open(fpath, "wb") as f:
                np.save(f, arr)
                f.flush()
                os.fsync(f.fileno())
            entry["shards"][key] = {
                "file": os.path.relpath(fpath, pending),
                # streaming crc over the staged buffer — the old
                # arr.tobytes() spelling held a SECOND full copy of
                # every shard just to hash it (pipeline.crc32_array)
                "crc": crc32_array(arr),
            }
        manifest["leaves"][path] = entry

    def _commit(self, step, pending, manifest) -> None:
        mpath = os.path.join(pending, MANIFEST)
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        # ---- phase 2: barrier, then atomic publish --------------------
        if _take_partial_commit():
            self.commit_failures += 1
            raise OSError(
                f"chaos: injected partial local commit at step {step} "
                f"(pending dir left behind)"
            )
        if self.barrier is not None:
            self.barrier(step)
        final = self.step_dir(step)
        os.rename(pending, final)
        cpath = os.path.join(final, COMMIT_MARKER)
        with open(cpath, "w") as f:
            f.write(f"{step}\n")
            f.flush()
            os.fsync(f.fileno())
        self.saves += 1
        self._retain()

    def wait(self) -> None:
        """Block until the in-flight write (if any) finished; re-raise
        its error exactly once."""
        t = self._writer
        if t is not None:
            t.join()
            self._writer = None
        err, self._writer_error = self._writer_error, None
        if err is not None:
            raise err

    def _retain(self) -> None:
        steps = self.committed_steps()
        for old in steps[: max(0, len(steps) - self.max_to_keep)]:
            shutil.rmtree(self.step_dir(old), ignore_errors=True)
        # stale pending dirs from a crashed/failed commit are garbage
        for name in os.listdir(self.host_dir):
            if name.endswith(PENDING_SUFFIX):
                try:
                    pstep = int(name[len("step-"):-len(PENDING_SUFFIX)])
                except ValueError:
                    continue
                if steps and pstep < steps[-1]:
                    shutil.rmtree(
                        os.path.join(self.host_dir, name), ignore_errors=True
                    )

    # ------------------------------------------------------------ progress

    def note_progress(self, step: int) -> None:
        """Record the last COMPLETED train step — a tiny atomic write
        per step. Restore reads it (from any surviving host) to compute
        lost-steps-per-restart: progress - restored_step."""
        os.makedirs(self.host_dir, exist_ok=True)
        tmp = os.path.join(self.host_dir, PROGRESS_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump({"step": int(step)}, f)
        os.replace(tmp, os.path.join(self.host_dir, PROGRESS_FILE))

    def progress(self) -> int:
        try:
            with open(os.path.join(self.host_dir, PROGRESS_FILE)) as f:
                return int(json.load(f)["step"])
        except (OSError, ValueError, KeyError):
            return -1

    # ------------------------------------------------------------ read side

    def committed_steps(self, host_id: Optional[int] = None) -> List[int]:
        """Ascending committed steps for a host on THIS filesystem
        (committed = dir renamed AND marker present)."""
        hdir = (
            self.host_dir
            if host_id is None
            else os.path.join(self.root, f"host-{host_id}")
        )
        steps = []
        try:
            names = os.listdir(hdir)
        except OSError:
            return []
        for name in names:
            if not name.startswith("step-") or name.endswith(PENDING_SUFFIX):
                continue
            if not os.path.exists(os.path.join(hdir, name, COMMIT_MARKER)):
                continue
            try:
                steps.append(int(name[len("step-"):]))
            except ValueError:
                continue
        return sorted(steps)

    def manifest(self, step: int, host_id: Optional[int] = None) -> Optional[dict]:
        hdir = (
            self.host_dir
            if host_id is None
            else os.path.join(self.root, f"host-{host_id}")
        )
        sdir = os.path.join(hdir, f"step-{step}")
        if not os.path.exists(os.path.join(sdir, COMMIT_MARKER)):
            return None
        try:
            with open(os.path.join(sdir, MANIFEST)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def read_shard(
        self, step: int, leaf_path: str, key: str, host_id: Optional[int] = None
    ) -> Optional[np.ndarray]:
        """Load + crc-verify one shard; None when missing or corrupt
        (the caller falls back to a peer / the persistent tier).

        The requested index does not have to match a stored index: a
        checkpoint written under a different layout (replicated opt
        state restored into a ``zero1=True`` run, or the reverse) is
        RESHARDED on read — the slice is cut out of one containing
        stored shard, or assembled from stored shards that tile it
        (:func:`covering_plan`). Both peer transports route through
        here (the REST wire server-side), so peers serve resharded
        reads too."""
        man = self.manifest(step, host_id=host_id)
        if man is None:
            return None
        entry = (man.get("leaves") or {}).get(leaf_path)
        if entry is None:
            return None
        shards = entry.get("shards") or {}
        plan = covering_plan(key, shards.keys())
        if plan is None:
            return None
        hdir = (
            self.host_dir
            if host_id is None
            else os.path.join(self.root, f"host-{host_id}")
        )

        def load(stored_key: str) -> Optional[np.ndarray]:
            shard = shards[stored_key]
            fpath = os.path.join(hdir, f"step-{step}", shard["file"])
            try:
                arr = np.load(fpath)
            except (OSError, ValueError):
                return None
            # streaming verify — tobytes here doubled peak host RAM per
            # shard on the restore path too (pipeline.crc32_array)
            if crc32_array(arr) != shard["crc"]:
                log.warning(
                    "local tier: crc mismatch for %s[%s] step %d host %s — "
                    "treating shard as lost",
                    leaf_path, stored_key, step,
                    host_id if host_id is not None else self.host_id,
                )
                return None
            return arr

        return compose_shard(key, plan, load)

    # ------------------------------------------------------------ chaos
    # helpers operating on a whole local root (any host) — used by the
    # fault matrix; deterministic given the injector's seeded rng.

    @staticmethod
    def corrupt_one_shard(root: str, rng) -> Optional[str]:
        """Flip bytes in one random committed shard file under ``root``.
        Returns the corrupted path, or None when nothing is committed."""
        candidates = []
        for host in sorted(os.listdir(root) if os.path.isdir(root) else []):
            hdir = os.path.join(root, host)
            if not host.startswith("host-") or not os.path.isdir(hdir):
                continue
            for sname in sorted(os.listdir(hdir)):
                sdir = os.path.join(hdir, sname)
                if sname.endswith(PENDING_SUFFIX) or not os.path.isdir(sdir):
                    continue
                if not os.path.exists(os.path.join(sdir, COMMIT_MARKER)):
                    continue
                for dirpath, _, files in os.walk(sdir):
                    for fn in files:
                        if fn.endswith(".npy"):
                            candidates.append(os.path.join(dirpath, fn))
        if not candidates:
            return None
        victim = rng.choice(sorted(candidates))
        with open(victim, "r+b") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            # stomp the tail (payload bytes, past the npy header)
            f.seek(max(0, size - 16))
            f.write(b"\xde\xad\xbe\xef" * 4)
        return victim

    @staticmethod
    def drop_host(root: str, rng, keep_at_least: int = 1) -> Optional[int]:
        """Delete one random host's entire local dir — the replaced-pod
        / lost-node simulation. Refuses to drop below ``keep_at_least``
        surviving hosts WITH DATA (an empty dir — a fresh pod that has
        not committed yet — neither counts as a survivor nor shields a
        populated tier from being the last one standing). Returns the
        dropped host id."""
        populated = []
        for n in sorted(os.listdir(root) if os.path.isdir(root) else []):
            hdir = os.path.join(root, n)
            if not n.startswith("host-") or not os.path.isdir(hdir):
                continue
            has_commit = any(
                s.startswith("step-") and not s.endswith(PENDING_SUFFIX)
                and os.path.exists(os.path.join(hdir, s, COMMIT_MARKER))
                for s in os.listdir(hdir)
            )
            if has_commit:
                try:
                    populated.append(int(n[len("host-"):]))
                except ValueError:
                    continue
        if len(populated) <= keep_at_least:
            return None
        victim = rng.choice(populated)
        shutil.rmtree(os.path.join(root, f"host-{victim}"), ignore_errors=True)
        return victim
