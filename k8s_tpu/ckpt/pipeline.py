"""Shared checkpoint-pipeline primitives: bounded staging + streaming crc.

Both halves of the checkpoint data path are pipelines over the same two
building blocks (docs/CHECKPOINT.md "Restore critical path" / "Save
critical path"):

- :class:`InflightGate` — the leaf-granular host-bytes admission gate.
  The restore planner acquires a leaf's estimated shard bytes before its
  fetches start and releases them once the device array is materialized;
  the save path acquires before a leaf's device→host copies start and
  releases once the background writer has flushed that leaf to disk and
  dropped the buffers. Either way the cap bounds the host RAM a multi-GB
  checkpoint can stage at once.
- a bounded ``ThreadPoolExecutor`` fanning out the per-shard work
  (I/O-bound reads on restore, device→host copies + nothing else on
  save — the writer thread owns all disk I/O).

:func:`crc32_array` is the shared integrity primitive: a chunked
``zlib.crc32`` over a contiguous memoryview of the array. The old
``zlib.crc32(arr.tobytes())`` spelling materialized a SECOND full copy
of every shard — doubling peak host RAM per shard on the save path and
again on the restore-verify path — for bytes that already sat
contiguous in memory. Chunking keeps each crc call's working set small
without ever copying the payload.
"""

from __future__ import annotations

import threading
import zlib
from typing import Optional

import numpy as np

# Default chunk for streaming crc: big enough that the Python-loop
# overhead vanishes, small enough to stay cache-friendly.
CRC_CHUNK_BYTES = 1 << 20


def crc32_array(arr: np.ndarray, chunk_bytes: int = CRC_CHUNK_BYTES) -> int:
    """crc32 of an ndarray's payload bytes WITHOUT the tobytes copy.

    Identical value to ``zlib.crc32(arr.tobytes())`` for any array
    (tobytes serializes in C order; so does the contiguous view), but
    zero-copy for contiguous input — ``memoryview(...).cast("B")`` is a
    view, and each ``zlib.crc32`` call reads a bounded slice of it. A
    non-contiguous array (never produced by the save/restore paths,
    which only handle fresh copies and ``np.load`` results) pays one
    compaction copy and nothing else.
    """
    a = np.ascontiguousarray(arr)
    mv = memoryview(a).cast("B") if a.ndim else memoryview(a.tobytes())
    crc = 0
    step = max(1, int(chunk_bytes))
    for off in range(0, len(mv), step):
        crc = zlib.crc32(mv[off:off + step], crc)
    return crc & 0xFFFFFFFF


class InflightGate:
    """Bounds the host bytes a checkpoint pipeline holds at once.

    Admission is LEAF-granular (the device-transfer unit): the
    scheduler acquires a whole leaf's estimated bytes before any of its
    per-shard work starts, and the consumer releases them when the
    leaf's buffers are dropped. Per-shard accounting would deadlock — a
    leaf bigger than the cap could never complete because release only
    happens per finished leaf — so a single leaf may exceed the cap
    alone (``inflight == 0`` always admits), and the cap bounds
    everything beyond it. ``cap <= 0`` disables the bound (peak still
    tracked)."""

    def __init__(self, cap_bytes: int):
        self.cap = int(cap_bytes)
        self._cond = threading.Condition()
        self.inflight = 0
        self.peak = 0
        self.waits = 0

    def acquire(self, n: int, abort: threading.Event) -> None:
        n = int(n)
        with self._cond:
            # n == 0 admits immediately: a leaf with no local shards
            # (device-narrowed tiers) must not queue behind an
            # oversized in-flight leaf just to stage zero bytes
            if self.cap > 0 and n > 0:
                waited = False
                while (self.inflight > 0 and self.inflight + n > self.cap
                       and not abort.is_set()):
                    if not waited:
                        waited = True
                        self.waits += 1
                    self._cond.wait(timeout=0.1)
            self.inflight += n
            self.peak = max(self.peak, self.inflight)

    def release(self, n: int) -> None:
        with self._cond:
            self.inflight -= int(n)
            self._cond.notify_all()


def stage_tree(tree, parallel: int = 8):
    """Host-staged deep copy of a pytree for a background committer.

    Every array leaf is copied device→host NOW (``np.array(copy=True)``
    — the donate-after contract: the caller may donate/mutate the live
    arrays the moment this returns; the committer only ever sees the
    copies), fanned across a bounded pool. Returns ``None`` when
    staging is unsafe: a leaf that is not fully addressable (multi-host
    sharding) cannot be host-copied by one process — those saves must
    go through orbax's own collective path synchronously.
    """
    import jax
    from concurrent.futures import ThreadPoolExecutor

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    for leaf in leaves:
        if (hasattr(leaf, "is_fully_addressable")
                and not leaf.is_fully_addressable):
            return None

    def copy(leaf):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return np.array(leaf, copy=True)
        return leaf  # plain python scalar/str: immutable, pass through

    if len(leaves) <= 1 or parallel <= 1:
        copies = [copy(x) for x in leaves]
    else:
        with ThreadPoolExecutor(
                max_workers=max(1, int(parallel)),
                thread_name_prefix="ckpt-stage") as pool:
            copies = list(pool.map(copy, leaves))
    return jax.tree_util.tree_unflatten(treedef, copies)


def est_leaf_bytes(shape, dtype) -> int:
    """Host bytes a staged copy of ``shape``/``dtype`` will hold —
    geometry only, no payload read (the admission-gate estimate)."""
    try:
        itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
    except TypeError:
        itemsize = 4
    n = 1
    for d in shape or ():
        n *= max(0, int(d))
    return max(1, n) * itemsize


__all__ = [
    "CRC_CHUNK_BYTES",
    "InflightGate",
    "crc32_array",
    "est_leaf_bytes",
    "stage_tree",
]
