"""Restore planner: pick the newest *consistent* step across tiers and
source every shard this host needs.

Decision table (docs/CHECKPOINT.md):

==========================  ============================================
situation                   restore
==========================  ============================================
local step > persistent,    local tier (fast path — no durable-store
all shards on own disk      read at all)
local step > persistent,    own disk + data-parallel peers for the
own shards missing/corrupt  missing indices ("local+peer")
no achievable local step    persistent tier (orbax)
newer than persistent
nothing anywhere            fresh start (restore returns None)
==========================  ============================================

A local step is *achievable* for this host when every shard index its
target sharding requires can be sourced — own committed+crc-valid file
first, else any peer advertising that (step, leaf, index). Uncommitted
steps (pending dirs without the COMMIT marker) are invisible by
construction: :meth:`LocalTier.committed_steps` never lists them.

Gang consistency: in a distributed run every process must restore the
SAME step — a host restoring step 6 next to a host restoring step 4 is
silent divergence. Two mechanisms compose:

- ``gang_consistent=True`` (the default for multi-process runs)
  replaces per-host achievability with **full global coverage**: a
  local step is a candidate only when the union of every visible
  manifest (own + peers) covers ALL indices of every leaf. Every host
  evaluates the same manifests, so every host reaches the same verdict
  with zero communication — and full coverage implies every host's
  subset is sourcible. Conservative by construction: a step only some
  hosts could restore is rejected for all of them.
- ``consensus`` (pluggable, e.g. a min-all-reduce over the
  coordination service) remains available as a belt-and-suspenders
  reduction on top; the single-host default is identity.

The chosen step is only a *plan* — if sourcing fails mid-way (a peer
died between planning and fetching), the planner degrades to the
persistent tier instead of wedging.

Execution is a **pipeline** (docs/CHECKPOINT.md "Restore critical
path"): shard fetches fan out across a bounded thread pool (I/O-bound
disk/HTTP reads, so near-linear in workers), admission is leaf-granular
against an in-flight-bytes gate so a multi-GB restore cannot blow host
RAM, and the consumer materializes device arrays in template order
while later leaves are still streaming — the ``data/prefetch.py``
double-buffer idiom applied to restore. Per-shard crc verification and
single-shard reroute-on-failure are unchanged from the serial path
(each worker runs the same sourcing ladder), so a parallel restore is
byte-identical to a serial one by construction.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from k8s_tpu.ckpt.local import (
    LocalTier,
    _leaf_paths,
    compose_shard,
    covering_plan,
    parse_index_key,
    required_indices,
    union_covering_plan,
)
from k8s_tpu.ckpt.pipeline import InflightGate

log = logging.getLogger(__name__)

def _full_indices(template_leaf) -> List[str]:
    """EVERY shard index of the leaf's global array across the whole
    sharding (not just this host's) — the gang-coverage vocabulary."""
    from k8s_tpu.ckpt.local import index_key

    sharding = getattr(template_leaf, "sharding", None)
    shape = tuple(getattr(template_leaf, "shape", ()))
    if sharding is None:
        return [index_key(tuple(slice(0, d) for d in shape), shape)]
    try:
        imap = sharding.devices_indices_map(shape)
    except Exception:
        return [index_key(tuple(slice(0, d) for d in shape), shape)]
    keys, seen = [], set()
    for idx in imap.values():
        key = index_key(idx, shape)
        if key not in seen:
            seen.add(key)
            keys.append(key)
    return keys


SOURCE_LOCAL = "local"
SOURCE_LOCAL_PEER = "local+peer"
SOURCE_PERSISTENT = "persistent"
SOURCE_NONE = "none"

# pipeline defaults (overridable via CheckpointPolicy /
# KTPU_CKPT_RESTORE_PARALLEL / KTPU_CKPT_RESTORE_INFLIGHT_MB)
DEFAULT_RESTORE_PARALLEL = 8
DEFAULT_INFLIGHT_BYTES = 1 << 30  # 1 GiB of host shard buffers


def _est_shard_bytes(leaf, key: str) -> int:
    """Host bytes one fetched shard will hold — geometry × itemsize
    from the template, no payload read. An estimate (a peer may serve a
    containing shard that is cut down after load), good enough for the
    admission gate."""
    dtype = getattr(leaf, "dtype", None)
    try:
        itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
    except TypeError:
        itemsize = 4
    try:
        slices = parse_index_key(key)
    except ValueError:
        return itemsize
    n = 1
    for s in slices or ():
        n *= max(0, int(s.stop) - int(s.start))
    return max(1, n) * itemsize


# The leaf-granular host-bytes admission gate, shared with the save
# pipeline since the zero-stall-save PR extracted it (ckpt/pipeline.py
# holds the class + its deadlock-avoidance contract).
_InflightGate = InflightGate


@dataclass
class RestorePlan:
    step: Optional[int]
    source: str
    # leaf path -> {index_key: host_id} for shards sourced from peers
    peer_shards: Dict[str, Dict[str, int]] = field(default_factory=dict)
    # leaf path -> {index_key: [(stored_key, host_id|None), ...]} for
    # shards no single manifest covers — assembled from pieces spread
    # across own disk (host None) and peers (a multi-host ZeRO-1
    # checkpoint restored into a replicated/coarser template)
    tiled: Dict[str, Dict[str, List[Tuple[str, Optional[int]]]]] = field(
        default_factory=dict)
    peer_fetches: int = 0


class RestorePlanner:
    """Plans and executes a restore across the local tier, peers, and
    the persistent (orbax) tier."""

    def __init__(
        self,
        local: Optional[LocalTier],
        persistent=None,
        transport=None,
        consensus: Optional[Callable[[int], int]] = None,
        devices=None,
        gang_consistent: bool = False,
        max_step: Optional[int] = None,
        parallel: int = DEFAULT_RESTORE_PARALLEL,
        inflight_bytes: int = DEFAULT_INFLIGHT_BYTES,
    ):
        self.local = local
        self.persistent = persistent  # train.checkpoint.CheckpointManager
        self.transport = transport
        self.consensus = consensus or (lambda step: step)
        # device subset defining "this host" (virtual-host simulation);
        # None = all of this process's devices
        self.devices = devices
        # multi-process mode: candidate steps must be FULLY covered by
        # the union of visible manifests (see module docstring) so every
        # host picks the same step without communicating
        self.gang_consistent = gang_consistent
        # restore ceiling ("last healthy step", docs/OBSERVABILITY.md
        # "Training health"): after a divergence verdict the operator
        # injects KTPU_CKPT_RESTORE_MAX_STEP on the restarted gang —
        # steps past it are invisible to planning on EVERY tier, so a
        # NaN checkpoint is never the restore target. Deterministic
        # like the gang rule: every host gets the same ceiling env.
        self.max_step = max_step
        # restore pipeline knobs: fetch-pool width and the in-flight
        # host-bytes cap (parallel=1 degrades to the serial schedule;
        # results are byte-identical either way)
        self.parallel = max(1, int(parallel))
        self.inflight_bytes = int(inflight_bytes)
        # phase timings + pipeline counters of the LAST restore() —
        # the MTTR evidence the manager exports (docs/CHECKPOINT.md
        # "Restore critical path"). fetch_s and device_s overlap by
        # design; their sum can exceed the restore wall time.
        self.last_restore_stats: Dict[str, Any] = {}

    # ------------------------------------------------------------ planning

    def _peer_steps(self) -> Dict[int, List[int]]:
        if self.transport is None:
            return {}
        try:
            return self.transport.steps()
        except Exception as e:
            log.warning("restore planner: peer step discovery failed (%s); "
                        "continuing without peers", e)
            return {}

    def _candidate_steps(
        self, peer_steps: Dict[int, List[int]]
    ) -> List[int]:
        """Local-tier candidate steps, newest first: own committed steps
        plus any step some peer committed (a replaced pod has NO own
        steps — peers are its only local-tier source)."""
        steps = set(self.local.committed_steps() if self.local else [])
        for peer_list in peer_steps.values():
            steps.update(peer_list)
        if self.max_step is not None:
            steps = {s for s in steps if s <= self.max_step}
        return sorted(steps, reverse=True)

    def _persistent_step(self) -> Optional[int]:
        """Newest persistent-tier step within the restore ceiling.
        Orbax managers expose ``all_steps`` so a bounded plan can reach
        past a too-new latest; a persistent tier without it degrades to
        all-or-nothing (its latest counts only when within bound)."""
        if self.persistent is None:
            return None
        try:
            if self.max_step is not None:
                all_steps = getattr(self.persistent, "all_steps", None)
                if callable(all_steps):
                    steps = [s for s in (all_steps() or [])
                             if s <= self.max_step]
                    return max(steps) if steps else None
            step = self.persistent.latest_step()
        except Exception as e:
            log.warning("restore planner: persistent tier step discovery "
                        "failed (%s)", e)
            return None
        if (self.max_step is not None and step is not None
                and step > self.max_step):
            return None
        return step

    def plan(self, template: Any) -> RestorePlan:
        """Choose the step + per-shard sources for this host. Template
        leaves are concrete arrays or ShapeDtypeStructs carrying the
        target shardings (same contract as CheckpointManager.restore)."""
        if self.transport is not None and hasattr(self.transport, "reset"):
            # a peer blacklisted during an earlier restore (booting,
            # transient timeout) gets a fresh chance each plan
            self.transport.reset()
        persistent_step = self._persistent_step()
        needed = {
            path: required_indices(leaf, devices=self.devices)
            for path, leaf in _leaf_paths(template)
        }
        # gang mode additionally demands the union of manifests cover
        # EVERY index of every leaf — the deterministic, communication-
        # free proof that each peer can restore this step too
        coverage = None
        if self.gang_consistent:
            coverage = {
                path: _full_indices(leaf)
                for path, leaf in _leaf_paths(template)
            }
        # one peer round-trip per plan, shared by candidate listing and
        # every per-step achievability check (a dead peer costs one
        # timeout, not one per retained step)
        peer_steps = self._peer_steps()
        best_local = None
        for step in self._candidate_steps(peer_steps):
            if persistent_step is not None and step <= persistent_step:
                break  # older than the durable tier — no point
            achievable, peer_shards, tiled, fetches = self._achievable(
                step, needed, coverage, peer_steps)
            if achievable:
                best_local = (step, peer_shards, tiled, fetches)
                break
        if best_local is not None:
            step = self.consensus(best_local[0])
            if step != best_local[0]:
                # the gang agreed on an older step (some peer couldn't
                # source ours) — re-plan shard sources for THAT step
                achievable, peer_shards, tiled, fetches = self._achievable(
                    step, needed, coverage, peer_steps)
                if not achievable:
                    return self._persistent_plan(persistent_step)
                best_local = (step, peer_shards, tiled, fetches)
            step, peer_shards, tiled, fetches = best_local
            return RestorePlan(
                step=step,
                source=SOURCE_LOCAL_PEER if fetches else SOURCE_LOCAL,
                peer_shards=peer_shards,
                tiled=tiled,
                peer_fetches=fetches,
            )
        return self._persistent_plan(persistent_step)

    def _persistent_plan(self, persistent_step) -> RestorePlan:
        if persistent_step is None:
            return RestorePlan(step=None, source=SOURCE_NONE)
        return RestorePlan(step=persistent_step, source=SOURCE_PERSISTENT)

    def _achievable(
        self, step: int, needed: Dict[str, List[str]],
        coverage: Optional[Dict[str, List[str]]] = None,
        peer_steps: Optional[Dict[int, List[int]]] = None,
    ) -> Tuple[bool, Dict[str, Dict[str, int]],
               Dict[str, Dict[str, List[Tuple[str, Optional[int]]]]], int]:
        """Can this host source every required shard at ``step``?
        Checks manifests only (no payload reads): own manifest first,
        then peers'. crc validation happens at fetch time; a corrupt
        own-shard is re-sourced from a peer then. A required index
        counts as held when a manifest's stored shards COVER it
        (covering_plan): a checkpoint saved under a different layout —
        replicated opt state vs a ``zero1=True`` template, or the
        reverse — is resharded on read instead of forcing the restore
        down to the persistent tier (or silently to a fresh start).
        When no SINGLE manifest covers an index (a multi-host ZeRO-1
        checkpoint: each host stores only its own 1/DP opt tile), the
        UNION of own + peer manifests may still tile it —
        union_covering_plan records the per-piece sources in ``tiled``.
        ``coverage`` (gang mode) additionally requires the union of
        visible manifests to hold EVERY listed index — proving every
        peer could restore this step too."""
        own = self.local.manifest(step) if self.local else None
        peer_manifests: Dict[int, dict] = {}
        peer_hosts = []
        if self.transport is not None:
            if peer_steps is None:
                peer_steps = self._peer_steps()
            for h, steps in sorted(peer_steps.items()):
                if step in steps:
                    peer_hosts.append(h)
        peer_shards: Dict[str, Dict[str, int]] = {}
        tiled: Dict[str, Dict[str, List[Tuple[str, Optional[int]]]]] = {}
        fetches = 0
        for path, keys in needed.items():
            own_entry = ((own or {}).get("leaves") or {}).get(path, {})
            own_keys = set((own_entry.get("shards") or {}))
            for key in keys:
                if covering_plan(key, own_keys) is not None:
                    continue
                host = self._peer_with(step, path, key, peer_hosts,
                                       peer_manifests)
                if host is not None:
                    peer_shards.setdefault(path, {})[key] = host
                    fetches += 1
                    continue
                union = union_covering_plan(
                    key, self._sources(path, own_keys, peer_hosts,
                                       peer_manifests))
                if union is None:
                    return False, {}, {}, 0
                tiled.setdefault(path, {})[key] = union
                fetches += sum(1 for _, src in union if src is not None)
        if coverage is not None:
            for path, keys in coverage.items():
                own_entry = ((own or {}).get("leaves") or {}).get(path, {})
                own_keys = set((own_entry.get("shards") or {}))
                for key in keys:
                    if covering_plan(key, own_keys) is not None:
                        continue
                    if self._peer_with(step, path, key, peer_hosts,
                                       peer_manifests) is not None:
                        continue
                    if union_covering_plan(
                            key, self._sources(path, own_keys, peer_hosts,
                                               peer_manifests)) is None:
                        return False, {}, {}, 0
        return True, peer_shards, tiled, fetches

    def _sources(self, path, own_keys, peer_hosts, peer_manifests):
        """Ordered ``[(source, stored keys), ...]`` for one leaf across
        every visible manifest — own disk first (source None), then
        peers. Peer manifests are already cached by the _peer_with pass
        that ran (and missed) before any union plan is attempted."""
        out = [(None, own_keys)]
        for h in peer_hosts:
            entry = ((peer_manifests.get(h) or {}).get("leaves")
                     or {}).get(path, {})
            out.append((h, set(entry.get("shards") or {})))
        return out

    def _peer_with(self, step, path, key, peer_hosts, peer_manifests):
        """First peer whose manifest can source ``key`` — exactly or by
        resharding from its stored shards (the transports' fetch routes
        through LocalTier.read_shard, which composes the same plan)."""
        for h in peer_hosts:
            man = peer_manifests.get(h)
            if man is None:
                try:
                    man = self.transport.manifest(step, h) or {}
                except Exception:
                    man = {}
                peer_manifests[h] = man
            entry = (man.get("leaves") or {}).get(path, {})
            if covering_plan(key, (entry.get("shards") or {}).keys()) \
                    is not None:
                return h
        return None

    # ------------------------------------------------------------ execution

    def restore(self, template: Any) -> Tuple[Optional[Any], RestorePlan]:
        """Execute the plan. Returns ``(tree, plan)``; tree is None for
        a fresh start. A mid-restore sourcing failure (peer died after
        planning, crc rot) degrades to the persistent tier.

        Virtual-host planners (``devices=`` a subset) are PLANNING-ONLY:
        execution materializes the full sharding, whose indices a
        subset-scoped plan never validated — restore through a
        full-device planner instead (what the soak's harness does)."""
        if self.devices is not None:
            raise ValueError(
                "RestorePlanner(devices=...) is planning-only; execute "
                "the restore with a full-device planner")
        t0 = time.perf_counter()
        plan = self.plan(template)
        self.last_restore_stats = {
            "plan_s": time.perf_counter() - t0,
            "fetch_s": 0.0,
            "device_s": 0.0,
            "parallel": self.parallel,
            "peak_inflight_bytes": 0,
            "gate_waits": 0,
        }
        if plan.source in (SOURCE_LOCAL, SOURCE_LOCAL_PEER):
            tree = self._restore_local(plan, template)
            if tree is not None:
                return tree, plan
            log.warning(
                "restore: local-tier restore of step %s failed mid-way; "
                "falling back to the persistent tier", plan.step)
            plan = self._persistent_plan(self._persistent_step())
        if plan.source == SOURCE_PERSISTENT:
            t1 = time.perf_counter()
            tree = self.persistent.restore(template, step=plan.step)
            # the orbax read is opaque to us: its whole wall time lands
            # in the fetch phase (there is no overlap to decompose)
            self.last_restore_stats["fetch_s"] += time.perf_counter() - t1
            if tree is None:
                return None, RestorePlan(step=None, source=SOURCE_NONE)
            return tree, plan
        return None, plan

    def _fetch_shard(self, plan: RestorePlan, path: str,
                     key: str) -> Optional[np.ndarray]:
        """The per-shard sourcing ladder — IDENTICAL to the old serial
        path, now also run from pool workers: tiled union pieces, else
        the planned peer (reroute to ANY peer when it died between
        planning and fetching), else own disk (reroute to any peer on a
        crc miss). crc validation lives in read_shard/the wire loaders;
        a None return fails the whole restore (degrade, never wedge)."""
        step = plan.step
        pieces = plan.tiled.get(path, {}).get(key)
        if pieces is not None:
            # assembled from shards no single manifest covers: own
            # tiles read locally, peer tiles fetched by their EXACT
            # stored key (read_shard serves exact keys trivially),
            # composed into the template slice
            src_of = dict(pieces)

            def load(k, _src=src_of, _step=step, _path=path):
                h = _src[k]
                if h is None:
                    return (self.local.read_shard(_step, _path, k)
                            if self.local is not None else None)
                return self.transport.fetch(_step, _path, k, h)

            return compose_shard(key, [k for k, _ in pieces], load)
        arr = None
        peer = plan.peer_shards.get(path, {}).get(key)
        if peer is None and self.local is not None:
            arr = self.local.read_shard(step, path, key)
            if arr is None and self.transport is not None:
                # own shard corrupt/raced away — any peer will do
                for h in sorted(self.transport.steps()):
                    arr = self.transport.fetch(step, path, key, h)
                    if arr is not None:
                        break
        elif peer is not None:
            arr = self.transport.fetch(step, path, key, peer)
            if arr is None:
                # planned peer died: try the others
                for h in sorted(self.transport.steps()):
                    if h == peer:
                        continue
                    arr = self.transport.fetch(step, path, key, h)
                    if arr is not None:
                        break
        return arr

    def _materialize_leaf(self, leaf, shard_data: Dict[str, np.ndarray]):
        """Host shards → one device-resident leaf in the TEMPLATE's
        placement. The jnp.copy re-buffers through XLA-allocated
        storage: the train step DONATES the restored state, and on jax
        0.4.x CPU gloo runtimes donating externally-created buffers
        (make_array_from_callback) corrupts the heap — the known
        "restored gloo worker" container bug, which surfaces either as
        a glibc abort or as SILENT corruption a step later (observed:
        bit-identical first post-restore step, garbage second). One
        device-side copy per leaf is noise next to the reads it
        follows."""
        import jax
        import jax.numpy as jnp

        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", None)
        sharding = getattr(leaf, "sharding", None)
        if sharding is None or not shape:
            # replicated / host / scalar leaf: the single full shard
            arr = next(iter(shard_data.values()))
            if dtype is not None:
                arr = np.asarray(arr, dtype=dtype)
            if sharding is not None:
                # honor the template placement — a committed
                # single-device scalar next to mesh-committed
                # arrays would poison the next jit call
                arr = jnp.copy(jax.device_put(arr, sharding))
            return arr

        def cb(idx, _data=shard_data, _shape=shape):
            from k8s_tpu.ckpt.local import index_key

            return _data[index_key(idx, _shape)]

        return jnp.copy(jax.make_array_from_callback(shape, sharding, cb))

    def _restore_local(self, plan: RestorePlan, template) -> Optional[Any]:
        """Execute a local/local+peer plan as a fetch→device pipeline.

        A scheduler thread admits leaves in template order against the
        in-flight-bytes gate and fans their shard fetches onto a
        bounded pool; the calling thread consumes leaves in the same
        order, materializing leaf N's device array while leaf N+1..
        stream from disk/peers (the prefetch.py double-buffer shape).
        Any failed shard aborts the whole pipeline promptly — the
        caller degrades to the persistent tier, never a wedge."""
        import jax
        from concurrent.futures import ThreadPoolExecutor
        from queue import Queue

        specs = []
        for path, leaf in _leaf_paths(template):
            keys = required_indices(leaf)
            est = sum(_est_shard_bytes(leaf, k) for k in keys)
            specs.append((path, leaf, keys, est))
        gate = _InflightGate(self.inflight_bytes)
        abort = threading.Event()
        fetch_t0 = time.perf_counter()
        fetch_end = [fetch_t0]
        fetch_end_lock = threading.Lock()

        def task(path, key):
            if abort.is_set():
                return None
            try:
                arr = self._fetch_shard(plan, path, key)
            except Exception as e:
                log.warning("restore: shard fetch %s[%s] raised (%s: %s)",
                            path, key, type(e).__name__, e)
                arr = None
            if arr is None:
                abort.set()  # fail fast: later fetches become no-ops
            now = time.perf_counter()
            with fetch_end_lock:  # last-finish max across pool workers
                if now > fetch_end[0]:
                    fetch_end[0] = now
            return arr

        ready: Queue = Queue()
        pool = ThreadPoolExecutor(
            max_workers=self.parallel, thread_name_prefix="ckpt-restore")

        def schedule():
            try:
                for path, leaf, keys, est in specs:
                    if abort.is_set():
                        break
                    gate.acquire(est, abort)
                    futs = [(k, pool.submit(task, path, k)) for k in keys]
                    ready.put((leaf, est, futs))
            finally:
                ready.put(None)

        sched = threading.Thread(target=schedule, daemon=True,
                                 name="ckpt-restore-sched")
        sched.start()
        leaves_out = []
        device_s = 0.0
        ok = True
        aborted = True  # stays True if the consumer loop dies mid-way
        try:
            while True:
                item = ready.get()
                if item is None:
                    aborted = abort.is_set()
                    break
                leaf, est, futs = item
                shard_data: Dict[str, np.ndarray] = {}
                for key, fut in futs:
                    arr = fut.result()
                    if arr is None:
                        ok = False
                    shard_data[key] = arr
                if ok and not abort.is_set():
                    t0 = time.perf_counter()
                    leaves_out.append(
                        self._materialize_leaf(leaf, shard_data))
                    device_s += time.perf_counter() - t0
                # drop host buffers BEFORE releasing their bytes — the
                # gate models host RAM, not queue slots
                shard_data.clear()
                gate.release(est)
        finally:
            # an exception escaping the consumer (a materialize
            # failure) must not strand the scheduler in gate.acquire
            # or leak the pool's threads — abort unblocks both
            # (aborted was captured first: a clean drain stays clean)
            abort.set()
            sched.join()
            pool.shutdown(wait=True)
        stats = self.last_restore_stats
        stats["fetch_s"] = max(0.0, fetch_end[0] - fetch_t0)
        stats["device_s"] = device_s
        stats["peak_inflight_bytes"] = gate.peak
        stats["gate_waits"] = gate.waits
        if not ok or aborted:
            return None
        flat, treedef = jax.tree_util.tree_flatten(template)
        return jax.tree_util.tree_unflatten(treedef, leaves_out)
