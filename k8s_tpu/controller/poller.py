"""Shared replica-status poller: one connection-reusing component for
the whole controller, replacing the fresh ``threading.Thread`` +
``urllib.request.urlopen`` spawned per replica per reconcile tick
(``trainer/training.py`` pre-refactor) — at O(1000) jobs that was
thousands of thread creations and TCP handshakes per sweep.

Design (docs/SCHEDULER.md "Event-driven core"):

- **Connection reuse**: one persistent ``http.client.HTTPConnection``
  per ``(host, port)`` endpoint, re-dialed only on error. A worker's
  obs endpoint is scraped over the same socket tick after tick.
- **Per-endpoint batching**: URLs in one sweep are grouped by
  endpoint; each endpoint's requests run sequentially on its one
  connection while distinct endpoints fan out across a *shared*
  bounded executor — parallelism across hosts, zero per-tick thread
  churn.
- **Accounting**: every request increments
  ``ktpu_controller_http_calls_total`` (by component), the satellite
  counter the idle-scaling regression test asserts on.

Process-global singleton via :func:`shared_poller` — every
TrainingJob's default HTTP fetch path routes through it, threaded and
event-driven modes alike.
"""

from __future__ import annotations

import json
import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from http.client import HTTPConnection
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlsplit

log = logging.getLogger(__name__)

DEFAULT_POOL_WORKERS = 16


class _Endpoint:
    """One (host, port) with a persistent connection + its own lock
    (requests to the same endpoint serialize — that IS the batching)."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.lock = threading.Lock()
        self.conn: Optional[HTTPConnection] = None

    def get_json(self, path: str, timeout: float) -> Optional[dict]:
        with self.lock:
            for attempt in (0, 1):
                try:
                    if self.conn is None:
                        self.conn = HTTPConnection(
                            self.host, self.port, timeout=timeout)
                    self.conn.request("GET", path)
                    resp = self.conn.getresponse()
                    body = resp.read()
                    if resp.status != 200:
                        return None
                    return json.loads(body)
                except Exception:
                    # stale keep-alive, connect refusal, bad JSON:
                    # drop the socket; retry once with a fresh dial,
                    # then report a miss
                    try:
                        if self.conn is not None:
                            self.conn.close()
                    except Exception:
                        pass
                    self.conn = None
                    if attempt:
                        return None
        return None

    def close(self) -> None:
        with self.lock:
            if self.conn is not None:
                try:
                    self.conn.close()
                except Exception:
                    pass
                self.conn = None


class SharedStatusPoller:
    """Fetch many JSON status endpoints in one batched, connection-
    reusing sweep on a shared bounded executor."""

    def __init__(self, workers: int = DEFAULT_POOL_WORKERS):
        self._workers = max(1, int(workers))
        self._executor: Optional[ThreadPoolExecutor] = None
        self._endpoints: Dict[Tuple[str, int], _Endpoint] = {}
        self._lock = threading.Lock()

    def _pool(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=self._workers,
                    thread_name_prefix="status-poller")
            return self._executor

    def _endpoint(self, host: str, port: int) -> _Endpoint:
        with self._lock:
            ep = self._endpoints.get((host, port))
            if ep is None:
                ep = self._endpoints[(host, port)] = _Endpoint(host, port)
            return ep

    def fetch_json_many(self, urls: Dict[int, str], timeout: float = 2.0,
                        component: str = "obs",
                        ) -> Dict[int, dict]:
        """GET every URL (key → url) and return key → parsed JSON for
        the ones that answered. Per-host failures are misses, never
        errors — a host that answers nothing is the gang-restart
        path's problem, not this one's."""
        from k8s_tpu.controller import metrics

        if not urls:
            return {}
        # group by endpoint: same-endpoint requests batch on one
        # connection; distinct endpoints fan out on the shared pool
        by_ep: Dict[Tuple[str, int], List[Tuple[int, str]]] = {}
        for key, url in urls.items():
            parts = urlsplit(url)
            host = parts.hostname or ""
            port = parts.port or 80
            path = parts.path or "/"
            if parts.query:
                path += "?" + parts.query
            by_ep.setdefault((host, port), []).append((key, path))
        metrics.CONTROLLER_HTTP_CALLS.inc(
            {"component": component}, by=float(len(urls)))
        out: Dict[int, dict] = {}
        out_lock = threading.Lock()

        def sweep(hp: Tuple[str, int],
                  reqs: List[Tuple[int, str]]) -> None:
            ep = self._endpoint(*hp)
            for key, path in reqs:
                payload = ep.get_json(path, timeout)
                if payload is not None:
                    with out_lock:
                        out[key] = payload

        if len(by_ep) == 1:
            ((hp, reqs),) = by_ep.items()
            sweep(hp, reqs)
            return out
        futures = [self._pool().submit(sweep, hp, reqs)
                   for hp, reqs in by_ep.items()]
        for f in futures:
            try:
                f.result(timeout=timeout + 3.0)
            except Exception:
                pass
        return out

    def close(self) -> None:
        with self._lock:
            endpoints = list(self._endpoints.values())
            self._endpoints.clear()
            executor, self._executor = self._executor, None
        for ep in endpoints:
            ep.close()
        if executor is not None:
            executor.shutdown(wait=False)


_shared: Optional[SharedStatusPoller] = None
_shared_lock = threading.Lock()


def shared_poller() -> SharedStatusPoller:
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = SharedStatusPoller()
        return _shared
