"""Operator metrics: Prometheus-style counters/gauges + text exposition.

Closes the observability gap SURVEY §5 flags in the reference ("no
Prometheus metrics, no K8s Events" — the event recorder was a
FakeRecorder, reference main.go:133). Dependency-free registry with
the text exposition format, served on the operator health port.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

LabelKV = Tuple[Tuple[str, str], ...]


def _escape_label_value(v: str) -> str:
    """Prometheus text-exposition label-value escaping: backslash,
    double-quote, and newline must be escaped or a single hostile/odd
    value (a job name with a quote, a multi-line error string) corrupts
    the WHOLE scrape. Order matters: backslash first."""
    return (
        v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _escape_help(v: str) -> str:
    """HELP-line escaping per the text format: backslash and newline."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


class _Metric:
    def __init__(self, name: str, help_text: str, mtype: str):
        self.name = name
        self.help = help_text
        self.type = mtype
        self.values: Dict[LabelKV, float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Optional[Dict[str, str]]) -> LabelKV:
        return tuple(sorted((labels or {}).items()))

    def clear(self) -> None:
        """Drop all label series (a component whose truth this metric
        mirrored has shut down)."""
        with self._lock:
            self.values.clear()

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} {self.type}"]
        with self._lock:
            for key, v in sorted(self.values.items()):
                if key:
                    lbl = ",".join(
                        f'{k}="{_escape_label_value(val)}"'
                        for k, val in key)
                    out.append(f"{self.name}{{{lbl}}} {v}")
                else:
                    out.append(f"{self.name} {v}")
        return out


class Counter(_Metric):
    def __init__(self, name, help_text):
        super().__init__(name, help_text, "counter")

    def inc(self, labels: Optional[Dict[str, str]] = None, by: float = 1.0):
        key = self._key(labels)
        with self._lock:
            self.values[key] = self.values.get(key, 0.0) + by

    def get(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self.values.get(self._key(labels), 0.0)


class Gauge(_Metric):
    def __init__(self, name, help_text):
        super().__init__(name, help_text, "gauge")

    def set(self, value: float, labels: Optional[Dict[str, str]] = None):
        with self._lock:
            self.values[self._key(labels)] = value

    def get(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self.values.get(self._key(labels), 0.0)


_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram per the text exposition format:
    ``<name>_bucket{le=...}`` (cumulative, ``+Inf`` last), ``_sum``,
    ``_count``. One instance per labelset, like the other types."""

    def __init__(self, name, help_text, buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_text, "histogram")
        self.buckets = tuple(sorted(buckets))
        # per-labelset: [counts per bucket] + sum + count
        self._series: Dict[LabelKV, List[float]] = {}
        self._sums: Dict[LabelKV, float] = {}
        self._counts: Dict[LabelKV, float] = {}

    def observe(self, value: float,
                labels: Optional[Dict[str, str]] = None) -> None:
        key = self._key(labels)
        with self._lock:
            counts = self._series.setdefault(
                key, [0.0] * len(self.buckets))
            for i, le in enumerate(self.buckets):
                if value <= le:
                    counts[i] += 1.0
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._counts[key] = self._counts.get(key, 0.0) + 1.0

    def count(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._counts.get(self._key(labels), 0.0)

    def sum(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._sums.get(self._key(labels), 0.0)

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._sums.clear()
            self._counts.clear()

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} {self.type}"]
        with self._lock:
            for key in sorted(self._series):
                base = [f'{k}="{_escape_label_value(v)}"'
                        for k, v in key]
                cum = 0.0
                for le, n in zip(self.buckets, self._series[key]):
                    cum = n  # buckets are already cumulative
                    lbl = ",".join(base + [f'le="{le:g}"'])
                    out.append(f"{self.name}_bucket{{{lbl}}} {cum}")
                lbl = ",".join(base + ['le="+Inf"'])
                out.append(
                    f"{self.name}_bucket{{{lbl}}} {self._counts[key]}")
                suffix = f"{{{','.join(base)}}}" if base else ""
                out.append(f"{self.name}_sum{suffix} {self._sums[key]}")
                out.append(
                    f"{self.name}_count{suffix} {self._counts[key]}")
        return out


class Registry:
    def __init__(self):
        self._metrics: List[_Metric] = []
        self.start_time = time.time()
        # samplers run at exposition time (gauges whose truth lives in
        # another component, e.g. informer cache sizes)
        self._collectors: List = []
        self._broken_collectors: set = set()

    def on_collect(self, fn) -> None:
        self._collectors.append(fn)

    def remove_collector(self, fn) -> None:
        if fn in self._collectors:
            self._collectors.remove(fn)
        # drop broken-status too: the closure would otherwise be pinned
        # (with everything it references) for the process lifetime, and
        # a re-registered collector would inherit its silenced warning
        self._broken_collectors.discard(fn)

    def counter(self, name: str, help_text: str) -> Counter:
        m = Counter(name, help_text)
        self._metrics.append(m)
        return m

    def gauge(self, name: str, help_text: str) -> Gauge:
        m = Gauge(name, help_text)
        self._metrics.append(m)
        return m

    def histogram(self, name: str, help_text: str,
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        m = Histogram(name, help_text, buckets)
        self._metrics.append(m)
        return m

    def expose(self) -> str:
        for fn in list(self._collectors):
            try:
                fn()
            except Exception as e:
                # a broken sampler must not break /metrics, but a
                # silently-frozen gauge is a debugging trap — log once
                if fn not in self._broken_collectors:
                    self._broken_collectors.add(fn)
                    import logging

                    logging.getLogger(__name__).warning(
                        "metrics collector %r failed (gauges it feeds "
                        "are now stale): %s", fn, e)
        lines: List[str] = []
        for m in self._metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


# process-global operator registry
REGISTRY = Registry()
EVENTS_HANDLED = REGISTRY.counter(
    "ktpu_operator_events_total", "Watch events dispatched, by type"
)
JOBS_STARTED = REGISTRY.counter(
    "ktpu_operator_jobs_started_total", "TrainingJob reconcilers started"
)
JOBS_TERMINAL = REGISTRY.counter(
    "ktpu_operator_jobs_terminal_total", "Jobs reaching a terminal state, by state"
)
RECONCILES = REGISTRY.counter(
    "ktpu_operator_reconciles_total", "Reconcile passes executed"
)
LIVE_JOBS = REGISTRY.gauge(
    "ktpu_operator_live_jobs", "Reconcilers currently tracked"
)
INFORMER_OBJECTS = REGISTRY.gauge(
    "ktpu_operator_informer_objects",
    "Objects held by the watch-fed informer cache, by kind",
)
INFORMER_SYNCED = REGISTRY.gauge(
    "ktpu_operator_informer_synced",
    "1 when every informer kind has completed its initial list",
)
GANG_RESTART_BACKOFF = REGISTRY.gauge(
    "ktpu_operator_gang_restart_backoff_seconds",
    "Current gang-restart backoff hold-off per job (0 = no hold-off)",
)
GANG_RESTARTS_DELAYED = REGISTRY.counter(
    "ktpu_operator_gang_restarts_delayed_total",
    "Gang restarts deferred by the backoff schedule, by job",
)
CHAOS_FAULTS = REGISTRY.counter(
    "ktpu_operator_chaos_faults_total",
    "Faults injected by the chaos matrix, by fault class",
)
# Multi-tier checkpoint goodput (k8s_tpu/ckpt, docs/CHECKPOINT.md).
# Registered here so any /metrics endpoint — operator health port or a
# trainer-side server — exposes them without new plumbing.
CKPT_RESTORES = REGISTRY.counter(
    "ktpu_ckpt_restores_total",
    "Checkpoint restores, by source tier (local / local+peer / persistent)",
)
CKPT_LOST_STEPS = REGISTRY.counter(
    "ktpu_ckpt_lost_steps_total",
    "Train steps lost to restarts (progress past the restored step)",
)
CKPT_LOST_STEPS_PER_RESTART = REGISTRY.gauge(
    "ktpu_ckpt_lost_steps_per_restart",
    "Mean steps lost per restart since process start",
)
CKPT_LOCAL_SAVES = REGISTRY.counter(
    "ktpu_ckpt_local_saves_total",
    "Local-tier snapshot commits",
)
CKPT_OVERHEAD_FRACTION = REGISTRY.gauge(
    "ktpu_ckpt_overhead_fraction",
    "Fraction of loop wall-clock spent in checkpoint saves",
)
CKPT_RESTORE_SECONDS = REGISTRY.gauge(
    "ktpu_ckpt_restore_seconds",
    "Wall seconds of the last restore, by phase (plan / fetch / device "
    "/ total; compile = the first post-restore step incl. XLA compile) "
    "— the MTTR breakdown, docs/CHECKPOINT.md 'Restore critical path'",
)
CKPT_SAVE_SECONDS = REGISTRY.gauge(
    "ktpu_ckpt_save_seconds",
    "Wall seconds of the last save, by phase (snapshot = the step-"
    "critical-path parallel device-to-host staging; serialize / commit "
    "= the background writer/committer legs, which overlap training) — "
    "docs/CHECKPOINT.md 'Save critical path'",
)
CKPT_SAVE_SKIPPED = REGISTRY.counter(
    "ktpu_ckpt_save_skipped_total",
    "Routed checkpoint saves skipped because the previous save was "
    "still committing in the background, by reason (writer_busy = "
    "local tier, committer_busy = persistent tier) — the visible cost "
    "of a save interval tighter than the disk/store can drain",
)
# Serving fleet (k8s_tpu/router, docs/SERVING.md "Fleet"). Registered
# process-global like the ckpt series: the router program's /metrics
# and any operator health port expose them without new plumbing.
ROUTER_REQUESTS = REGISTRY.counter(
    "ktpu_router_requests_total",
    "Requests routed (forward attempts), by replica index",
)
ROUTER_RETRIES = REGISTRY.counter(
    "ktpu_router_retries_total",
    "Forwards retried on a peer after a replica-side failure, by the "
    "replica that failed",
)
ROUTER_AFFINITY_HITS = REGISTRY.counter(
    "ktpu_router_affinity_hits_total",
    "Requests routed to their warm prefix-affine replica",
)
ROUTER_AFFINITY_FALLBACKS = REGISTRY.counter(
    "ktpu_router_affinity_fallbacks_total",
    "Affine replica saturated/dead; fell back to the score winner",
)
ROUTER_REPLICAS_READY = REGISTRY.gauge(
    "ktpu_router_replicas_ready",
    "Replicas the router currently considers routable",
)
SERVING_SCALE_EVENTS = REGISTRY.counter(
    "ktpu_router_scale_events_total",
    "SLO-autoscaler replica-count changes, by direction",
)
SERVING_REPLICAS = REGISTRY.gauge(
    "ktpu_router_serving_replicas",
    "Current desired serving replica count per job",
)
# Step-phase telemetry + gang straggler detection (k8s_tpu/obs,
# docs/OBSERVABILITY.md). Fed by the reconciler's per-host heartbeat
# aggregation over the workers' obs endpoints.
OBS_STEP_SKEW = REGISTRY.gauge(
    "ktpu_obs_step_skew_seconds",
    "Gang busy-step-time skew (slowest host - peer median), by job",
)
OBS_HOST_STEP_TIME = REGISTRY.gauge(
    "ktpu_obs_host_step_time_seconds",
    "Latest per-host train-step wall time, by job/host",
)
OBS_PHASE_SECONDS = REGISTRY.gauge(
    "ktpu_obs_phase_seconds",
    "Latest per-host step-phase duration, by job/host/phase",
)
OBS_STRAGGLERS = REGISTRY.counter(
    "ktpu_obs_stragglers_total",
    "StragglerDetected verdicts raised, by job",
)
# Training-health monitoring (k8s_tpu/obs/health.py,
# docs/OBSERVABILITY.md "Training health"): numerics verdicts + the
# goodput cost of divergence, fed by the reconciler's obs tick.
OBS_DIVERGENCE_RESTARTS = REGISTRY.counter(
    "ktpu_obs_divergence_restarts_total",
    "Gang restarts driven by a TrainingDiverged verdict, by job",
)
OBS_DIVERGED_STEPS = REGISTRY.counter(
    "ktpu_obs_diverged_steps_total",
    "Train steps discarded to divergence (progress past the last "
    "healthy step at verdict time), by job",
)
OBS_NUMERICS_WARNINGS = REGISTRY.counter(
    "ktpu_obs_numerics_warnings_total",
    "NumericsWarning verdicts raised (loss spike / plateau), by job/kind",
)
OBS_MEMORY_PRESSURE = REGISTRY.counter(
    "ktpu_obs_memory_pressure_total",
    "MemoryPressure events raised (HBM peak over the spec'd fraction "
    "of capacity), by job/host",
)
# Device HBM gauges (jax Device.memory_stats), exported by every
# process that serves an obs/metrics endpoint — trainer hosts and
# serving engines alike. Empty on backends that don't report (CPU).
OBS_HBM_IN_USE = REGISTRY.gauge(
    "ktpu_obs_hbm_bytes_in_use",
    "Device HBM bytes currently allocated, by device",
)
OBS_HBM_PEAK = REGISTRY.gauge(
    "ktpu_obs_hbm_bytes_peak",
    "Device HBM high-water mark since process start, by device",
)
OBS_HBM_LIMIT = REGISTRY.gauge(
    "ktpu_obs_hbm_bytes_limit",
    "Device HBM capacity visible to the allocator, by device",
)
# Cluster scheduler (k8s_tpu/sched, docs/SCHEDULER.md): the resource
# market's own telemetry — queue pressure, admission/preemption flow,
# quota burn, and the goodput priced into eviction decisions.
SCHED_QUEUE_DEPTH = REGISTRY.gauge(
    "ktpu_sched_queue_depth",
    "Jobs waiting for admission (incl. re-queued preemption victims), "
    "by queue",
)
SCHED_ADMITTED = REGISTRY.counter(
    "ktpu_sched_admitted_total",
    "Jobs admitted by the cluster scheduler, by queue",
)
SCHED_PREEMPTED = REGISTRY.counter(
    "ktpu_sched_preempted_total",
    "Running jobs preempted for a higher-priority job, by the victim's "
    "queue",
)
SCHED_QUOTA_USED = REGISTRY.gauge(
    "ktpu_sched_quota_used_chips",
    "Chips currently admitted against each queue's quota, by queue",
)
SCHED_SLICES_FREE = REGISTRY.gauge(
    "ktpu_sched_slices_free",
    "Unassigned slices in the fleet inventory, by accelerator",
)
SCHED_PREEMPT_LOST_STEPS = REGISTRY.counter(
    "ktpu_sched_preempt_lost_steps_total",
    "Steps at stake at each preemption decision (victim progress past "
    "its last checkpoint — the cost the scheduler priced; the preempt "
    "flush usually reduces the realized loss, visible in "
    "ktpu_ckpt_lost_steps_total), by victim job",
)
SCHED_TICK_SECONDS = REGISTRY.histogram(
    "ktpu_sched_tick_seconds",
    "Wall-clock duration of each pure scheduler decision pass "
    "(placement scoring + backfill pricing included; acting on the "
    "verdicts is reconcile work and is not counted)",
)
SCHED_BACKFILLS = REGISTRY.counter(
    "ktpu_sched_backfill_total",
    "Jobs admitted through a head-of-line reservation gap by "
    "conservative backfill, by queue",
)
SCHED_FRAGMENTATION = REGISTRY.gauge(
    "ktpu_sched_fragmentation",
    "Free-space fragmentation of each topology pool (1 − largest free "
    "ICI-contiguous block / total free slices; 0 = one whole block), "
    "by accelerator",
)
SCHED_CONTIGUITY_HIT_RATE = REGISTRY.gauge(
    "ktpu_sched_contiguity_hit_rate",
    "Fraction of multi-slice gang placements that landed on an "
    "ICI-contiguous block since operator start, by accelerator",
)
# Elastic gang resize (k8s_tpu/resize, docs/ELASTIC.md): the
# re-partitioning loop's own telemetry — how often gangs change shape,
# what each shrink put at stake, and the live DP degree per job.
RESIZE_TOTAL = REGISTRY.counter(
    "ktpu_resize_total",
    "Elastic gang resizes performed, by job and direction "
    "(shrink / grow)",
)
RESIZE_LOST_STEPS = REGISTRY.counter(
    "ktpu_resize_lost_steps_total",
    "Steps at stake at each shrink decision (gang progress past its "
    "last checkpoint — the flush usually reduces the realized loss, "
    "visible in ktpu_ckpt_lost_steps_total), by job",
)
RESIZE_DP = REGISTRY.gauge(
    "ktpu_resize_dp_degree",
    "Current data-parallel degree (slices) of each elastic gang after "
    "its last resize",
)
# Serving: device bytes held by the shared-prefix KV snapshot LRU
# (docs/SERVING.md "Fleet") — the count-bounded cache finally gets
# bytes accounting so fleet capacity planning has real numbers.
SERVING_PREFIX_CACHE_BYTES = REGISTRY.gauge(
    "ktpu_serving_prefix_cache_bytes",
    "Device bytes held by the engine's shared-prefix KV snapshot LRU",
)
# Disaggregated prefill/decode serving (docs/SERVING.md
# "Disaggregation"): the router's KV-handoff leg plus the decode
# pool's self-speculative fast path.
ROUTER_KV_TRANSFERS = REGISTRY.counter(
    "ktpu_router_kv_transfers_total",
    "Prefill→decode KV handoffs completed end to end (both legs)",
)
ROUTER_KV_FALLBACKS = REGISTRY.counter(
    "ktpu_router_kv_fallback_total",
    "Disaggregated requests served via a fallback rung (failed KV "
    "push, dead decode replica, or empty pool) — degraded latency, "
    "never a lost request",
)
ROUTER_KV_BYTES = REGISTRY.counter(
    "ktpu_router_kv_bytes_total",
    "Wire bytes of completed prefill→decode KV handoffs",
)
SERVING_SPEC_DECODE_ROUNDS = REGISTRY.gauge(
    "ktpu_serving_spec_decode_rounds",
    "Self-speculative verify rounds run by this engine (lifetime)",
)
SERVING_SPEC_DECODE_DRAFTED = REGISTRY.gauge(
    "ktpu_serving_spec_decode_drafted",
    "Draft tokens proposed by the n-gram drafter (lifetime)",
)
SERVING_SPEC_DECODE_ACCEPTED = REGISTRY.gauge(
    "ktpu_serving_spec_decode_accepted",
    "Draft tokens accepted by the verify step (lifetime); the bonus "
    "correction token is not counted",
)
# Live request migration + fleet-wide prefix directory (docs/SERVING.md
# "Live migration & prefix directory"): mid-stream slot moves instead
# of re-prefill, and cross-replica prefix snapshot fetches.
ROUTER_MIGRATIONS = REGISTRY.counter(
    "ktpu_router_migrations_total",
    "Mid-stream requests resumed on a peer via live KV migration, by "
    "reason (drain = operator-initiated resize, reactive = decode-pod "
    "death resumed from a mirrored slot)",
)
ROUTER_MIGRATION_FALLBACKS = REGISTRY.counter(
    "ktpu_router_migration_fallback_total",
    "Migration attempts that fell through to the next ladder rung "
    "(missing/expired mirror, dead target, resume rejected) — the "
    "request then pays the re-prefill the migration would have saved",
)
SERVING_PREFIX_REMOTE_HITS = REGISTRY.counter(
    "ktpu_serving_prefix_remote_hits_total",
    "Shared-prefix snapshots fetched from a holding peer on a local "
    "LRU miss (the prefix directory's fleet-wide hit path)",
)
# Event-driven control plane (docs/SCHEDULER.md "Event-driven core"):
# the shared reconciler core's own telemetry — how much work the queue
# is doing, how much it avoided, and what each pass cost.
RECONCILE_LATENCY = REGISTRY.histogram(
    "ktpu_controller_reconcile_latency_seconds",
    "Wall-clock duration of each reconcile pass through the shared "
    "worker pool",
)
WORKQUEUE_DEPTH = REGISTRY.gauge(
    "ktpu_controller_workqueue_depth",
    "Keys waiting in the shared reconciler work queue (ready + "
    "delayed requeues)",
)
WORKQUEUE_COALESCED = REGISTRY.counter(
    "ktpu_controller_workqueue_coalesced_total",
    "Queue adds merged into an already-queued or in-flight key — "
    "reconcile passes the coalescing saved",
)
RECONCILE_REQUEUES = REGISTRY.counter(
    "ktpu_controller_requeues_total",
    "Keys re-queued after a pass, by reason (poll = periodic "
    "obs/serving cadence, resync = slow backstop, error = exponential "
    "failure backoff)",
)
CONTROLLER_HTTP_CALLS = REGISTRY.counter(
    "ktpu_controller_http_calls_total",
    "Status-poll HTTP calls issued by the shared connection-reusing "
    "poller, by component (obs = worker heartbeat sweep, router = "
    "serving stats)",
)
SCHED_KICKS = REGISTRY.counter(
    "ktpu_sched_kicks_total",
    "Scheduler-tick kicks requested by job/capacity deltas (each "
    "wakes the event-driven tick loop at most once)",
)
SCHED_KICKS_COALESCED = REGISTRY.counter(
    "ktpu_sched_kicks_coalesced_total",
    "Scheduler kicks merged into an already-pending wakeup — full "
    "scheduler passes the dedup kick saved",
)
HEARTBEATS_PUSHED = REGISTRY.counter(
    "ktpu_controller_heartbeats_pushed_total",
    "Worker obs heartbeats PUSHED into the control plane (the "
    "/v1/heartbeat receiver) instead of polled",
)
