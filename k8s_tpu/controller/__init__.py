"""Controller: CRD registration, watch loop, event dispatch.

Analogue of reference ``pkg/controller/``.
"""

from k8s_tpu.controller.controller import Controller  # noqa: F401
from k8s_tpu.controller.watchdog import PanicTimer  # noqa: F401
