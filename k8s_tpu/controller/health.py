"""Operator health + metrics HTTP endpoint.

The chart declares a liveness probe against the operator
(``chart/templates/deployment.yaml`` -> ``.Values.operator.healthPort``);
this module is the listener behind it. The reference had no health
endpoint at all (liveness was "process up"); SURVEY §5 flags metrics as
a gap to close, and ``controller/metrics.py`` provides the registry —
this serves it.

Routes:
  ``/healthz``  -> 200 ``ok`` while the process is live (503 after
                   ``HealthServer.set_unhealthy()``, e.g. lost leadership
                   with no re-acquire).
  ``/metrics``  -> Prometheus text exposition from the process-global
                   :data:`k8s_tpu.controller.metrics.REGISTRY`.
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from k8s_tpu.controller import metrics

log = logging.getLogger(__name__)


class _Handler(BaseHTTPRequestHandler):
    server: "_Server"

    def do_GET(self):  # noqa: N802 (http.server API)
        if self.path in ("/healthz", "/", "/readyz"):
            healthy = self.server.owner.healthy
            body = b"ok\n" if healthy else b"unhealthy\n"
            self.send_response(200 if healthy else 503)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/metrics":
            body = self.server.owner.registry.expose().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_response(404)
            self.end_headers()

    def log_message(self, fmt, *args):  # kubelet probes every few seconds
        log.debug("health: " + fmt, *args)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    owner: "HealthServer"


class HealthServer:
    """Tiny embedded HTTP server for liveness + /metrics.

    ``port=0`` binds an ephemeral port (tests); the bound port is
    available as :attr:`port` after :meth:`start`.
    """

    def __init__(self, port: int, registry: Optional[metrics.Registry] = None,
                 host: str = "0.0.0.0"):
        self.registry = registry or metrics.REGISTRY
        self.healthy = True
        self._server = _Server((host, port), _Handler)
        self._server.owner = self
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HealthServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="ktpu-health"
        )
        self._thread.start()
        log.info("health endpoint listening on :%d (/healthz, /metrics)", self.port)
        return self

    def set_unhealthy(self) -> None:
        self.healthy = False

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
