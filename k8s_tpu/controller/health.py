"""Operator health + metrics HTTP endpoint.

The chart declares a liveness probe against the operator
(``chart/templates/deployment.yaml`` -> ``.Values.operator.healthPort``);
this module is the listener behind it. The reference had no health
endpoint at all (liveness was "process up"); SURVEY §5 flags metrics as
a gap to close, and ``controller/metrics.py`` provides the registry —
this serves it.

Routes:
  ``/healthz``  -> 200 ``ok`` while the process is live (503 after
                   ``HealthServer.set_unhealthy()``, e.g. lost leadership
                   with no re-acquire).
  ``/metrics``  -> Prometheus text exposition from the process-global
                   :data:`k8s_tpu.controller.metrics.REGISTRY`.
  ``/debug/flightrecorder``
                -> the attached flight recorder's ring of recent spans/
                   events (404 when none attached) — the live half of
                   the post-mortem surface (docs/OBSERVABILITY.md).
  ``/debug/profile?seconds=N``
                -> run the attached profiler hook for N seconds (a
                   bounded jax.profiler trace into the flight-recorder
                   dir on trainer obs endpoints; 404 when no hook) and
                   return its JSON result — the on-demand profiling
                   surface (docs/OBSERVABILITY.md).
  ``POST /v1/heartbeat/<ns>/<name>/<host>``
                -> pushed obs heartbeat (the event-driven control
                   plane's inbound path, docs/SCHEDULER.md): JSON body
                   is routed to the owning reconciler via the attached
                   ``heartbeat_sink``; 404 when no sink or unknown job.
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from k8s_tpu.controller import metrics

log = logging.getLogger(__name__)


class _Handler(BaseHTTPRequestHandler):
    server: "_Server"

    def do_GET(self):  # noqa: N802 (http.server API)
        if self.path in ("/healthz", "/", "/readyz"):
            healthy = self.server.owner.healthy
            provider = self.server.owner.stats_provider
            if provider is not None:
                # stats-enriched healthz (the serving-server idiom):
                # liveness verdict + a JSON block of component stats,
                # e.g. checkpoint goodput (docs/CHECKPOINT.md)
                import json

                try:
                    # default=str: numpy scalars out of a training loop
                    # must not break serialization; the whole pipeline
                    # stays inside the guard — an unserializable stats
                    # dict must never break the LIVENESS probe either
                    body = json.dumps(
                        {"ok": healthy, **(provider() or {})},
                        default=str).encode() + b"\n"
                except Exception as e:  # stats must never break liveness
                    body = json.dumps(
                        {"ok": healthy, "stats_error": str(e)}
                    ).encode() + b"\n"
                ctype = "application/json"
            else:
                body = b"ok\n" if healthy else b"unhealthy\n"
                ctype = "text/plain; charset=utf-8"
            self.send_response(200 if healthy else 503)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/metrics":
            body = self.server.owner.registry.expose().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path.split("?", 1)[0] == "/debug/profile":
            import json
            from urllib.parse import parse_qs, urlsplit

            hook = self.server.owner.profiler
            if hook is None:
                self.send_response(404)
                self.end_headers()
                return
            try:
                q = parse_qs(urlsplit(self.path).query)
                seconds = float((q.get("seconds") or ["2"])[0])
            except ValueError:
                seconds = 2.0
            # the hook blocks this handler thread for the capture
            # window (ThreadingHTTPServer — probes/scrapes unaffected)
            # and never raises (capture_profile's contract)
            try:
                result = hook(seconds)
            except Exception as e:  # a hook bug must not kill the probe
                result = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            body = json.dumps(result, default=str).encode() + b"\n"
            self.send_response(200 if result.get("ok") else 503)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/debug/flightrecorder":
            import json

            rec = self.server.owner.flight_recorder
            if rec is None:
                self.send_response(404)
                self.end_headers()
                return
            body = json.dumps(
                {"entries": rec.snapshot()}, default=str
            ).encode() + b"\n"
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_response(404)
            self.end_headers()

    def do_POST(self):  # noqa: N802 (http.server API)
        parts = self.path.strip("/").split("/")
        # /v1/heartbeat/<ns>/<name>/<host>
        if len(parts) == 5 and parts[:2] == ["v1", "heartbeat"]:
            import json

            sink = self.server.owner.heartbeat_sink
            if sink is None:
                self.send_response(404)
                self.end_headers()
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                host = int(parts[4])
                ok = bool(sink(parts[2], parts[3], host, payload))
            except Exception as e:  # malformed push must not 500-loop
                log.debug("heartbeat push rejected: %s", e)
                ok = False
            self.send_response(204 if ok else 404)
            self.end_headers()
        else:
            self.send_response(404)
            self.end_headers()

    def log_message(self, fmt, *args):  # kubelet probes every few seconds
        log.debug("health: " + fmt, *args)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    owner: "HealthServer"
    # the stock listen backlog of 5 drops SYNs when a liveness probe, a
    # Prometheus scrape, a straggler-aggregation poll, and a flight-
    # recorder pull land together — each drop costs a 1s TCP retransmit
    # (the same cliff measured and fixed in the router/frontend, PR 7)
    request_queue_size = 128


class HealthServer:
    """Tiny embedded HTTP server for liveness + /metrics.

    ``port=0`` binds an ephemeral port (tests); the bound port is
    available as :attr:`port` after :meth:`start`.
    """

    def __init__(self, port: int, registry: Optional[metrics.Registry] = None,
                 host: str = "0.0.0.0", stats_provider=None,
                 flight_recorder=None, profiler=None):
        self.registry = registry or metrics.REGISTRY
        self.healthy = True
        # optional callable returning a dict merged into the /healthz
        # body (checkpoint goodput, scheduler stats, ...); None keeps
        # the plain "ok" contract
        self.stats_provider = stats_provider
        # optional k8s_tpu.obs.trace.FlightRecorder served live at
        # /debug/flightrecorder (the on-disk dump covers the dead-pod
        # case; this route covers the live one)
        self.flight_recorder = flight_recorder
        # optional callable(seconds) -> dict behind /debug/profile —
        # the on-demand jax.profiler capture on trainer obs endpoints
        # (k8s_tpu.obs.health.capture_profile); None keeps the route 404
        self.profiler = profiler
        # optional callable(ns, name, host, payload) -> bool behind
        # POST /v1/heartbeat/... — Controller.ingest_heartbeat when the
        # operator wires it; None keeps the route 404
        self.heartbeat_sink = None
        self._server = _Server((host, port), _Handler)
        self._server.owner = self
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HealthServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="ktpu-health"
        )
        self._thread.start()
        log.info("health endpoint listening on :%d (/healthz, /metrics)", self.port)
        return self

    def set_unhealthy(self) -> None:
        self.healthy = False

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
