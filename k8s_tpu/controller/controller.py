"""The operator controller: CRD registration, watch loop, dispatch.

Analogue of reference ``pkg/controller/controller.go``: holds the live
job map (:46-61); ``run()`` = init-resource with retry (:86-96) + the
event pump with a per-event watchdog (:109-119); Added → new
TrainingJob thread, Deleted → ``Delete()``, Modified forwarded but not
acted on (:123-170); ``find_all_jobs`` re-adopts existing jobs on
startup (:172-201) so an operator crash/restart is seamless; CRD
create + established wait (:234-286); watch staleness (410 Gone) →
``OutdatedVersionError`` → relist and re-watch (:292-376).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from k8s_tpu.api import errors
from k8s_tpu.api.client import KubeClient
from k8s_tpu.api.crd_client import TpuJobClient
from k8s_tpu import utils
from k8s_tpu.controller.reconciler import ReconcilerCore
from k8s_tpu.controller.watchdog import PanicTimer
from k8s_tpu.trainer.labels import JOB_NAME_LABEL
from k8s_tpu.robustness.backoff import Backoff, BackoffPolicy
from k8s_tpu.sched import (
    ClusterScheduler,
    JobRequest,
    PoolTopology,
    Preemption,
    SliceInventory,
    TickResult,
    footprint_of,
)
from k8s_tpu.spec import ControllerConfig, TpuJob, TpuJobPhase
from k8s_tpu.trainer.training import TrainingJob

log = logging.getLogger(__name__)

INIT_RETRY_WAIT = 30.0  # reference controller.go:33
WATCHDOG_DEADLINE = 60.0  # reference controller.go:110
# Event-driven mode's scheduler-tick backstop: every job/capacity
# delta kicks a tick explicitly, so the periodic pass is demoted from
# sched_interval (1s) to a slow catch-all for anything a kick missed.
SCHED_BACKSTOP_SECONDS = 30.0

# Requeue schedule for the controller's outer loop: init failures,
# relist-after-410, and pump crashes all hold off through this (capped
# at the reference's fixed 30s init wait, which it replaces).
REQUEUE_POLICY = BackoffPolicy(
    base=0.5, factor=2.0, cap=INIT_RETRY_WAIT, jitter=0.5, reset_after=120.0
)


def _dp_footprint(fp, dp: int):
    """A gang footprint rescaled to ``dp`` slices, preserving its own
    chips-per-slice ratio — the ONE way an elastic gang's charge is
    derived at any width (pricing, re-admission, resize re-charge), so
    the ledger can never see two inconsistent derivations of the same
    job (docs/ELASTIC.md)."""
    per_slice = fp.chips // max(1, fp.slices)
    return type(fp)(fp.accelerator, slices=dp, chips=dp * per_slice)


class Controller:
    def __init__(
        self,
        client: KubeClient,
        job_client: TpuJobClient,
        config: Optional[ControllerConfig] = None,
        namespace: Optional[str] = None,
        reconcile_interval: float = 8.0,
        watchdog_deadline: float = WATCHDOG_DEADLINE,
        sched_interval: float = 1.0,
    ):
        self.client = client
        self.job_client = job_client
        self.config = config or ControllerConfig()
        self.namespace = namespace
        self.reconcile_interval = reconcile_interval
        self.watchdog_deadline = watchdog_deadline
        self.jobs: Dict[str, TrainingJob] = {}  # reference jobs map, :46-61
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._owns_informer = False
        self._informer_sampler = None
        # Cluster scheduler (docs/SCHEDULER.md): ON iff the controller
        # config declares an accelerator fleet. With it on, jobs enter
        # QUEUED and a reconciler only spawns on admission; with it off
        # (the default empty fleet) every path below is byte-for-byte
        # today's start-immediately behavior.
        self.sched_interval = sched_interval
        self.scheduler: Optional[ClusterScheduler] = None
        if self.config.fleet:
            # fleet entries with a topology block get named slices on
            # the ICI-pod grid; the placement scorer packs them only
            # under the backfill+pack policy (docs/SCHEDULER.md
            # "Placement" — every policy is A/B-proven on sched_bench
            # before it runs a real fleet)
            policy = getattr(
                self.config, "scheduler_policy", "fifo-reserve")
            topology = {
                accel: PoolTopology(int(shape[0]), int(shape[1]))
                for accel, shape in (
                    getattr(self.config, "fleet_topology", None)
                    or {}).items()
            }
            self.scheduler = ClusterScheduler(
                SliceInventory(self.config.fleet,
                               topology=topology,
                               packing=policy == "backfill+pack"),
                quotas=self.config.scheduler_quotas,
                cost_fn=self._preemption_cost,
                preemption_cooldown=self.config.scheduler_cooldown_seconds,
                backfill=policy in ("backfill", "backfill+pack"),
            )
            # capacity-return tick (docs/ELASTIC.md): a freed slice
            # nudges every elastic gang's reconciler so grow decisions
            # land within a tick, not a polling interval
            self.scheduler.inventory.on_capacity(self._on_capacity_return)
        self._sched_lock = threading.RLock()
        self._sched_thread: Optional[threading.Thread] = None
        # key → blocked category last written into its Queued condition
        # (diagnosability): a condition is appended only when the WHY
        # changes, never per tick — a parked job must not accrete a
        # thousand identical conditions
        self._blocked_surfaced: Dict[str, str] = {}
        # dedup "kick" for the event-driven scheduler tick: a burst of
        # job deltas (N completions, a mass delete) wakes the tick loop
        # ONCE instead of running N full scheduler passes
        self._sched_kick_pending = threading.Event()
        # Event-driven reconciler core (docs/SCHEDULER.md "Event-driven
        # core", default ON): one informer-fed coalescing work queue
        # drained by a bounded worker pool replaces the thread-per-job
        # loops; reconciles fire on events + rate-limited requeues.
        core_workers = self.config.reconcile_workers
        if self.config.max_concurrent_reconciles:
            # the legacy concurrency knob stays meaningful in event
            # mode: it caps the worker pool
            core_workers = min(core_workers,
                               self.config.max_concurrent_reconciles)
        self.core: Optional[ReconcilerCore] = (
            ReconcilerCore(workers=core_workers)
            if self.config.event_driven else None)
        self._informer_listener = None
        # O(100) hygiene (LEGACY threaded mode only): one shared
        # semaphore bounds concurrent reconcile ticks across every
        # TrainingJob thread (0 = off); the core's worker pool subsumes
        # it in event-driven mode
        n = self.config.max_concurrent_reconciles
        self._reconcile_limiter = (
            threading.BoundedSemaphore(n)
            if n and n > 0 and self.core is None else None)
        # test/e2e seam: build a per-job worker-stats fetcher (the
        # heartbeat source preemption pricing reads) for reconcilers
        # the CONTROLLER spawns — outside a cluster there is no
        # Service DNS for the default HTTP fetcher to resolve
        self.worker_stats_fetcher_factory = None

    # ------------------------------------------------------------ bootstrap

    def init_resource(self) -> int:
        """Create the CRD if needed and wait Established (reference
        initResource + createCRD, controller.go:213-286). Returns the
        resourceVersion to start watching from."""
        if self.client.informer is None:
            # the informer replaces the reference's per-tick polling
            # (SURVEY §7.2 #4): one watch stream per kind, reconcilers
            # read the cache — not O(replicas) GETs every 8s
            inf = self.client.start_informer(namespace=self.namespace)
            self._owns_informer = True

            from k8s_tpu.controller import metrics

            stopped = threading.Event()
            sample_lock = threading.Lock()

            def sample_informer(inf=inf, stopped=stopped,
                                lock=sample_lock):
                # the lock serializes the sampler body against stop()'s
                # gauge reset: without it a scrape that passed the flag
                # check could finish its writes AFTER the reset and
                # leave a dead informer reported synced forever (the
                # sampler is removed, so nothing would correct it)
                with lock:
                    if stopped.is_set():
                        metrics.INFORMER_SYNCED.set(0.0)
                        metrics.INFORMER_OBJECTS.clear()
                        return
                    for kind, cache in inf.caches.items():
                        with cache.lock:
                            n = len(cache.objects)
                        metrics.INFORMER_OBJECTS.set(
                            float(n), {"kind": kind})
                    metrics.INFORMER_SYNCED.set(
                        1.0 if inf.synced else 0.0)

            self._informer_sampler = sample_informer
            self._informer_sampler_stopped = stopped
            self._informer_sampler_lock = sample_lock
            metrics.REGISTRY.on_collect(sample_informer)
        if self.core is not None:
            self.core.start()
            inf = self.client.informer
            if inf is not None and self._informer_listener is None:
                # informer-fed kicks: a Pod/Job delta for an owned job
                # wakes exactly that job's reconcile key — how a
                # quiescent job learns its gang finished without a poll
                self._informer_listener = self._on_informer_event
                inf.add_listener(self._informer_listener)
        try:
            self.job_client.create_crd_definition()
        except errors.AlreadyExistsError:
            pass
        utils.retry(0.5, 120, self.job_client.crd_established)
        rv = self.find_all_jobs()
        self._ensure_sched_loop()
        return rv

    def find_all_jobs(self) -> int:
        """Adopt pre-existing TpuJobs (reference findAllTfJobs,
        controller.go:172-201): resource creation is idempotent, so
        re-adopting a live job is safe."""
        rv = self.client.cluster.resource_version
        for job in self.job_client.list(self.namespace):
            if job.status.is_failed():
                log.warning("ignoring failed job %s", job.key)
                continue
            if job.key not in self.jobs:
                self._start_job(job)
        return rv

    # ------------------------------------------------------------ dispatch

    def _start_job(self, job: TpuJob) -> None:
        """Entry for a newly-seen job (watch ADDED / startup adoption).
        Without a scheduler this spawns the reconciler immediately
        (reference behavior). With one (``config.fleet`` non-empty) the
        scheduler is consulted first: NONE-phase jobs park in QUEUED
        until admitted; already-materialized jobs (operator restart)
        are adopted straight into the ledger — a restart must never
        re-queue a gang that is physically running."""
        if self.scheduler is None:
            self._spawn_reconciler(job)
            return
        phase = job.status.phase
        if phase == TpuJobPhase.QUEUED:
            # re-adopted queued job (operator restart): back in line,
            # original status already says Queued
            self.scheduler.submit(self._request_for(job))
        elif phase == TpuJobPhase.NONE:
            self._submit_queued(job)
        elif phase in (TpuJobPhase.CREATING, TpuJobPhase.RUNNING,
                       TpuJobPhase.CLEANUP):
            self.scheduler.adopt_running(self._request_for(job))
            self._spawn_reconciler(job)
        else:  # terminal phases: reconciler handles bookkeeping, no charge
            self._spawn_reconciler(job)
        self._sched_kick()

    def _spawn_reconciler(self, job: TpuJob) -> bool:
        from k8s_tpu.controller import metrics

        old = self.jobs.get(job.key)
        if old is not None:
            # re-admission after preemption: the previous reconciler
            # exited when it parked the job in QUEUED — quiesce it
            # before the fresh one takes the key (two reconcilers on
            # one job would race every status write)
            old.stop()
            old.join(timeout=10)
            if old.is_alive():
                # still tearing down (e.g. deletes stuck behind an
                # apiserver brown-out): spawning now would put two
                # reconcilers on one job — refuse; the caller re-queues
                log.error("job %s: previous reconciler still alive "
                          "after stop; deferring respawn", job.key)
                return False
        tj = TrainingJob(self.client, self.job_client, job)
        tj.reconcile_limiter = self._reconcile_limiter
        if self.core is not None:
            tj.attach_core(self.core, self.config.resync_seconds)
        if self.scheduler is not None:
            tj.on_terminal = self._on_job_terminal
            # elastic resize (docs/ELASTIC.md): the reconciler's
            # inventory view + the atomic ledger re-charge
            tj.capacity_fn = (
                lambda key=job.key: self._attainable_slices(key))
            tj.on_resize = self._on_job_resize
        if self.worker_stats_fetcher_factory is not None:
            try:
                tj.worker_stats_fetcher = \
                    self.worker_stats_fetcher_factory(tj)
            except Exception as e:
                log.warning("job %s: stats fetcher factory: %s",
                            job.key, e)
        self.jobs[job.key] = tj
        tj.start(self.config, self.reconcile_interval)
        metrics.JOBS_STARTED.inc()
        metrics.LIVE_JOBS.set(len(self.jobs))
        self.client.record_event(
            job.metadata.namespace,
            {"kind": "TpuJob", "name": job.metadata.name},
            "Started",
            f"reconciler started for {job.key}",
        )
        return True

    # ------------------------------------------------------------ scheduler

    def _request_for(self, job: TpuJob) -> JobRequest:
        s = job.spec.scheduling
        priority = 0
        queue = "default"
        preemptible = True
        estimate = 0.0
        if s is not None:
            try:
                priority = int(s.priority)
            except (TypeError, ValueError):
                priority = 0  # validation rejects it properly at setup
            queue = s.queue or "default"
            preemptible = bool(s.preemptible)
            try:
                estimate = max(
                    0.0, float(s.runtime_estimate_seconds or 0.0))
            except (TypeError, ValueError):
                estimate = 0.0
        fp = footprint_of(job.spec)
        dp = getattr(job.status, "dp_degree", 0) or 0
        if (dp > 0 and job.spec.elastic is not None
                and job.spec.serving is None and not fp.empty):
            # a resized elastic gang is priced at its CURRENT width
            # (status.dp_degree), not the spec's original numSlices —
            # re-admission/adoption must charge what the reconciler
            # will actually materialize (docs/ELASTIC.md)
            fp = _dp_footprint(fp, dp)
        return JobRequest(
            key=job.key, footprint=fp,
            priority=priority, queue=queue, preemptible=preemptible,
            runtime_estimate_s=estimate,
        )

    def _preemption_cost(self, key: str) -> int:
        """The scheduler's eviction pricing: steps the victim has run
        past its last checkpoint, read from the reconciler's freshest
        heartbeat sweep (PR 9's goodput block). Unknown ⇒ 0."""
        tj = self.jobs.get(key)
        return tj.preemption_cost() if tj is not None else 0

    # -------------------------------------------------------- elastic

    def _attainable_slices(self, key: str) -> Optional[int]:
        """Slices job ``key`` could hold right now = its current charge
        + the pool's (unclamped) headroom — the elastic resizer's
        inventory view (docs/ELASTIC.md). A pool driven UNDER its usage
        by a permanent loss yields attainable < held: the shrink
        trigger. None when the job holds no accelerator charge."""
        sched = self.scheduler
        if sched is None:
            return None
        held_fp = sched.inventory.holder(key)
        if held_fp is None or held_fp.empty:
            return None
        free = (sched.inventory.capacity(held_fp.accelerator)
                - sched.inventory.used(held_fp.accelerator))
        return max(0, held_fp.slices + free)

    def _on_job_resize(self, tj: TrainingJob, old_dp: int,
                       new_dp: int, trigger: str = "") -> bool:
        """The reconciler's ledger re-charge at a resize verdict: swap
        the job's charge for the reshaped footprint ATOMICALLY (shrink
        frees slices, grow re-charges them — the high-water mark never
        sees both shapes). An INVENTORY-triggered shrink must re-verify
        the pool deficit inside the ledger's critical section: two
        elastic gangs sharing a pool both observe one revoked slice,
        and without the check both would surrender a slice for it
        (dead-host shrinks carry their own evidence and skip it). A
        shrink immediately re-runs the decision core: the freed slices
        may admit a queued job this tick."""
        sched = self.scheduler
        if sched is None:
            return True  # no ledger to keep consistent
        key = tj.job.key
        req = sched.running_request(key)
        if req is None or req.footprint.empty:
            return True  # zero-footprint / unscheduled: nothing charged
        # scale the RUNNING charge, not a fresh topology lookup: the
        # charge's own slices/chips ratio is consistent by construction
        new_fp = _dp_footprint(req.footprint, new_dp)
        if not sched.resize_running(
                key, new_fp,
                require_pool_deficit=(new_dp < old_dp
                                      and trigger == "inventory")):
            return False
        self._export_sched_metrics()
        if new_dp < old_dp:
            self._sched_kick()
        return True

    def _on_capacity_return(self, accelerator: str) -> None:
        """Inventory capacity-return listener: wake every running
        elastic gang's reconciler so the grow hold starts counting NOW
        (best-effort — the periodic tick remains the backstop)."""
        for tj in list(self.jobs.values()):
            try:
                if tj.job.spec.elastic is None or not tj.is_alive():
                    continue
                fp = (self.scheduler.inventory.holder(tj.job.key)
                      if self.scheduler is not None else None)
                if fp is not None and fp.accelerator == accelerator:
                    tj.nudge()
            except Exception:  # a nudge must never break the ledger path
                pass

    def _submit_queued(self, job: TpuJob) -> None:
        """First sighting of a fresh job under the scheduler: park it
        in QUEUED (no resources exist yet — ``_start_job`` only spawns
        a reconciler on admission) and persist the gate so users see
        WHY nothing is running."""
        req = self._request_for(job)
        if (req.key in self.scheduler.pending_keys()
                or self.scheduler.is_running(req.key)):
            return  # watch replay — already in line
        # persist the gate BEFORE enqueueing: the background sched loop
        # may admit the instant submit() returns, and the admitted
        # reconciler's runtime_id+CREATING write must never be
        # overwritten by a stale pre-admission Queued snapshot (the
        # status write is last-write-wins, not CAS)
        job.status.phase = TpuJobPhase.QUEUED
        job.status.append_condition(
            "Queued",
            reason=f"queue '{req.queue}' priority {req.priority}: "
                   f"awaiting {req.footprint}")
        try:
            job = self.job_client.update(job)
        except Exception as e:
            # the gate is still effective (no reconciler spawns); only
            # the user-visible phase write is retried by the next event
            log.warning("job %s: queued status write: %s", job.key, e)
        self.scheduler.submit(req)
        self.client.record_event(
            job.metadata.namespace,
            {"kind": "TpuJob", "name": job.metadata.name},
            "Queued",
            f"queued by the cluster scheduler (queue '{req.queue}', "
            f"priority {req.priority}, {req.footprint})",
        )

    def _ensure_sched_loop(self) -> None:
        if self.scheduler is None or self._sched_thread is not None:
            return
        self._sched_thread = threading.Thread(
            target=self._sched_loop, daemon=True, name="cluster-sched")
        self._sched_thread.start()

    def _sched_kick(self) -> None:
        """Coalesced request for a scheduler pass: job/capacity deltas
        (submit, terminal, delete, resize, queued-edit) set ONE pending
        flag the tick loop drains — a burst of N events runs one pass,
        not N. Falls back to a synchronous tick when the loop is not
        running (unit tests driving the controller by hand)."""
        from k8s_tpu.controller import metrics

        if self.scheduler is None:
            return
        t = self._sched_thread
        if t is None or not t.is_alive():
            self._sched_tick()
            return
        metrics.SCHED_KICKS.inc()
        if self._sched_kick_pending.is_set():
            metrics.SCHED_KICKS_COALESCED.inc()
        else:
            self._sched_kick_pending.set()

    def _sched_backstop(self) -> float:
        """How long the tick loop may sleep with no kicks. Legacy mode
        keeps the configured interval (the tick IS the event source);
        event-driven mode stretches it to the slow backstop (every
        delta kicks explicitly), shortened to the next preemption-
        cooldown expiry so a held victim is re-considered the moment
        its hold-off ends, not one backstop later."""
        base = self.sched_interval
        if self.core is not None:
            base = max(base, SCHED_BACKSTOP_SECONDS)
        sched = self.scheduler
        if sched is not None:
            exp = sched.next_holdoff_expiry()
            if exp is not None:
                delta = exp - sched.clock()
                if delta > 0:
                    base = min(base, delta + 0.01)
        return max(0.02, base)

    def _sched_loop(self) -> None:
        """Event-driven tick loop: woken by :meth:`_sched_kick` (job or
        capacity deltas), with the periodic interval demoted to a slow
        backstop for anything a kick ever misses."""
        while not self._stop.is_set():
            self._sched_kick_pending.wait(self._sched_backstop())
            if self._stop.is_set():
                return
            # clear BEFORE ticking: a kick landing mid-pass re-arms the
            # flag and the loop runs again immediately — never lost
            self._sched_kick_pending.clear()
            try:
                self._sched_tick()
            except Exception as e:  # a tick bug must not kill the loop
                log.error("scheduler tick failed: %s", e)

    def _sched_tick(self) -> None:
        """One scheduling round: let the pure core decide (briefly
        under the lock), then act OUTSIDE it — preempt flushes,
        reconciler spawns, and gauge export all do I/O or joins, and
        holding the lock through them would convoy the watch pump,
        force_preempt, and every reconciler's terminal callback behind
        one apiserver brown-out. Acting lock-free is safe: each
        decision in ``result`` belongs to exactly this caller (tick()
        already moved the jobs, so a concurrent tick cannot re-decide
        them)."""
        from k8s_tpu.controller import metrics

        sched = self.scheduler
        if sched is None:
            return
        t0 = time.monotonic()
        with self._sched_lock:
            result = sched.tick()
        # placement-scoring cost at O(1000) jobs is a measured quantity
        # (the reconcile-latency idiom): only the pure decision pass is
        # timed — acting on the verdicts does I/O and is not the
        # scheduler's cost
        metrics.SCHED_TICK_SECONDS.observe(time.monotonic() - t0)
        for key in result.backfilled:
            req = sched.running_request(key)
            metrics.SCHED_BACKFILLS.inc(
                {"queue": req.queue if req is not None else "unknown"})
        for p in result.preempted:
            self._apply_preemption(p)
        for req in result.admitted:
            self._admit_job(req)
        self._surface_blocked(result)
        self._export_sched_metrics()

    def _surface_blocked(self, result: TickResult) -> None:
        """Queued-phase diagnosability: write each parked job's blocked
        WHY (capacity / quota / cooldown / reservation /
        backfill-refused) into its Queued condition — but only when the
        category CHANGES, so a job parked behind capacity for an hour
        carries one condition, not 3600. A key that leaves the blocked
        set is forgotten, so re-parking later re-surfaces."""
        for key in list(self._blocked_surfaced):
            if key not in result.blocked:
                self._blocked_surfaced.pop(key, None)
        for key, reason in result.blocked.items():
            category = result.blocked_category.get(key, "")
            if self._blocked_surfaced.get(key) == category:
                continue
            self._blocked_surfaced[key] = category
            ns, name = key.split("/", 1)
            # Some scheduler messages already lead with the category
            # word ("capacity: 2 × ..."); don't double the prefix.
            text = reason if reason.startswith(f"{category}:") \
                else f"{category}: {reason}"
            try:
                job = self.job_client.get(ns, name)
                if job.status.phase != TpuJobPhase.QUEUED:
                    continue
                job.status.append_condition("Queued", reason=text)
                self.job_client.update(job)
            except Exception as e:  # diagnosability is best-effort
                log.debug("job %s: blocked-reason write: %s", key, e)

    def _admit_job(self, req: JobRequest) -> None:
        from k8s_tpu.controller import metrics

        ns, name = req.key.split("/", 1)
        try:
            job = self.job_client.get(ns, name)
        except Exception as e:
            log.warning("admitted job %s unreadable (%s); released",
                        req.key, e)
            self.scheduler.remove(req.key)
            return
        if job.status.phase in (TpuJobPhase.DONE, TpuJobPhase.FAILED):
            # raced a terminal transition (or a preempt raced the
            # finish): never charge the fleet for a finished job
            self.scheduler.remove(req.key)
            return
        fresh = self._request_for(job)
        if fresh.footprint != req.footprint:
            # the spec changed between the decision and this fetch (a
            # queued-edit racing the tick): the charge no longer
            # matches what the reconciler would materialize — release
            # and re-queue under the real footprint; the next tick
            # re-decides against the honest ledger
            log.warning("job %s: footprint changed at admission "
                        "(%s -> %s); re-queued", req.key,
                        req.footprint, fresh.footprint)
            fresh.seq = req.seq  # keep its place in line
            self.scheduler.reinstate(fresh)
            self._sched_kick()  # re-decide now, not at the backstop
            return
        metrics.SCHED_ADMITTED.inc({"queue": req.queue})
        job.status.append_condition(
            "Admitted",
            reason=f"admitted by the cluster scheduler "
                   f"({req.footprint} charged to queue '{req.queue}')")
        self.client.record_event(
            ns, {"kind": "TpuJob", "name": name},
            "Admitted",
            f"admitted (queue '{req.queue}', priority {req.priority}, "
            f"{req.footprint})",
        )
        if not self._spawn_reconciler(job):
            # the previous reconciler is still winding down: give the
            # slices back and re-queue AT ITS ORIGINAL position; a
            # DELAYED kick retries (an immediate one would hot-loop
            # against the still-draining reconciler)
            self.scheduler.reinstate(req)
            threading.Timer(1.0, self._sched_kick).start()

    def _apply_preemption(self, p: Preemption) -> None:
        """Act on an eviction verdict: goodput + Events naming BOTH
        parties, then drive the victim's reconciler through the
        checkpoint-safe preempt flush (condition, SIGTERM-flush
        teardown, park in QUEUED)."""
        from k8s_tpu.controller import metrics

        metrics.SCHED_PREEMPTED.inc({"queue": p.queue})
        if p.cost > 0:
            metrics.SCHED_PREEMPT_LOST_STEPS.inc(
                {"job": p.victim}, by=float(p.cost))
        vns, vname = p.victim.split("/", 1)
        pns, pname = p.preemptor.split("/", 1)
        self.client.record_event(
            pns, {"kind": "TpuJob", "name": pname},
            "Preempting",
            f"preempting lower-priority {p.victim} "
            f"(~{p.cost} steps since its last checkpoint at stake)",
        )
        tj = self.jobs.get(p.victim)
        if tj is None:
            # adopted-queued edge: no reconciler exists; the scheduler
            # already re-queued it, the ledger is consistent
            log.warning("preemption victim %s has no reconciler",
                        p.victim)
            return
        tj.preempt(
            f"preempted by higher-priority job {p.preemptor} "
            f"(~{p.cost} steps since the last checkpoint discarded at "
            f"worst; the preempt flush preserves them when healthy)")

    def force_preempt(self, key: str, reason: str = "") -> bool:
        """Evict one running job through the full preemption path
        without a competing preemptor — the ``sched-preempt`` chaos
        fault's surface (and an operator escape hatch). Returns False
        when the job is not running under the scheduler."""
        from k8s_tpu.controller import metrics

        sched = self.scheduler
        if sched is None:
            return False
        tj = self.jobs.get(key)
        cost = tj.preemption_cost() if tj is not None else 0
        if not sched.requeue(key):  # atomic: running → queued+cooldown
            return False
        metrics.SCHED_PREEMPTED.inc({"queue": "chaos"})
        if cost > 0:
            metrics.SCHED_PREEMPT_LOST_STEPS.inc(
                {"job": key}, by=float(cost))
        if tj is not None:
            tj.preempt(reason or "forced preemption")
        self._export_sched_metrics()
        return True

    def _on_job_terminal(self, tj: TrainingJob) -> None:
        """Reconciler callback at the terminal transition: free the
        slices and immediately re-run the decision core so the next
        queued job starts this tick, not next interval."""
        if self.scheduler is None:
            return
        self.scheduler.remove(tj.job.key)
        self._sched_kick()

    def _export_sched_metrics(self) -> None:
        from k8s_tpu.controller import metrics

        stats = self.scheduler.stats()
        metrics.SCHED_QUEUE_DEPTH.clear()
        for q, d in stats["queue_depth"].items():
            metrics.SCHED_QUEUE_DEPTH.set(float(d), {"queue": q})
        metrics.SCHED_QUOTA_USED.clear()
        for q, chips in stats["quota_used_chips"].items():
            metrics.SCHED_QUOTA_USED.set(float(chips), {"queue": q})
        metrics.SCHED_SLICES_FREE.clear()
        for accel, pool in stats["pools"].items():
            metrics.SCHED_SLICES_FREE.set(
                float(pool["free"]), {"accelerator": accel})
        # placement scoring (pools with a fleet topology block only)
        for accel, p in stats.get("placement", {}).items():
            metrics.SCHED_FRAGMENTATION.set(
                p["fragmentation"], {"accelerator": accel})
            if p["contiguity_requests"] > 0:
                metrics.SCHED_CONTIGUITY_HIT_RATE.set(
                    p["contiguity_hits"] / p["contiguity_requests"],
                    {"accelerator": accel})

    # ---------------------------------------------------- event-driven feed

    def _on_informer_event(self, ev) -> None:
        """Informer listener (event-driven core): map a Pod/Job delta to
        the owning TpuJob's reconcile key via the ``tpu_job_name`` label
        and kick exactly that key. The informer only notifies on
        MATERIAL cache changes, and the local kubelet writes pod status
        once at launch and once at finish — so a quiescent 1000-job
        fleet generates no kicks at all. A synthetic RESYNC event
        (reflector relist: anything may have changed while the watch
        was down) re-kicks every live job once."""
        if self.core is None:
            return
        if ev.type == "RESYNC":
            for key, tj in list(self.jobs.items()):
                if tj.is_alive():
                    tj.nudge()
            return
        labels = ((ev.object.get("metadata") or {}).get("labels") or {})
        name = labels.get(JOB_NAME_LABEL)
        if not name:
            return
        key = f"{ev.namespace or 'default'}/{name}"
        tj = self.jobs.get(key)
        if tj is not None and tj.is_alive():
            tj.nudge()

    def ingest_heartbeat(self, namespace: str, name: str, host: int,
                         payload: dict) -> bool:
        """Pushed obs heartbeat (POST /v1/heartbeat/<ns>/<name>/<host>
        on the operator health server): route to the owning reconciler,
        which caches the stats and kicks its key — replacing a poll.
        Returns False for an unknown/dead job (HTTP 404)."""
        tj = self.jobs.get(f"{namespace}/{name}")
        if tj is None or not tj.is_alive():
            return False
        tj.ingest_heartbeat(host, payload)
        return True

    def handle_event(self, ev_type: str, job: TpuJob) -> None:
        """Reference handleTfJobEvent (controller.go:123-170)."""
        from k8s_tpu.controller import metrics

        metrics.EVENTS_HANDLED.inc({"type": ev_type})
        key = job.key
        if ev_type == "ADDED":
            if job.status.is_failed():
                log.warning("ignoring failed job %s", key)  # quarantine, :126-133
                return
            if key in self.jobs:
                return
            self._start_job(job)
        elif ev_type == "DELETED":
            was_scheduled = False
            if self.scheduler is not None:
                # frees the slices (or drops the queue entry) whether a
                # reconciler exists or not — a QUEUED job has none
                was_scheduled = self.scheduler.remove(key)
            tj = self.jobs.pop(key, None)
            metrics.LIVE_JOBS.set(len(self.jobs))
            if tj is None:
                if not was_scheduled:
                    log.warning("unsafe state: %s deleted but not tracked",
                                key)
                self._sched_kick()
                return
            if tj.is_alive():
                tj.delete()
            else:
                # a preempted/queued job's reconciler has exited — its
                # event queue drains nowhere, so the teardown of what
                # survives the queue (per-index Services, TensorBoard,
                # launcher ConfigMap) must run inline or it leaks
                try:
                    tj.delete_resources()
                except Exception as e:
                    log.error("job %s: queued-job delete: %s", key, e)
            self._sched_kick()
        elif ev_type == "MODIFIED":
            tj = self.jobs.get(key)
            if tj is not None and tj.is_alive():
                tj.update(job)
            elif self.scheduler is not None:
                # spec edited while QUEUED (no reconciler polices
                # immutability yet): the ledger must charge what the
                # reconciler will materialize on admission, or the
                # stale footprint breaks zero-oversubscription
                if self.scheduler.update_pending(self._request_for(job)):
                    self._sched_kick()

    # ------------------------------------------------------------ run loop

    def run(self) -> None:
        """Watch pump (reference Run + watch, controller.go:80-119,292-376).

        Every requeue path — init failure, relist-after-410, a pump
        crash (e.g. an event handler exceeding the watchdog under an
        apiserver brown-out) — routes through one :class:`Backoff`:
        repeated failures space out exponentially instead of hot-
        looping the apiserver, and a stable stretch earns the fast
        retry back. A pump crash previously killed the controller
        thread silently; now it re-initializes and keeps serving."""
        requeue = Backoff(REQUEUE_POLICY)
        while not self._stop.is_set():
            try:
                watch_rv = self.init_resource()
            except Exception as e:
                delay = requeue.note_failure()
                log.error("initialization failed: %s; retrying in %.1fs",
                          e, delay)
                if requeue.wait(self._stop):
                    return
                continue
            try:
                self._pump(watch_rv)
                return
            except errors.OutdatedVersionError:
                # 410 Gone → relist and re-watch (reference
                # ErrVersionOutdated restart path, controller.go:331-344)
                delay = requeue.note_failure()
                log.info("watch outdated; relisting in %.1fs", delay)
            except Exception as e:
                delay = requeue.note_failure()
                log.error("event pump failed: %s; re-initializing in %.1fs",
                          e, delay)
            if requeue.wait(self._stop):
                return

    def _pump(self, watch_rv: int) -> None:
        watcher = self.job_client.watch(self.namespace, resource_version=watch_rv)
        try:
            while not self._stop.is_set():
                ev = watcher.next(timeout=0.2)
                if ev is None:
                    continue
                job = TpuJob.from_dict(ev.object)
                with PanicTimer(
                    self.watchdog_deadline,
                    msg=f"handling {ev.type} for {job.key}",
                    hard=False,
                ) as wd:
                    self.handle_event(ev.type, job)
                if wd.fired.is_set():
                    raise RuntimeError("event handler exceeded watchdog deadline")
        finally:
            watcher.stop()

    # ------------------------------------------------------------ lifecycle

    def start(self) -> threading.Thread:
        self._thread = threading.Thread(target=self.run, daemon=True, name="controller")
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self._stop.set()
        # wake the sched loop out of its backstop sleep immediately
        self._sched_kick_pending.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._sched_thread is not None:
            self._sched_thread.join(timeout=5)
            self._sched_thread = None
        # stop reconcilers only after the pump thread is down: run() /
        # find_all_jobs may still be adding jobs concurrently, and a job
        # added after an early stop loop would leak its thread. Join so
        # stop() really quiesces the process.
        inf = self.client.informer
        if self._informer_listener is not None and inf is not None:
            inf.remove_listener(self._informer_listener)
            self._informer_listener = None
        for tj in list(self.jobs.values()):
            tj.stop()
        for tj in list(self.jobs.values()):
            tj.join(timeout=5)
        if self.core is not None:
            self.core.stop()
        if self._owns_informer:
            if self._informer_sampler is not None:
                from k8s_tpu.controller import metrics

                # under the sampler's own lock: flag + reset become
                # atomic w.r.t. any in-flight scrape, so a dead
                # informer can never be reported synced afterwards
                with self._informer_sampler_lock:
                    self._informer_sampler_stopped.set()
                    metrics.REGISTRY.remove_collector(
                        self._informer_sampler)
                    self._informer_sampler = None
                    metrics.INFORMER_SYNCED.set(0.0)
                    metrics.INFORMER_OBJECTS.clear()
            self.client.stop_informer()
            self._owns_informer = False

    def wait_for_job(
        self, namespace: str, name: str, timeout: float = 300.0, poll: float = 0.05
    ) -> TpuJob:
        """Poll a job to a terminal phase (the analogue of the e2e
        binary's wait, reference test/e2e/main.go:111-123)."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            job = self.job_client.get(namespace, name)
            if job.status.phase in (TpuJobPhase.DONE, TpuJobPhase.FAILED):
                return job
            time.sleep(poll)
        raise TimeoutError(f"job {namespace}/{name} did not finish in {timeout}s")
