"""The operator controller: CRD registration, watch loop, dispatch.

Analogue of reference ``pkg/controller/controller.go``: holds the live
job map (:46-61); ``run()`` = init-resource with retry (:86-96) + the
event pump with a per-event watchdog (:109-119); Added → new
TrainingJob thread, Deleted → ``Delete()``, Modified forwarded but not
acted on (:123-170); ``find_all_jobs`` re-adopts existing jobs on
startup (:172-201) so an operator crash/restart is seamless; CRD
create + established wait (:234-286); watch staleness (410 Gone) →
``OutdatedVersionError`` → relist and re-watch (:292-376).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from k8s_tpu.api import errors
from k8s_tpu.api.client import KubeClient
from k8s_tpu.api.crd_client import TpuJobClient
from k8s_tpu import utils
from k8s_tpu.controller.watchdog import PanicTimer
from k8s_tpu.robustness.backoff import Backoff, BackoffPolicy
from k8s_tpu.spec import ControllerConfig, TpuJob, TpuJobPhase
from k8s_tpu.trainer.training import TrainingJob

log = logging.getLogger(__name__)

INIT_RETRY_WAIT = 30.0  # reference controller.go:33
WATCHDOG_DEADLINE = 60.0  # reference controller.go:110

# Requeue schedule for the controller's outer loop: init failures,
# relist-after-410, and pump crashes all hold off through this (capped
# at the reference's fixed 30s init wait, which it replaces).
REQUEUE_POLICY = BackoffPolicy(
    base=0.5, factor=2.0, cap=INIT_RETRY_WAIT, jitter=0.5, reset_after=120.0
)


class Controller:
    def __init__(
        self,
        client: KubeClient,
        job_client: TpuJobClient,
        config: Optional[ControllerConfig] = None,
        namespace: Optional[str] = None,
        reconcile_interval: float = 8.0,
        watchdog_deadline: float = WATCHDOG_DEADLINE,
    ):
        self.client = client
        self.job_client = job_client
        self.config = config or ControllerConfig()
        self.namespace = namespace
        self.reconcile_interval = reconcile_interval
        self.watchdog_deadline = watchdog_deadline
        self.jobs: Dict[str, TrainingJob] = {}  # reference jobs map, :46-61
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._owns_informer = False
        self._informer_sampler = None

    # ------------------------------------------------------------ bootstrap

    def init_resource(self) -> int:
        """Create the CRD if needed and wait Established (reference
        initResource + createCRD, controller.go:213-286). Returns the
        resourceVersion to start watching from."""
        if self.client.informer is None:
            # the informer replaces the reference's per-tick polling
            # (SURVEY §7.2 #4): one watch stream per kind, reconcilers
            # read the cache — not O(replicas) GETs every 8s
            inf = self.client.start_informer(namespace=self.namespace)
            self._owns_informer = True

            from k8s_tpu.controller import metrics

            stopped = threading.Event()
            sample_lock = threading.Lock()

            def sample_informer(inf=inf, stopped=stopped,
                                lock=sample_lock):
                # the lock serializes the sampler body against stop()'s
                # gauge reset: without it a scrape that passed the flag
                # check could finish its writes AFTER the reset and
                # leave a dead informer reported synced forever (the
                # sampler is removed, so nothing would correct it)
                with lock:
                    if stopped.is_set():
                        metrics.INFORMER_SYNCED.set(0.0)
                        metrics.INFORMER_OBJECTS.clear()
                        return
                    for kind, cache in inf.caches.items():
                        with cache.lock:
                            n = len(cache.objects)
                        metrics.INFORMER_OBJECTS.set(
                            float(n), {"kind": kind})
                    metrics.INFORMER_SYNCED.set(
                        1.0 if inf.synced else 0.0)

            self._informer_sampler = sample_informer
            self._informer_sampler_stopped = stopped
            self._informer_sampler_lock = sample_lock
            metrics.REGISTRY.on_collect(sample_informer)
        try:
            self.job_client.create_crd_definition()
        except errors.AlreadyExistsError:
            pass
        utils.retry(0.5, 120, self.job_client.crd_established)
        return self.find_all_jobs()

    def find_all_jobs(self) -> int:
        """Adopt pre-existing TpuJobs (reference findAllTfJobs,
        controller.go:172-201): resource creation is idempotent, so
        re-adopting a live job is safe."""
        rv = self.client.cluster.resource_version
        for job in self.job_client.list(self.namespace):
            if job.status.is_failed():
                log.warning("ignoring failed job %s", job.key)
                continue
            if job.key not in self.jobs:
                self._start_job(job)
        return rv

    # ------------------------------------------------------------ dispatch

    def _start_job(self, job: TpuJob) -> None:
        from k8s_tpu.controller import metrics

        tj = TrainingJob(self.client, self.job_client, job)
        self.jobs[job.key] = tj
        tj.start(self.config, self.reconcile_interval)
        metrics.JOBS_STARTED.inc()
        metrics.LIVE_JOBS.set(len(self.jobs))
        self.client.record_event(
            job.metadata.namespace,
            {"kind": "TpuJob", "name": job.metadata.name},
            "Started",
            f"reconciler started for {job.key}",
        )

    def handle_event(self, ev_type: str, job: TpuJob) -> None:
        """Reference handleTfJobEvent (controller.go:123-170)."""
        from k8s_tpu.controller import metrics

        metrics.EVENTS_HANDLED.inc({"type": ev_type})
        key = job.key
        if ev_type == "ADDED":
            if job.status.is_failed():
                log.warning("ignoring failed job %s", key)  # quarantine, :126-133
                return
            if key in self.jobs:
                return
            self._start_job(job)
        elif ev_type == "DELETED":
            tj = self.jobs.pop(key, None)
            metrics.LIVE_JOBS.set(len(self.jobs))
            if tj is None:
                log.warning("unsafe state: %s deleted but not tracked", key)
                return
            tj.delete()
        elif ev_type == "MODIFIED":
            tj = self.jobs.get(key)
            if tj is not None:
                tj.update(job)

    # ------------------------------------------------------------ run loop

    def run(self) -> None:
        """Watch pump (reference Run + watch, controller.go:80-119,292-376).

        Every requeue path — init failure, relist-after-410, a pump
        crash (e.g. an event handler exceeding the watchdog under an
        apiserver brown-out) — routes through one :class:`Backoff`:
        repeated failures space out exponentially instead of hot-
        looping the apiserver, and a stable stretch earns the fast
        retry back. A pump crash previously killed the controller
        thread silently; now it re-initializes and keeps serving."""
        requeue = Backoff(REQUEUE_POLICY)
        while not self._stop.is_set():
            try:
                watch_rv = self.init_resource()
            except Exception as e:
                delay = requeue.note_failure()
                log.error("initialization failed: %s; retrying in %.1fs",
                          e, delay)
                if requeue.wait(self._stop):
                    return
                continue
            try:
                self._pump(watch_rv)
                return
            except errors.OutdatedVersionError:
                # 410 Gone → relist and re-watch (reference
                # ErrVersionOutdated restart path, controller.go:331-344)
                delay = requeue.note_failure()
                log.info("watch outdated; relisting in %.1fs", delay)
            except Exception as e:
                delay = requeue.note_failure()
                log.error("event pump failed: %s; re-initializing in %.1fs",
                          e, delay)
            if requeue.wait(self._stop):
                return

    def _pump(self, watch_rv: int) -> None:
        watcher = self.job_client.watch(self.namespace, resource_version=watch_rv)
        try:
            while not self._stop.is_set():
                ev = watcher.next(timeout=0.2)
                if ev is None:
                    continue
                job = TpuJob.from_dict(ev.object)
                with PanicTimer(
                    self.watchdog_deadline,
                    msg=f"handling {ev.type} for {job.key}",
                    hard=False,
                ) as wd:
                    self.handle_event(ev.type, job)
                if wd.fired.is_set():
                    raise RuntimeError("event handler exceeded watchdog deadline")
        finally:
            watcher.stop()

    # ------------------------------------------------------------ lifecycle

    def start(self) -> threading.Thread:
        self._thread = threading.Thread(target=self.run, daemon=True, name="controller")
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # stop reconcilers only after the pump thread is down: run() /
        # find_all_jobs may still be adding jobs concurrently, and a job
        # added after an early stop loop would leak its thread. Join so
        # stop() really quiesces the process.
        for tj in list(self.jobs.values()):
            tj.stop()
        for tj in list(self.jobs.values()):
            tj.join(timeout=5)
        if self._owns_informer:
            if self._informer_sampler is not None:
                from k8s_tpu.controller import metrics

                # under the sampler's own lock: flag + reset become
                # atomic w.r.t. any in-flight scrape, so a dead
                # informer can never be reported synced afterwards
                with self._informer_sampler_lock:
                    self._informer_sampler_stopped.set()
                    metrics.REGISTRY.remove_collector(
                        self._informer_sampler)
                    self._informer_sampler = None
                    metrics.INFORMER_SYNCED.set(0.0)
                    metrics.INFORMER_OBJECTS.clear()
            self.client.stop_informer()
            self._owns_informer = False

    def wait_for_job(
        self, namespace: str, name: str, timeout: float = 300.0, poll: float = 0.05
    ) -> TpuJob:
        """Poll a job to a terminal phase (the analogue of the e2e
        binary's wait, reference test/e2e/main.go:111-123)."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            job = self.job_client.get(namespace, name)
            if job.status.phase in (TpuJobPhase.DONE, TpuJobPhase.FAILED):
                return job
            time.sleep(poll)
        raise TimeoutError(f"job {namespace}/{name} did not finish in {timeout}s")
