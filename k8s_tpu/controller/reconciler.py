"""ReconcilerCore: a bounded worker pool draining one shared
coalescing work queue — the event-driven replacement for one thread
per TrainingJob (docs/SCHEDULER.md "Event-driven core").

Each registered key owns a handler ``() -> Optional[float]``: process
the job once and return the desired requeue delay (None = wait for the
next event/kick; the slow resync backstop is the handler's own
business). The queue's dirty/processing sets guarantee a key is never
processed on two workers at once, so per-job reconcile logic needs no
extra locking beyond what the threaded mode already had.

Failure policy: a handler that *raises* is re-queued on the per-key
exponential :class:`~k8s_tpu.controller.workqueue.RateLimiter`
(0.5s → 30s) — the event-driven analogue of "the ticker paces the
retry"; a handler that returns normally resets its key's backoff.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

from k8s_tpu.controller.workqueue import CoalescingWorkQueue, RateLimiter

log = logging.getLogger(__name__)

Handler = Callable[[], Optional[float]]


class ReconcilerCore:
    def __init__(self, workers: int = 4,
                 clock: Callable[[], float] = time.monotonic,
                 failure_base: float = 0.5, failure_cap: float = 30.0):
        self.queue = CoalescingWorkQueue(clock=clock)
        self.limiter = RateLimiter(base=failure_base, cap=failure_cap)
        self.clock = clock
        self.workers = max(1, int(workers))
        self._handlers: Dict[str, Handler] = {}
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._inflight: Dict[str, int] = {}
        self._threads: list = []
        self._stop = threading.Event()
        self._started = False
        self._coalesced_exported = 0

    # ------------------------------------------------------------ registry

    def register(self, key: str, handler: Handler) -> None:
        """(Re)bind ``key`` to ``handler``. Rebinding is how the
        controller replaces a preempted job's reconciler on
        re-admission: the queue's processing set serializes the old
        handler's in-flight pass against the new one's first."""
        with self._lock:
            self._handlers[key] = handler

    def deregister(self, key: str) -> None:
        with self._lock:
            self._handlers.pop(key, None)
        self.queue.discard(key)

    def registered(self, key: str) -> bool:
        with self._lock:
            return key in self._handlers

    # ------------------------------------------------------------ kicks

    def kick(self, key: str, delay: float = 0.0) -> None:
        if delay > 0:
            self.queue.add_after(key, delay)
        else:
            self.queue.add(key)

    def wait_idle(self, key: str, timeout: float = 10.0) -> bool:
        """Block until no worker is processing ``key`` (the respawn
        path's quiesce barrier). True = idle within the timeout."""
        deadline = self.clock() + timeout
        with self._idle:
            while self._inflight.get(key, 0) > 0:
                remaining = deadline - self.clock()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
            return True

    # ------------------------------------------------------------ workers

    def start(self) -> "ReconcilerCore":
        if self._started:
            return self
        self._started = True
        for i in range(self.workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"reconciler-core-{i}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []
        self._started = False

    def _worker(self) -> None:
        from k8s_tpu.controller import metrics

        while not self._stop.is_set():
            key = self.queue.get(timeout=0.2)
            if key is None:
                continue
            with self._lock:
                handler = self._handlers.get(key)
                self._inflight[key] = self._inflight.get(key, 0) + 1
            try:
                if handler is None:
                    continue  # deregistered while queued: drop
                t0 = time.monotonic()
                try:
                    delay = handler()
                except Exception as e:
                    backoff = self.limiter.when(key)
                    metrics.RECONCILE_REQUEUES.inc({"reason": "error"})
                    log.error("key %s: reconcile failed (%s); requeued "
                              "in %.1fs", key, e, backoff)
                    self.queue.add_after(key, backoff)
                else:
                    self.limiter.forget(key)
                    if delay is not None:
                        metrics.RECONCILE_REQUEUES.inc(
                            {"reason": "resync" if delay >= 60.0
                             else "poll"})
                        self.queue.add_after(key, max(0.0, delay))
                metrics.RECONCILE_LATENCY.observe(time.monotonic() - t0)
            finally:
                self.queue.done(key)
                with self._idle:
                    n = self._inflight.get(key, 1) - 1
                    if n <= 0:
                        self._inflight.pop(key, None)
                    else:
                        self._inflight[key] = n
                    self._idle.notify_all()
            self._export()

    def _export(self) -> None:
        from k8s_tpu.controller import metrics

        metrics.WORKQUEUE_DEPTH.set(float(len(self.queue)))
        delta = self.queue.coalesced - self._coalesced_exported
        if delta > 0:
            self._coalesced_exported = self.queue.coalesced
            metrics.WORKQUEUE_COALESCED.inc(by=float(delta))
