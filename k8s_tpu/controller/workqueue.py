"""Keyed, coalescing, rate-limited work queue — the event-driven
reconciler core's spine (docs/SCHEDULER.md "Event-driven core").

client-go workqueue semantics, dependency-free:

- **Coalescing**: a key added while already queued (dirty) is merged —
  a burst of N events for one job costs ONE reconcile, not N. A key
  added while being *processed* is re-queued once ``done()`` is called,
  so no event is ever lost and no key is processed concurrently.
- **Delayed adds**: ``add_after(key, delay)`` parks the key on a heap
  until its due time — the requeue-with-backoff and slow-resync
  mechanism that replaces the per-job fixed-interval sleep loop.
- **Injected clock**: every time read goes through ``clock`` so
  ``benches/sched_bench.py`` replays this exact code on a virtual
  clock (``pop_ready`` + ``next_ready_at`` are the non-blocking
  surface the simulator drives; worker threads use blocking ``get``).

The per-key :class:`RateLimiter` provides the exponential failure
backoff: each consecutive failure doubles the requeue delay up to a
cap; ``forget()`` on success resets the key to the base delay.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple


class RateLimiter:
    """Per-key exponential backoff: ``when(key)`` returns the delay to
    wait before the next retry of ``key`` and arms the next step;
    ``forget(key)`` resets it after a success."""

    def __init__(self, base: float = 0.05, cap: float = 30.0):
        self.base = base
        self.cap = cap
        self._failures: Dict[str, int] = {}
        self._lock = threading.Lock()

    def when(self, key: str) -> float:
        with self._lock:
            n = self._failures.get(key, 0)
            self._failures[key] = n + 1
        return min(self.cap, self.base * (2.0 ** n))

    def failures(self, key: str) -> int:
        with self._lock:
            return self._failures.get(key, 0)

    def forget(self, key: str) -> None:
        with self._lock:
            self._failures.pop(key, None)


class CoalescingWorkQueue:
    """Keyed FIFO with dirty/processing coalescing + a delayed heap."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[str] = []          # ready keys, FIFO
        self._dirty: Set[str] = set()        # queued or needs-requeue
        self._processing: Set[str] = set()   # handed out, not done()
        self._delayed: List[Tuple[float, int, str]] = []  # (due, seq, key)
        self._seq = 0
        self._closed = False
        # counters mirrored into the controller metrics by the owner;
        # kept as plain ints so the simulator reads them with no
        # Prometheus coupling
        self.added = 0
        self.coalesced = 0
        self.requeued = 0

    # ------------------------------------------------------------ producers

    def add(self, key: str) -> bool:
        """Mark ``key`` dirty and queue it unless it already is. Returns
        True when a new queue entry was created (False = coalesced into
        an existing one)."""
        with self._cond:
            if self._closed:
                return False
            self.added += 1
            if key in self._dirty:
                # already queued (or will re-queue at done()): merge
                self.coalesced += 1
                return False
            self._dirty.add(key)
            if key in self._processing:
                # re-queued by done(); counts as coalesced-into-flight
                self.coalesced += 1
                return False
            self._queue.append(key)
            self._cond.notify()
            return True

    def add_after(self, key: str, delay: float) -> None:
        """Queue ``key`` after ``delay`` seconds (0 ⇒ immediate). An
        earlier pending delayed add for the same key wins — the heap
        just delivers the first due entry; later ones coalesce."""
        if delay <= 0:
            self.add(key)
            return
        with self._cond:
            if self._closed:
                return
            self.requeued += 1
            self._seq += 1
            heapq.heappush(
                self._delayed, (self.clock() + delay, self._seq, key))
            self._cond.notify()

    # ------------------------------------------------------------ consumers

    def _promote_due(self) -> None:
        """Move due delayed entries to the ready queue (lock held)."""
        now = self.clock()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, key = heapq.heappop(self._delayed)
            if key in self._dirty:
                continue  # already queued: coalesce
            self._dirty.add(key)
            if key not in self._processing:
                self._queue.append(key)

    def get(self, timeout: Optional[float] = None) -> Optional[str]:
        """Blocking pop: the next ready key (marked processing), or
        None on timeout/close. Workers MUST call :meth:`done` after."""
        deadline = None if timeout is None else self.clock() + timeout
        with self._cond:
            while True:
                self._promote_due()
                if self._queue:
                    key = self._queue.pop(0)
                    self._dirty.discard(key)
                    self._processing.add(key)
                    return key
                if self._closed:
                    return None
                # wake early for the nearest delayed entry
                wait = None
                if deadline is not None:
                    wait = deadline - self.clock()
                    if wait <= 0:
                        return None
                if self._delayed:
                    until_due = self._delayed[0][0] - self.clock()
                    wait = until_due if wait is None else min(wait, until_due)
                    wait = max(wait, 0.005)
                self._cond.wait(wait)

    def done(self, key: str) -> None:
        """Processing finished; a key re-added mid-flight re-queues."""
        with self._cond:
            self._processing.discard(key)
            if key in self._dirty:
                self._queue.append(key)
                self._cond.notify()

    # ------------------------------------------ simulator (virtual clock)

    def pop_ready(self) -> Optional[str]:
        """Non-blocking pop for discrete-event replay: the next key due
        at or before ``clock()`` (marked processing), else None."""
        with self._cond:
            self._promote_due()
            if not self._queue:
                return None
            key = self._queue.pop(0)
            self._dirty.discard(key)
            self._processing.add(key)
            return key

    def next_ready_at(self) -> Optional[float]:
        """The earliest time a key becomes deliverable: ``clock()`` if
        one is ready now, the nearest delayed due-time otherwise, None
        when the queue is empty — the simulator's next-event time."""
        with self._cond:
            self._promote_due()
            if self._queue:
                return self.clock()
            if self._delayed:
                return self._delayed[0][0]
            return None

    # ------------------------------------------------------------ lifecycle

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue) + len(self._delayed)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def discard(self, key: str) -> None:
        """Forget a key entirely (job deregistered): drop its ready
        entry; delayed entries drain harmlessly (the consumer drops
        unknown keys)."""
        with self._cond:
            self._dirty.discard(key)
            if key in self._queue:
                self._queue.remove(key)
