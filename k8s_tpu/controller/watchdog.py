"""Event-handler watchdog.

Analogue of reference ``pkg/controller/util.go:51-77`` (``panicTimer``):
the operator crashes itself if a single event handler blocks longer
than a deadline (1 min in the reference, armed at controller.go:110-117)
— a liveness guard standing in for real deadlock detection.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

log = logging.getLogger(__name__)

DEFAULT_DEADLINE = 60.0  # reference: panic at 1 min


class PanicTimerError(RuntimeError):
    pass


class PanicTimer:
    """Arm around each event dispatch; fires if not stopped in time."""

    def __init__(self, deadline: float = DEFAULT_DEADLINE, msg: str = "", hard: bool = False):
        self.deadline = deadline
        self.msg = msg
        self.hard = hard  # True → kill the process like Go panic would
        self._timer: Optional[threading.Timer] = None
        self.fired = threading.Event()

    def _fire(self):
        self.fired.set()
        log.critical("watchdog fired: %s (handler blocked > %.0fs)", self.msg, self.deadline)
        if self.hard:  # pragma: no cover - process suicide
            os._exit(2)

    def start(self) -> None:
        self.stop()
        self._timer = threading.Timer(self.deadline, self._fire)
        self._timer.daemon = True
        self._timer.start()

    def stop(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
