"""Templated-job test runner.

Analogue of reference ``py/test_runner.py`` (:18-73): render a job
manifest template, uniquify the name, create it, wait, record junit.
Template variables use ``str.format`` (``{name}``, ``{image_tag}``)
instead of jinja2 (not a baked dependency).
"""

from __future__ import annotations

import argparse
import sys
import uuid

from k8s_tpu.client.job_client import load_tpu_job_yaml
from k8s_tpu import spec as S
from k8s_tpu.tools.junit import TestCase, Timer, create_junit_xml_file
from k8s_tpu.tools.local_world import LocalWorld


def run_test(spec_text: str, timeout: float, world: LocalWorld) -> TestCase:
    job = load_tpu_job_yaml(spec_text)
    # uniquify (reference: name + salt)
    job.metadata.name = f"{job.metadata.name}-{uuid.uuid4().hex[:4]}"
    if not job.metadata.namespace:
        job.metadata.namespace = "default"
    with Timer() as t:
        world.api.create(job)
        try:
            final = world.api.wait_for_job(
                job.metadata.namespace, job.metadata.name, timeout=timeout
            )
            failure = (
                None
                if final.status.state == S.TpuJobState.SUCCEEDED
                else f"state={final.status.state} reason={final.status.reason}"
            )
        except TimeoutError as e:
            failure = str(e)
    return TestCase("tpu-job", job.metadata.name, t.elapsed, failure)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ktpu-test-runner")
    p.add_argument("--spec", required=True, help="TpuJob YAML (template) path")
    p.add_argument("--image-tag", default="", help="substituted for {image_tag}")
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--junit-path", default="")
    p.add_argument("--subprocess", action="store_true")
    args = p.parse_args(argv)

    with open(args.spec) as f:
        text = f.read()
    if "{image_tag}" in text:
        text = text.replace("{image_tag}", args.image_tag)

    with LocalWorld(subprocess_pods=args.subprocess) as world:
        case = run_test(text, args.timeout, world)

    if args.junit_path:
        create_junit_xml_file([case], args.junit_path)
    if case.failure:
        print(f"FAILED {case.name}: {case.failure}")
        return 1
    print(f"PASSED {case.name} in {case.time:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
