"""Release builder.

Analogue of reference ``py/release.py`` (:116-280) +
``py/build_and_push_image.py``: image tag ``v<date>-<githash>`` with a
dirty-diff suffix, docker-context assembly, chart packaging, and a
``latest_release.json`` manifest. Runs docker/gcloud when present;
``--dry-run`` emits the plan (used by tests and airgapped CI).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tarfile
import time
from typing import List, Optional


def run(cmd: List[str], dry_run: bool = False, **kw) -> Optional[str]:
    print("$ " + " ".join(cmd))
    if dry_run:
        return None
    out = subprocess.run(cmd, check=True, capture_output=True, text=True, **kw)
    return out.stdout


def get_git_hash(repo_dir: str) -> str:
    """Short hash, suffixed with a diff digest when dirty (reference
    build_and_push_image.py:14-32)."""
    h = subprocess.run(
        ["git", "rev-parse", "--short=8", "HEAD"],
        cwd=repo_dir, capture_output=True, text=True, check=True,
    ).stdout.strip()
    diff = subprocess.run(
        ["git", "diff", "HEAD"], cwd=repo_dir, capture_output=True, text=True
    ).stdout
    if diff.strip():
        h = f"{h}-dirty-{hashlib.sha256(diff.encode()).hexdigest()[:8]}"
    return h


def image_tag(repo_dir: str, now: Optional[time.struct_time] = None) -> str:
    now = now or time.gmtime()
    return "v{}-{}".format(time.strftime("%Y%m%d", now), get_git_hash(repo_dir))


def build_operator_image(repo_dir: str, registry: str, dry_run: bool = False) -> str:
    tag = image_tag(repo_dir)
    image = f"{registry}/tpu-operator:{tag}"
    run(
        ["docker", "build", "-t", image, "-f", "images/operator/Dockerfile", "."],
        dry_run=dry_run, cwd=repo_dir,
    )
    run(["docker", "push", image], dry_run=dry_run)
    return image


def package_chart(repo_dir: str, out_dir: str, version: str) -> str:
    """Chart re-version + package (reference release.py:193-239),
    helm-free: tar.gz the chart with the version stamped in."""
    os.makedirs(out_dir, exist_ok=True)
    chart_dir = os.path.join(repo_dir, "chart")
    out_path = os.path.join(out_dir, f"tpu-job-operator-{version}.tgz")
    with tarfile.open(out_path, "w:gz") as tar:
        for root, _, files in os.walk(chart_dir):
            for f in files:
                full = os.path.join(root, f)
                arc = os.path.join(
                    "tpu-job-operator", os.path.relpath(full, chart_dir)
                )
                if f == "Chart.yaml":
                    content = open(full).read()
                    content = "\n".join(
                        f"version: {version}" if line.startswith("version:") else line
                        for line in content.splitlines()
                    )
                    import io

                    data = content.encode()
                    info = tarfile.TarInfo(arc)
                    info.size = len(data)
                    tar.addfile(info, io.BytesIO(data))
                else:
                    tar.add(full, arcname=arc)
    return out_path


def write_release_manifest(out_dir: str, image: str, chart_path: str) -> str:
    """``latest_release.json`` analogue (reference release.py:258-280)."""
    manifest = {
        "image": image,
        "chart": os.path.basename(chart_path),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    path = os.path.join(out_dir, "latest_release.json")
    with open(path, "w") as f:
        json.dump(manifest, f, indent=2)
    return path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ktpu-release")
    p.add_argument("--registry", default="ghcr.io/k8s-tpu")
    p.add_argument("--out-dir", default="build/release")
    p.add_argument("--repo-dir", default=".")
    p.add_argument("--chart-version", default="0.1.0")
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)

    tag = image_tag(args.repo_dir)
    print(f"release tag: {tag}")
    image = (
        build_operator_image(args.repo_dir, args.registry, dry_run=args.dry_run)
        if not args.dry_run
        else f"{args.registry}/tpu-operator:{tag}"
    )
    chart = package_chart(args.repo_dir, args.out_dir, f"{args.chart_version}+{tag}")
    manifest = write_release_manifest(args.out_dir, image, chart)
    print(f"chart: {chart}\nmanifest: {manifest}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
