"""Release builder.

Analogue of reference ``py/release.py`` + ``py/build_and_push_image.py``:

- image tag ``v<date>-<githash>`` with a dirty-diff suffix
  (build_and_push_image.py:14-32), built locally via docker (the GCB
  branch of reference release.py:116-190 is cloud-specific; the local
  branch is ported) and also tagged ``:latest``
- chart re-version + package + publish to an :class:`ArtifactStore`
  under ``<version>/`` AND a ``latest/`` alias, plus a
  ``latest_release.json`` {sha, target, image} manifest
  (release.py:193-280)
- continuous mode (``--check-interval-secs``): poll the store's
  ``latest_green.json`` (written by CI on a green postsubmit,
  py/prow.py:191-207) and cut a release whenever the green sha moves —
  the in-cluster releaser loop of ``release/releaser.yaml:20-25``

The store is pluggable: a local directory stands in for the GCS bucket
(same layout), so the whole flow is testable without cloud access.
``--dry-run`` emits the plan (used by tests and airgapped CI).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import subprocess
import sys
import tarfile
import time
from typing import List, Optional


def run(cmd: List[str], dry_run: bool = False, **kw) -> Optional[str]:
    print("$ " + " ".join(cmd))
    if dry_run:
        return None
    out = subprocess.run(cmd, check=True, capture_output=True, text=True, **kw)
    return out.stdout


def get_git_hash(repo_dir: str) -> str:
    """Short hash, suffixed with a diff digest when dirty (reference
    build_and_push_image.py:14-32)."""
    h = subprocess.run(
        ["git", "rev-parse", "--short=8", "HEAD"],
        cwd=repo_dir, capture_output=True, text=True, check=True,
    ).stdout.strip()
    diff = subprocess.run(
        ["git", "diff", "HEAD"], cwd=repo_dir, capture_output=True, text=True
    ).stdout
    if diff.strip():
        h = f"{h}-dirty-{hashlib.sha256(diff.encode()).hexdigest()[:8]}"
    return h


def image_tag(repo_dir: str, now: Optional[time.struct_time] = None) -> str:
    now = now or time.gmtime()
    return "v{}-{}".format(time.strftime("%Y%m%d", now), get_git_hash(repo_dir))


def build_operator_image(repo_dir: str, registry: str, dry_run: bool = False,
                         push: bool = True) -> str:
    """Local docker build (the reference's non-GCB branch,
    release.py:175-190): versioned tag + a ``:latest`` alias."""
    tag = image_tag(repo_dir)
    image = f"{registry}/tpu-operator:{tag}"
    latest = f"{registry}/tpu-operator:latest"
    run(
        ["docker", "build", "-t", image, "-f", "images/operator/Dockerfile", "."],
        dry_run=dry_run, cwd=repo_dir,
    )
    run(["docker", "tag", image, latest], dry_run=dry_run)
    if push:
        run(["docker", "push", image], dry_run=dry_run)
        run(["docker", "push", latest], dry_run=dry_run)
    return image


def package_chart(repo_dir: str, out_dir: str, version: str) -> str:
    """Chart re-version + package (reference release.py:193-239),
    helm-free: tar.gz the chart with the version stamped in."""
    os.makedirs(out_dir, exist_ok=True)
    chart_dir = os.path.join(repo_dir, "chart")
    out_path = os.path.join(out_dir, f"tpu-job-operator-{version}.tgz")
    with tarfile.open(out_path, "w:gz") as tar:
        for root, _, files in os.walk(chart_dir):
            for f in files:
                full = os.path.join(root, f)
                arc = os.path.join(
                    "tpu-job-operator", os.path.relpath(full, chart_dir)
                )
                if f == "Chart.yaml":
                    content = open(full).read()
                    content = "\n".join(
                        f"version: {version}" if line.startswith("version:") else line
                        for line in content.splitlines()
                    )
                    import io

                    data = content.encode()
                    info = tarfile.TarInfo(arc)
                    info.size = len(data)
                    tar.addfile(info, io.BytesIO(data))
                else:
                    tar.add(full, arcname=arc)
    return out_path


def write_release_manifest(out_dir: str, image: str, chart_path: str) -> str:
    """``latest_release.json`` analogue (reference release.py:258-280)."""
    manifest = {
        "image": image,
        "chart": os.path.basename(chart_path),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    path = os.path.join(out_dir, "latest_release.json")
    with open(path, "w") as f:
        json.dump(manifest, f, indent=2)
    return path


class ArtifactStore:
    """Pluggable release/CI artifact store with the reference's GCS
    bucket layout; the default backend is a local directory (a real GCS
    backend is the same three methods over gsutil/google-cloud-storage,
    deliberately not imported here — zero cloud deps in-tree)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, target: str) -> str:
        return os.path.join(self.root, target)

    def upload_file(self, local_path: str, target: str) -> str:
        dest = self._path(target)
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        shutil.copyfile(local_path, dest)
        return dest

    def upload_string(self, content: str, target: str) -> str:
        dest = self._path(target)
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        with open(dest, "w") as f:
            f.write(content)
        return dest

    def read(self, target: str) -> Optional[str]:
        try:
            with open(self._path(target)) as f:
                return f.read()
        except FileNotFoundError:
            return None


def publish_release(store: ArtifactStore, image: str, chart_archive: str,
                    sha: str, version: str) -> dict:
    """Publish a cut release to the store (reference release.py:193-280):
    chart under ``<version>/`` and the ``latest/`` alias, then the
    ``latest_release.json`` pointer {sha, target, image}."""
    versioned = f"{version}/{os.path.basename(chart_archive)}"
    store.upload_file(chart_archive, versioned)
    store.upload_file(chart_archive, "latest/tpu-job-operator-latest.tgz")
    manifest = {
        "sha": sha,
        "target": versioned,
        "image": image,
        "timestamp": int(time.time()),
    }
    store.upload_string(json.dumps(manifest, indent=2), "latest_release.json")
    return manifest


def get_last_release_sha(store: ArtifactStore) -> str:
    raw = store.read("latest_release.json")
    if not raw:
        return ""
    try:
        return json.loads(raw).get("sha", "")
    except ValueError:
        return ""


def get_latest_green_sha(store: ArtifactStore, job_name: str = "ci") -> str:
    """The green-postsubmit pointer CI maintains
    (reference prow.py:191-207)."""
    raw = store.read(os.path.join(job_name, "latest_green.json"))
    if not raw:
        return ""
    try:
        return json.loads(raw).get("sha", "")
    except ValueError:
        return ""


def publish_green(store: ArtifactStore, job_name: str, sha: str) -> str:
    """Write the green-postsubmit pointer (reference prow.py:191-207).
    Called by ``ci/run_ci.py`` after a FULL green pipeline."""
    return store.upload_string(
        json.dumps({"status": "passing", "job": job_name, "sha": sha}),
        os.path.join(job_name, "latest_green.json"),
    )


def cut_release(repo_dir: str, out_dir: str, registry: str, store: ArtifactStore,
                chart_version: str = "0.1.0", dry_run: bool = False,
                sha: Optional[str] = None) -> dict:
    """One full release: image (+:latest), chart, publish. ``sha``
    overrides the recorded sha (continuous mode records the GREEN sha it
    was asked to release, so the loop converges — the reference clones
    that sha first, release.py:436-462; locally the checkout is the repo)."""
    tag = image_tag(repo_dir)
    sha = sha or get_git_hash(repo_dir)
    if dry_run:
        image = f"{registry}/tpu-operator:{tag}"
    else:
        image = build_operator_image(repo_dir, registry)
    chart = package_chart(repo_dir, out_dir, f"{chart_version}+{tag}")
    write_release_manifest(out_dir, image, chart)
    return publish_release(store, image, chart, sha, tag)


def continuous_release(repo_dir: str, out_dir: str, registry: str,
                       store: ArtifactStore, check_interval_secs: float,
                       chart_version: str = "0.1.0", dry_run: bool = False,
                       max_iterations: Optional[int] = None,
                       job_name: str = "ci") -> int:
    """The in-cluster releaser loop (reference releaser.yaml:20-25 +
    release.py build_lastgreen): whenever CI's green sha moves past the
    last released sha, cut a release. ``max_iterations`` bounds the loop
    for tests; None = forever. ``job_name`` must match the CI run's
    ``--job-name`` (the green pointer lives under ``<job>/``)."""
    released = 0
    i = 0
    while max_iterations is None or i < max_iterations:
        i += 1
        green = get_latest_green_sha(store, job_name)
        last = get_last_release_sha(store)
        if green and green != last:
            print(f"green sha moved ({last or '<none>'} -> {green}); releasing")
            try:
                cut_release(repo_dir, out_dir, registry, store,
                            chart_version, dry_run=dry_run, sha=green)
                released += 1
            except Exception as e:
                # a forever loop must survive transient build/push
                # failures; retry at the next poll
                print(f"release of {green} failed (will retry): {e}",
                      file=sys.stderr)
        elif green:
            print(f"already released {green}")
        else:
            print("no latest_green.json yet")
        if max_iterations is not None and i >= max_iterations:
            break
        time.sleep(check_interval_secs)
    return released


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ktpu-release")
    p.add_argument("--registry", default="ghcr.io/k8s-tpu")
    p.add_argument("--out-dir", default="build/release")
    p.add_argument("--repo-dir", default=".")
    p.add_argument("--chart-version", default="0.1.0")
    p.add_argument("--store", default="",
                   help="artifact-store root (local dir standing in for "
                        "the GCS releases bucket); publishes chart + "
                        "latest/ alias + latest_release.json there")
    p.add_argument("--check-interval-secs", type=float, default=0,
                   help="continuous mode: poll the store's "
                        "latest_green.json and release when it moves "
                        "(the in-cluster releaser loop); requires --store")
    p.add_argument("--max-iterations", type=int, default=None)
    p.add_argument("--job-name", default="ci",
                   help="CI job whose latest_green.json to follow")
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)

    if args.check_interval_secs:
        if not args.store:
            p.error("--check-interval-secs requires --store")
        store = ArtifactStore(args.store)
        continuous_release(
            args.repo_dir, args.out_dir, args.registry, store,
            args.check_interval_secs, args.chart_version,
            dry_run=args.dry_run, max_iterations=args.max_iterations,
            job_name=args.job_name,
        )
        return 0

    if args.store:
        cut_release(args.repo_dir, args.out_dir, args.registry,
                    ArtifactStore(args.store), args.chart_version,
                    dry_run=args.dry_run)
        return 0

    tag = image_tag(args.repo_dir)
    print(f"release tag: {tag}")
    image = (
        build_operator_image(args.repo_dir, args.registry, dry_run=args.dry_run)
        if not args.dry_run
        else f"{args.registry}/tpu-operator:{tag}"
    )
    chart = package_chart(args.repo_dir, args.out_dir, f"{args.chart_version}+{tag}")
    manifest = write_release_manifest(args.out_dir, image, chart)
    print(f"chart: {chart}\nmanifest: {manifest}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
