"""kubectl-style CLI for local mode.

The reference's user surface is ``kubectl create -f tf_job.yaml``
(README quickstart). Local mode has no apiserver, so this CLI gives
the same verbs against a LocalWorld that lives for the command's
duration: ``create`` runs the job to completion (with real launcher
subprocesses), ``validate`` checks a manifest offline.
"""

from __future__ import annotations

import argparse
import sys

from k8s_tpu.client.job_client import load_tpu_job_yaml
from k8s_tpu import spec as S
from k8s_tpu.tools.local_world import LocalWorld


def cmd_validate(args) -> int:
    with open(args.file) as f:
        job = load_tpu_job_yaml(f.read())
    job.spec.set_defaults()
    try:
        job.spec.validate()
    except S.ValidationError as e:
        print(f"INVALID: {e}")
        return 1
    print(f"valid TpuJob {job.metadata.name or '<unnamed>'}")
    for r in job.spec.replica_specs:
        print(f"  {r.replica_type}: replicas={r.replicas} port={r.port}")
    if job.spec.tpu:
        t = job.spec.tpu.topology()
        print(
            f"  tpu: {job.spec.tpu.accelerator} ({t.chips} chips, "
            f"{t.num_hosts} hosts) × {job.spec.tpu.num_slices} slice(s)"
        )
    return 0


def cmd_create(args) -> int:
    with open(args.file) as f:
        text = f.read()
    with LocalWorld(subprocess_pods=not args.simulate, log_dir=args.log_dir) as world:
        job = world.api.create_from_yaml(text)
        print(f"tpujob.tpu.k8s.io/{job.metadata.name} created")
        if args.wait:
            final = world.api.wait_for_job(
                job.metadata.namespace or "default",
                job.metadata.name,
                timeout=args.timeout,
                status_callback=lambda j: print(
                    f"  phase={j.status.phase or 'None'} state={j.status.state}"
                ),
            )
            print(f"final: phase={final.status.phase} state={final.status.state}")
            return 0 if final.status.state == S.TpuJobState.SUCCEEDED else 1
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ktpu")
    sub = p.add_subparsers(dest="cmd", required=True)
    c = sub.add_parser("create", help="create a TpuJob in a local world and run it")
    c.add_argument("-f", "--file", required=True)
    c.add_argument("--wait", action="store_true", default=True)
    c.add_argument("--timeout", type=float, default=600.0)
    c.add_argument("--simulate", action="store_true", help="simulated pods")
    c.add_argument("--log-dir", default="/tmp/ktpu-logs")
    v = sub.add_parser("validate", help="validate a TpuJob manifest")
    v.add_argument("-f", "--file", required=True)
    args = p.parse_args(argv)
    return {"create": cmd_create, "validate": cmd_validate}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
