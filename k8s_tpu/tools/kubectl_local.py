"""kubectl-style CLI.

The reference's user surface is ``kubectl create -f tf_job.yaml``
(README quickstart). Two modes:

- default: verbs against a LocalWorld that lives for the command's
  duration — ``create`` runs the job to completion (with real launcher
  subprocesses), ``validate`` checks a manifest offline.
- ``--server URL`` (or ``KTPU_APISERVER_URL``): create/get/delete
  TpuJobs against a running apiserver (a real cluster via kubectl
  proxy, or ``python -m k8s_tpu.api.apiserver``) where a separately
  running operator reconciles them — the reference's actual
  deployment shape.
"""

from __future__ import annotations

import argparse
import os
import sys

from k8s_tpu.client.job_client import load_tpu_job_yaml
from k8s_tpu import spec as S
from k8s_tpu.tools.local_world import LocalWorld


def cmd_validate(args) -> int:
    with open(args.file) as f:
        job = load_tpu_job_yaml(f.read())
    job.spec.set_defaults()
    try:
        job.spec.validate()
    except S.ValidationError as e:
        print(f"INVALID: {e}")
        return 1
    print(f"valid TpuJob {job.metadata.name or '<unnamed>'}")
    for r in job.spec.replica_specs:
        print(f"  {r.replica_type}: replicas={r.replicas} port={r.port}")
    if job.spec.tpu:
        t = job.spec.tpu.topology()
        print(
            f"  tpu: {job.spec.tpu.accelerator} ({t.chips} chips, "
            f"{t.num_hosts} hosts) × {job.spec.tpu.num_slices} slice(s)"
        )
    return 0


def _remote_client(server: str):
    from k8s_tpu.api.crd_client import TpuJobClient
    from k8s_tpu.api.restcluster import RestCluster

    return TpuJobClient(RestCluster(server))


def _wait_remote(jc, namespace: str, name: str, timeout: float) -> int:
    import time

    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        j = jc.get(namespace, name)
        key = (j.status.phase, j.status.state)
        if key != last:
            print(f"  phase={j.status.phase or 'None'} state={j.status.state}")
            last = key
        if j.status.phase in (S.TpuJobPhase.DONE, S.TpuJobPhase.FAILED):
            return 0 if j.status.state == S.TpuJobState.SUCCEEDED else 1
        time.sleep(1.0)
    print("timeout waiting for job")
    return 1


def cmd_create(args) -> int:
    with open(args.file) as f:
        text = f.read()
    if args.server:
        jc = _remote_client(args.server)
        job = load_tpu_job_yaml(text)
        ns = job.metadata.namespace or "default"
        jc.create(job)
        print(f"tpujob.tpu.k8s.io/{job.metadata.name} created")
        if args.wait:
            return _wait_remote(jc, ns, job.metadata.name, args.timeout)
        return 0
    with LocalWorld(subprocess_pods=not args.simulate, log_dir=args.log_dir) as world:
        job = world.api.create_from_yaml(text)
        print(f"tpujob.tpu.k8s.io/{job.metadata.name} created")
        if args.wait:
            final = world.api.wait_for_job(
                job.metadata.namespace or "default",
                job.metadata.name,
                timeout=args.timeout,
                status_callback=lambda j: print(
                    f"  phase={j.status.phase or 'None'} state={j.status.state}"
                ),
            )
            print(f"final: phase={final.status.phase} state={final.status.state}")
            return 0 if final.status.state == S.TpuJobState.SUCCEEDED else 1
    return 0


_RESOURCE_WORDS = ("tpujobs", "tpujob", "tj")


def cmd_get(args) -> int:
    jc = _remote_client(args.server)
    # kubectl grammar: `get [tpujobs] [name]` — an optional resource
    # word then an optional name, so `get tpujobs`, `get tpujob myjob`,
    # and the bare `get myjob` all work (and a job literally named
    # "tpujob" is still reachable as `get tpujobs tpujob`)
    if args.resource in _RESOURCE_WORDS:
        pass  # name already holds the (optional) job name
    elif args.name is None:
        args.name = args.resource
    if args.name:
        j = jc.get(args.namespace, args.name)
        jobs = [j]
    else:
        jobs = jc.list(args.namespace)
    print(f"{'NAME':24} {'PHASE':10} {'STATE':10}")
    for j in jobs:
        print(f"{j.metadata.name:24} {j.status.phase or 'None':10} "
              f"{j.status.state or '-':10}")
    return 0


def cmd_delete(args) -> int:
    jc = _remote_client(args.server)
    jc.delete(args.namespace, args.name)
    print(f"tpujob.tpu.k8s.io/{args.name} deleted")
    return 0


def cmd_describe(args) -> int:
    """`kubectl describe`-style detail: spec summary, status, the
    condition ring, replica-state histograms, and this job's Events —
    the reference pointed users at `kubectl describe tfjobs`
    (README:437-479) for exactly this view."""
    from k8s_tpu.api import errors
    from k8s_tpu.api.client import KubeClient
    from k8s_tpu.api.crd_client import TpuJobClient
    from k8s_tpu.api.restcluster import RestCluster

    # kubectl grammar: optional resource word, then the name —
    # `describe tpujobs tj` reaches a job literally named "tj"
    if args.resource in _RESOURCE_WORDS:
        name = args.name
    else:
        name = args.name if args.name is not None else args.resource
    if not name:
        print("usage: describe [tpujobs] <name>")
        return 1
    rest = RestCluster(args.server)
    jc = TpuJobClient(rest)
    try:
        j = jc.get(args.namespace, name)
    except errors.NotFoundError:
        print(f"TpuJob {args.namespace}/{name} not found")
        return 1
    print(f"Name:       {j.metadata.name}")
    print(f"Namespace:  {j.metadata.namespace}")
    print(f"RuntimeId:  {j.spec.runtime_id or '<unassigned>'}")
    if j.spec.tpu is not None and j.spec.tpu.accelerator:
        print(f"TPU:        {j.spec.tpu.accelerator} × "
              f"{j.spec.tpu.num_slices} slice(s)")
    print("Replicas:")
    for r in j.spec.replica_specs:
        print(f"  {r.replica_type}: replicas={r.replicas} port={r.port}")
    s = j.status
    print(f"Phase:      {s.phase or 'None'}")
    print(f"State:      {s.state or '-'}")
    if s.reason:
        print(f"Reason:     {s.reason}")
    if s.gang_restarts:
        print(f"GangRestarts: {s.gang_restarts}/{j.spec.max_gang_restarts}")
    if s.replica_statuses:
        print("ReplicaStatuses:")
        for rs in s.replica_statuses:
            hist = " ".join(f"{k}={v}" for k, v in
                            sorted(rs.replicas_states.items()))
            print(f"  {rs.replica_type}: {rs.state}  [{hist}]")
    if s.conditions:
        print("Conditions:")
        for c in s.conditions:
            print(f"  {c.type}: {c.reason}")
    events = KubeClient(rest).events.list(args.namespace)
    mine = [e for e in events
            if (e.involved_object or {}).get("name") == name]
    if mine:
        print("Events:")
        for e in mine[-15:]:
            print(f"  {e.type:8} {e.reason:20} {e.message}")
    return 0


def cmd_logs(args) -> int:
    """`kubectl logs`-style: fetch a pod's log through the apiserver's
    pods/{name}/log subresource (the reference's debugging flow,
    README:497-563 — find pods by runtime_id, read their logs). With a
    TpuJob name, fetches the logs of its worker-0 pod; with an exact
    pod name, that pod."""
    from k8s_tpu.api import errors
    from k8s_tpu.api.client import KubeClient
    from k8s_tpu.api.restcluster import RestCluster
    from k8s_tpu.trainer import labels as L

    rest = RestCluster(args.server)
    name = args.name
    try:
        # exact pod name first — works even for a deleted/crashed pod,
        # whose log deliberately outlives it on the server
        sys.stdout.write(rest.pod_log(args.namespace, name,
                                      tail_lines=args.tail))
        return 0
    except errors.NotFoundError:
        pass
    # a TpuJob name: resolve its pods by the job-name label, ordered by
    # the numeric task_index label (name sort would put 10 before 2)
    pods = [
        p for p in KubeClient(rest).pods.list(args.namespace)
        if (p.metadata.labels or {}).get(L.JOB_NAME_LABEL) == name
    ]
    pods.sort(key=lambda p: int(
        (p.metadata.labels or {}).get(L.TASK_INDEX_LABEL, "0") or 0))
    if not pods:
        print(f"no pod log or TpuJob pods named {name!r}")
        return 1
    idx = min(max(args.index, 0), len(pods) - 1)
    pod_name = pods[idx].metadata.name
    print(f"# logs of {pod_name}", flush=True)
    try:
        sys.stdout.write(rest.pod_log(args.namespace, pod_name,
                                      tail_lines=args.tail))
    except errors.NotFoundError as e:
        print(f"logs unavailable: {e}")
        return 1
    return 0


def main(argv=None) -> int:
    default_server = os.environ.get("KTPU_APISERVER_URL", "")
    p = argparse.ArgumentParser(prog="ktpu")
    sub = p.add_subparsers(dest="cmd", required=True)
    c = sub.add_parser("create", help="create a TpuJob (local world, or --server)")
    c.add_argument("-f", "--file", required=True)
    c.add_argument("--wait", action="store_true", default=True)
    c.add_argument("--timeout", type=float, default=600.0)
    c.add_argument("--simulate", action="store_true", help="simulated pods")
    c.add_argument("--log-dir", default="/tmp/ktpu-logs")
    c.add_argument("--server", default=default_server,
                   help="apiserver URL (default: $KTPU_APISERVER_URL)")
    v = sub.add_parser("validate", help="validate a TpuJob manifest")
    v.add_argument("-f", "--file", required=True)
    g = sub.add_parser("get", help="list/get TpuJobs on an apiserver")
    g.add_argument("resource", nargs="?", default=None,
                   help="kubectl-style resource word (tpujobs) or a job name")
    g.add_argument("name", nargs="?", default=None)
    g.add_argument("-n", "--namespace", default="default")
    g.add_argument("--server", default=default_server, required=not default_server)
    d = sub.add_parser("delete", help="delete a TpuJob on an apiserver")
    d.add_argument("name")
    d.add_argument("-n", "--namespace", default="default")
    d.add_argument("--server", default=default_server, required=not default_server)
    ds = sub.add_parser("describe",
                        help="detailed status + conditions + events")
    ds.add_argument("resource", nargs="?", default=None,
                    help="kubectl-style resource word (tpujobs) or a job name")
    ds.add_argument("name", nargs="?", default=None)
    ds.add_argument("-n", "--namespace", default="default")
    ds.add_argument("--server", default=default_server,
                    required=not default_server)
    lg = sub.add_parser("logs", help="pod logs via the apiserver "
                                     "(pod name or TpuJob name)")
    lg.add_argument("name")
    lg.add_argument("-n", "--namespace", default="default")
    lg.add_argument("--tail", type=int, default=None,
                    help="last N lines only")
    lg.add_argument("--index", type=int, default=0,
                    help="which replica's pod when given a TpuJob name")
    lg.add_argument("--server", default=default_server,
                    required=not default_server)
    args = p.parse_args(argv)
    return {"create": cmd_create, "validate": cmd_validate,
            "get": cmd_get, "delete": cmd_delete,
            "describe": cmd_describe, "logs": cmd_logs}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
