"""LocalWorld: a complete single-host control+data plane in one object.

Bundles the in-memory cluster, CRD client, controller, and kubelet —
the "ephemeral GKE cluster per CI run" of the reference's test infra
(SURVEY §4 tier 3), collapsed to one process with real subprocess
execution when requested.
"""

from __future__ import annotations

from typing import Optional

from k8s_tpu.api.client import KubeClient
from k8s_tpu.api.cluster import InMemoryCluster
from k8s_tpu.api.crd_client import TpuJobClient
from k8s_tpu.client.job_client import TpuJobApi
from k8s_tpu.controller.controller import Controller
from k8s_tpu.runtime.kubelet import LocalKubelet, SimulatedExecutor, SubprocessExecutor
from k8s_tpu.spec import ControllerConfig


class LocalWorld:
    def __init__(
        self,
        subprocess_pods: bool = False,
        log_dir: Optional[str] = None,
        config: Optional[ControllerConfig] = None,
        reconcile_interval: float = 0.1,
        executor=None,
    ):
        self.cluster = InMemoryCluster()
        self.client = KubeClient(self.cluster)
        self.job_client = TpuJobClient(self.cluster)
        self.api = TpuJobApi(self.job_client)
        self.controller = Controller(
            self.client,
            self.job_client,
            config or ControllerConfig(),
            reconcile_interval=reconcile_interval,
        )
        if executor is None:
            if subprocess_pods:
                executor = SubprocessExecutor(
                    log_dir=log_dir,
                    extra_env={
                        "KTPU_FORCE_PLATFORM": "cpu",
                        "KTPU_NUM_CPU_DEVICES": "2",
                    },
                )
            else:
                executor = SimulatedExecutor(exit_code=0)
        self.kubelet = LocalKubelet(self.client, executor)

    def start(self) -> "LocalWorld":
        self.kubelet.start()
        self.controller.start()
        return self

    def stop(self) -> None:
        self.controller.stop()
        self.kubelet.stop()

    def __enter__(self) -> "LocalWorld":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
