"""Trigger/poll client for a REMOTE CI orchestrator.

The reference triggers its e2e pipeline on a remote Airflow over REST
and polls the final task's state to completion, retrieving result
artifacts afterwards (``/root/reference/py/airflow.py:27-118`` — the
trigger_dag/get_task_status/wait loop). ``ci/run_ci.py`` runs this
repo's stage DAG in-process; this module is the remote half of that
story: point it at an orchestrator service and drive a run from a
laptop, a cron job, or another cluster without importing the CI code.

Endpoint shape (any service can implement it; the test stub in
``tests/test_tools.py`` is the contract):

- ``POST {base}/api/v1/dags/{dag}/runs``  body ``{"conf": {...}}``
  → ``{"run_id": ...}``
- ``GET  {base}/api/v1/dags/{dag}/runs/{run}/tasks/{task}``
  → ``{"state": "queued|running|succeeded|failed|upstream_failed"}``
- ``GET  {base}/api/v1/dags/{dag}/runs/{run}/results/{key}``
  → arbitrary JSON (the xcom-style result retrieval)

stdlib-only (urllib): this rides in the same no-dependency tier as the
launcher.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional

# states that mean "still going" — anything else is terminal, including
# the reference's "upstream_failed" (an earlier stage died and the
# final task will never run)
NONTERMINAL_STATES = ("", "none", "queued", "running")


class OrchestratorError(IOError):
    """Server-reported failure (non-2xx with an error payload)."""


class RemoteOrchestratorClient:
    """Minimal trigger/poll/result client. ``token`` is sent as a
    Bearer header when given (the deployment-agnostic stand-in for the
    reference's google-auth credential refresh)."""

    def __init__(self, base_url: str, token: Optional[str] = None,
                 request_timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.request_timeout = float(request_timeout)

    def _request(self, path: str, method: str = "GET",
                 json_body: Optional[dict] = None) -> dict:
        url = self.base_url + path
        data = json.dumps(json_body).encode() if json_body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except Exception:  # noqa: BLE001 - body may be anything
                payload = {}
            raise OrchestratorError(
                payload.get("error", f"server error {e.code}")) from e

    # -- API ---------------------------------------------------------------

    def trigger_run(self, dag_id: str,
                    conf: Optional[Dict] = None) -> str:
        data = self._request(
            f"/api/v1/dags/{dag_id}/runs", method="POST",
            json_body={"conf": conf or {}},
        )
        return data["run_id"]

    def get_task_state(self, dag_id: str, run_id: str,
                       task_id: str) -> str:
        data = self._request(
            f"/api/v1/dags/{dag_id}/runs/{run_id}/tasks/{task_id}")
        return str(data.get("state", ""))

    def get_result(self, dag_id: str, run_id: str, key: str) -> dict:
        """Fetch a run artifact by key — the xcom-retrieval analogue."""
        return self._request(
            f"/api/v1/dags/{dag_id}/runs/{run_id}/results/{key}")

    def wait_for_run(
        self,
        dag_id: str,
        run_id: str,
        final_task: str = "done",
        timeout: float = 1800.0,
        polling_interval: float = 15.0,
        on_status: Optional[Callable[[str], None]] = None,
    ) -> str:
        """Poll the final task until it leaves the non-terminal states;
        returns the terminal state. ``on_status`` (optional) receives
        every observed state — progress reporting without coupling to a
        logger. Raises TimeoutError when the deadline passes."""
        deadline = time.monotonic() + timeout
        while True:
            state = self.get_task_state(dag_id, run_id, final_task)
            if on_status is not None:
                on_status(state)
            if state.lower() not in NONTERMINAL_STATES:
                return state
            now = time.monotonic()
            if now >= deadline:
                raise TimeoutError(
                    f"run {run_id} of dag {dag_id} did not finish "
                    f"within {timeout}s (last state: {state or 'none'})"
                )
            # never oversleep the deadline: the FULL budget gets a final
            # poll (a run finishing in the last partial interval counts)
            time.sleep(min(polling_interval, deadline - now))


def run_and_wait(client: RemoteOrchestratorClient, dag_id: str,
                 conf: Optional[Dict] = None, **wait_kw) -> str:
    """Trigger + wait in one call (the reference's
    ``_run_dag_and_wait`` shape). Returns the terminal state."""
    run_id = client.trigger_run(dag_id, conf)
    return client.wait_for_run(dag_id, run_id, **wait_kw)
