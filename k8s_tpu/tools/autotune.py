"""Compiler/parallelism autotune harness: folklore → measured config.

The train-step knobs this repo grew — ZeRO stage, gradient-accumulation
depth, the latency-hiding scheduler, donation, remat policy, raw XLA
flags — interact in ways nobody should be asked to reason about from
first principles (the TensorFlow system paper's ethos: tuning knobs get
*measured*, PAPERS.md arXiv:1605.08695). This module sweeps a declared
grid of candidates over ONE train-step setup, gates every candidate
through the ``hlo_lint`` machinery (a config that compiles to
involuntary rematerialization or a backward all-gather is wrong, not
slow — it is rejected before any timing), times the survivors, and
emits a ranked JSON artifact whose winner round-trips directly into
``make_train_step(**chosen["make_train_step_kwargs"])``.

Grid format (JSON-able; every axis is a list, candidates are the
cartesian product in sorted-key order, so candidate order — and
therefore tie-breaks — is deterministic)::

    {
      "axes": {
        "zero_stage": [0, 1, 2, 3],
        "accum_steps": [1, 2],
        "latency_hiding": [false],
        "donate": [true],
        "remat_policy": ["off"],          # "off" | model policy name
        "compiler_options": [null]        # null | {"xla_flag": "val"}
      },
      "zero3_leaves": ["embedding", "lm_head"],   # used when stage == 3
      "gates": {
        "max_involuntary_remat": 0,
        "max_backward_all_gather": 0
      }
    }

Two timers:

- ``stub`` — a deterministic surrogate computed from the compiled
  program alone (collective bytes + op count + remat penalty). Same
  HLO in, same number out: the CI stage ranks the stand-in grid with
  it so the artifact is reproducible and the golden
  (``ci/autotune/``) can pin the CHOSEN config, its collective
  signature, and its surrogate cost. It is a scheduling cost model,
  not a clock — use it to compare programs, never to report time.
- ``wall`` — min-of-N real step executions (min, not mean: the minimum
  is the contention-free estimate, the same policy as
  ``benches/*_bench``). ``benches/autotune_bench.py`` runs the same
  grid under this timer on real hardware.

CLI::

    python -m k8s_tpu.tools.autotune --grid standin --timer stub \
        --out /tmp/autotune.json            # sweep + write artifact
    python -m k8s_tpu.tools.autotune --grid standin --timer stub \
        --check                             # sweep + diff vs ci/autotune/

``--check`` fails (exit 1) when the chosen config changed, its
collective signature changed, its surrogate cost regressed past the
golden's 25% headroom, or any candidate's accept/reject status flipped
— the same loud-diff contract as the HLO budget goldens.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
from itertools import product
from typing import Any, Callable, Dict, List, Optional, Tuple

from k8s_tpu.tools.hlo_lint import capture_stderr, lint_compiled

DEFAULT_ARTIFACT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "ci", "autotune",
)

# The CI stand-in grid (8-device virtual CPU mesh, tiny llama): the
# ZeRO ladder × accumulation depth, gated hard — accum_steps=2
# candidates compile with one involuntary remat and scan-internal
# backward gathers on this backend (pinned as such by
# ci/hlo_budgets/standin-zero2-dp-cpu8.json), so under these gates the
# artifact DEMONSTRATES lint rejection on every CI run while the
# accum=1 ladder is ranked. Wall-clock tuning on real hardware relaxes
# the gates to that config's own budget instead.
STANDIN_GRID: Dict[str, Any] = {
    "axes": {
        "zero_stage": [0, 1, 2, 3],
        "accum_steps": [1, 2],
        "latency_hiding": [False],
        "donate": [True],
        "remat_policy": ["off"],
        "compiler_options": [None],
    },
    "zero3_leaves": ["embedding", "lm_head"],
    "gates": {
        "max_involuntary_remat": 0,
        "max_backward_all_gather": 0,
    },
}

GRIDS: Dict[str, Dict[str, Any]] = {"standin": STANDIN_GRID}


def expand_grid(grid: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cartesian product of ``grid["axes"]`` in sorted-key order —
    deterministic candidate order, so ranking tie-breaks and golden
    diffs are stable across runs."""
    axes = grid.get("axes", {})
    keys = sorted(axes)
    out = []
    for combo in product(*(axes[k] for k in keys)):
        out.append(dict(zip(keys, combo)))
    return out


@dataclasses.dataclass
class TuneSetup:
    """One train-step problem the grid is swept over: everything a
    candidate needs to build, compile, and run a step."""

    make_state: Callable[[Dict[str, Any]], Any]   # candidate → TrainState
    make_loss: Callable[[Dict[str, Any]], Any]    # candidate → loss_fn
    mesh: Any
    rules: Any
    batch: Any
    rng: Any


def _standin_setup(grid: Dict[str, Any]) -> TuneSetup:
    """The stand-in problem: tiny llama on the 8-device virtual CPU DP
    mesh — the same shapes as the zero* hlo_lint stand-ins, so the lint
    gates and the HLO budget goldens talk about the same programs."""
    import jax
    import jax.numpy as jnp
    import optax

    from k8s_tpu.models import LlamaConfig, LlamaForCausalLM
    from k8s_tpu.ops.fused_ce import fused_lm_head_cross_entropy
    from k8s_tpu.parallel import LogicalRules, MeshConfig, build_mesh
    from k8s_tpu.train import create_sharded_state, make_batch_sharder

    mesh = build_mesh(MeshConfig(data=8), devices=jax.devices()[:8])
    rules = LogicalRules(LogicalRules.DP)
    example = jnp.zeros((8, 64), jnp.int32)
    zero3_leaves = list(grid.get("zero3_leaves") or [])

    def model_for(cand: Dict[str, Any]):
        policy = cand.get("remat_policy", "off")
        cfg = LlamaConfig.tiny(
            num_heads=4, num_kv_heads=2, head_dim=32, attention="flash",
            remat=policy != "off",
            **({"remat_policy": policy} if policy != "off" else {}),
        )
        return LlamaForCausalLM(cfg), cfg

    def make_state(cand: Dict[str, Any]):
        model, _ = model_for(cand)
        stage = int(cand.get("zero_stage", 0))
        return create_sharded_state(
            model, optax.adamw(1e-3), mesh, rules,
            jax.random.PRNGKey(0), example,
            zero_stage=stage,
            zero3_leaves=zero3_leaves if stage >= 3 else None,
        )

    def make_loss(cand: Dict[str, Any]):
        _, cfg = model_for(cand)

        def loss_fn(st, params, b, rng):
            hidden = st.apply_fn(
                {"params": params}, b["input_ids"], return_hidden=True
            )
            return fused_lm_head_cross_entropy(
                hidden[:, :-1], params["lm_head"]["kernel"],
                b["input_ids"][:, 1:], target_chunk=cfg.vocab_size // 4,
                mesh=mesh,
            ), {}

        return loss_fn

    batch = make_batch_sharder(mesh, rules)({"input_ids": example})
    return TuneSetup(make_state=make_state, make_loss=make_loss,
                     mesh=mesh, rules=rules, batch=batch,
                     rng=jax.random.PRNGKey(2))


def step_kwargs_of(cand: Dict[str, Any]) -> Dict[str, Any]:
    """The ``make_train_step`` kwargs a candidate denotes — exactly
    what ``chosen["make_train_step_kwargs"]`` carries, so a consumer
    builds the winning step with ``make_train_step(loss_fn, mesh,
    rules, **kwargs)`` and nothing else."""
    return {
        "zero_stage": int(cand.get("zero_stage", 0)),
        "accum_steps": int(cand.get("accum_steps", 1)),
        "latency_hiding": bool(cand.get("latency_hiding", False)),
        "donate": bool(cand.get("donate", True)),
        "compiler_options": cand.get("compiler_options") or None,
    }


def gate_report(report: dict, gates: Dict[str, Any]) -> List[str]:
    """Human-readable gate violations for one candidate's lint report
    (empty = accepted). Mirrors the hlo_lint budget wording so CI
    output reads the same in both stages."""
    reasons: List[str] = []
    max_remat = int(gates.get("max_involuntary_remat", 0))
    got_remat = int(report.get("involuntary_remat", 0))
    if got_remat > max_remat:
        reasons.append(
            f"involuntary_remat: {got_remat} > gate {max_remat}")
    max_bwd_ag = gates.get("max_backward_all_gather")
    if max_bwd_ag is not None:
        got = int(report.get("backward", {}).get("all-gather", 0))
        if got > int(max_bwd_ag):
            reasons.append(
                f"backward all-gather: {got} > gate {max_bwd_ag}")
    max_bytes = gates.get("max_collective_bytes")
    if max_bytes is not None:
        got_b = int(report.get("total_collective_bytes", 0))
        if got_b > int(max_bytes):
            reasons.append(
                f"total_collective_bytes: {got_b} > gate {max_bytes}")
    return reasons


def stub_cost_ms(report: dict, cand: Dict[str, Any]) -> float:
    """The deterministic surrogate the CI ranking runs on: bytes moved
    by collectives dominate, op count (dispatch overhead) and any
    involuntary remat (a full re-partition round trip) penalize. Pure
    function of the compiled program + candidate — same inputs, same
    ranking, which is what lets ci/autotune/ pin the chosen config."""
    n_ops = sum(report.get("collectives", {}).values())
    return round(
        report.get("total_collective_bytes", 0) / 1e6
        + 0.05 * n_ops
        + 5.0 * report.get("involuntary_remat", 0),
        6,
    )


def time_step_wall(step, state, batch, rng, repeat: int = 5) -> float:
    """Min-of-N wall-clock step time in ms (one warmup/compile call
    outside the timed region; min is the contention-free estimate)."""
    import jax

    new_state, metrics = step(state, batch, rng)
    jax.block_until_ready(metrics)
    best = float("inf")
    for _ in range(max(1, repeat)):
        t0 = time.perf_counter()
        new_state, metrics = step(new_state, batch, rng)
        jax.block_until_ready(metrics)
        best = min(best, (time.perf_counter() - t0) * 1e3)
    return round(best, 3)


def evaluate_candidate(
    setup: TuneSetup,
    cand: Dict[str, Any],
    gates: Dict[str, Any],
    timer: str = "stub",
    repeat: int = 5,
) -> Dict[str, Any]:
    """Build + compile one candidate, lint-gate it, time it if it
    survives. Never raises on a candidate's own failure — a candidate
    that cannot compile is a *result* (status "compile_error"), not an
    abort of the sweep."""
    import flax.linen as nn

    from k8s_tpu.train import make_train_step

    entry: Dict[str, Any] = {"config": dict(cand), "status": "ok",
                             "reasons": []}
    try:
        state = setup.make_state(cand)
        loss_fn = setup.make_loss(cand)
        step = make_train_step(
            loss_fn, setup.mesh, setup.rules, **step_kwargs_of(cand)
        )
        with nn.logical_axis_rules(setup.rules.to_flax()):
            # the aot gate: lower+compile of the EXACT program the step
            # would run (compiler options included via the AOT path)
            with capture_stderr() as cap:
                compiled = step.jitted.compiled(
                    state, setup.batch, setup.rng)
        report = lint_compiled(compiled, setup.mesh, cap.text)
    except Exception as e:  # noqa: BLE001 — candidate, not harness, failed
        entry["status"] = "compile_error"
        entry["reasons"] = [f"{type(e).__name__}: {e}"]
        return entry
    entry["lint"] = {
        "collectives": report["collectives"],
        "backward": report["backward"],
        "involuntary_remat": report["involuntary_remat"],
        "total_collective_bytes": report["total_collective_bytes"],
    }
    reasons = gate_report(report, gates or {})
    if reasons:
        entry["status"] = "rejected"
        entry["reasons"] = reasons
        return entry
    if timer == "stub":
        entry["step_time_ms"] = stub_cost_ms(report, cand)
    else:
        with nn.logical_axis_rules(setup.rules.to_flax()):
            entry["step_time_ms"] = time_step_wall(
                step, state, setup.batch, setup.rng, repeat=repeat)
    return entry


def run_grid(
    grid: Dict[str, Any],
    setup: Optional[TuneSetup] = None,
    timer: str = "stub",
    repeat: int = 5,
) -> Dict[str, Any]:
    """Sweep the grid and return the ranked artifact."""
    setup = setup or _standin_setup(grid)
    gates = grid.get("gates", {})
    candidates = [
        evaluate_candidate(setup, cand, gates, timer=timer, repeat=repeat)
        for cand in expand_grid(grid)
    ]
    accepted = [c for c in candidates if c["status"] == "ok"]
    # stable sort: equal times keep grid order (deterministic ties)
    accepted.sort(key=lambda c: c["step_time_ms"])
    for i, c in enumerate(accepted):
        c["rank"] = i
    artifact: Dict[str, Any] = {
        "grid": grid,
        "timer": timer,
        "mesh": {k: int(v) for k, v in setup.mesh.shape.items()},
        "candidates": candidates,
        "n_accepted": len(accepted),
        "n_rejected": sum(c["status"] == "rejected" for c in candidates),
        "n_compile_error": sum(
            c["status"] == "compile_error" for c in candidates),
    }
    if accepted:
        best = accepted[0]
        artifact["chosen"] = {
            "config": best["config"],
            "step_time_ms": best["step_time_ms"],
            "collectives": best["lint"]["collectives"],
            "backward": best["lint"]["backward"],
            "make_train_step_kwargs": step_kwargs_of(best["config"]),
        }
    return artifact


# ---------------------------------------------------------------------------
# Golden check (ci/autotune/)
# ---------------------------------------------------------------------------


def _cand_key(config: Dict[str, Any]) -> str:
    return json.dumps(config, sort_keys=True)


def check_artifact(artifact: dict, golden: dict) -> List[str]:
    """Readable diffs between a fresh sweep and the committed golden.
    Pins: the chosen config, its collective signature, its surrogate
    cost (25% headroom — the hlo-budget bytes policy), and every
    candidate's accept/reject status. Times of non-chosen candidates
    and raw byte counts float free."""
    diffs: List[str] = []
    got_chosen = artifact.get("chosen", {})
    want_chosen = golden.get("chosen", {})
    if got_chosen.get("config") != want_chosen.get("config"):
        diffs.append(
            "chosen config changed: "
            f"{_cand_key(got_chosen.get('config', {}))} != golden "
            f"{_cand_key(want_chosen.get('config', {}))}")
    for sig in ("collectives", "backward"):
        if got_chosen.get(sig) != want_chosen.get(sig):
            diffs.append(
                f"chosen {sig} signature changed: {got_chosen.get(sig)} "
                f"!= golden {want_chosen.get(sig)}")
    want_t = want_chosen.get("step_time_ms")
    got_t = got_chosen.get("step_time_ms")
    if want_t is not None and got_t is not None and got_t > want_t * 1.25:
        diffs.append(
            f"chosen step_time_ms regressed: {got_t} > {want_t} * 1.25")
    want_status = {
        _cand_key(c["config"]): c["status"]
        for c in golden.get("candidates", [])
    }
    got_status = {
        _cand_key(c["config"]): c["status"]
        for c in artifact.get("candidates", [])
    }
    for key in sorted(set(want_status) | set(got_status)):
        g, w = got_status.get(key, "MISSING"), want_status.get(key, "MISSING")
        if g != w:
            diffs.append(f"candidate {key}: status {g} != golden {w}")
    return diffs


def artifact_path(artifact_dir: str, name: str) -> str:
    return os.path.join(artifact_dir, f"{name}.json")


def save_artifact(path: str, artifact: dict) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser("autotune")
    ap.add_argument("--grid", default="standin",
                    help="named grid (%s) or a path to a grid JSON"
                         % "/".join(sorted(GRIDS)))
    ap.add_argument("--timer", choices=("stub", "wall"), default="stub")
    ap.add_argument("--repeat", type=int, default=5,
                    help="N for the wall timer's min-of-N")
    ap.add_argument("--out", default="",
                    help="write the ranked artifact here")
    ap.add_argument("--check", action="store_true",
                    help="diff against the committed golden "
                         "(ci/autotune/<grid>-grid-cpu8.json)")
    ap.add_argument("--write-golden", action="store_true",
                    help="(re)write the golden from this run")
    ap.add_argument("--golden-dir", default=DEFAULT_ARTIFACT_DIR)
    args = ap.parse_args(argv)

    # virtual CPU mesh before first device query (hlo_lint's approach)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

    if args.grid in GRIDS:
        grid_name, grid = args.grid, GRIDS[args.grid]
    else:
        with open(args.grid) as f:
            grid = json.load(f)
        grid_name = os.path.splitext(os.path.basename(args.grid))[0]

    artifact = run_grid(grid, timer=args.timer, repeat=args.repeat)
    if args.out:
        save_artifact(args.out, artifact)
    golden_path = artifact_path(args.golden_dir, f"{grid_name}-grid-cpu8")
    if args.write_golden:
        save_artifact(golden_path, artifact)
        print(json.dumps({"grid": grid_name, "wrote": golden_path,
                          "chosen": artifact.get("chosen", {}).get("config"),
                          "n_accepted": artifact["n_accepted"],
                          "n_rejected": artifact["n_rejected"]}))
        return 0
    summary = {
        "grid": grid_name,
        "timer": args.timer,
        "chosen": artifact.get("chosen", {}).get("config"),
        "chosen_step_time_ms": artifact.get("chosen", {}).get("step_time_ms"),
        "n_accepted": artifact["n_accepted"],
        "n_rejected": artifact["n_rejected"],
        "n_compile_error": artifact["n_compile_error"],
    }
    if not args.check:
        print(json.dumps(summary))
        return 0
    if not os.path.exists(golden_path):
        summary["golden"] = "MISSING"
        summary["hint"] = (
            f"run: python -m k8s_tpu.tools.autotune --grid {grid_name} "
            f"--write-golden")
        print(json.dumps(summary))
        return 1
    with open(golden_path) as f:
        golden = json.load(f)
    diffs = check_artifact(artifact, golden)
    summary["golden"] = "FAIL" if diffs else "ok"
    summary["diffs"] = diffs
    print(json.dumps(summary))
    for d in diffs:
        print(f"AUTOTUNE GOLDEN DIFF [{grid_name}]: {d}", file=sys.stderr)
    return 1 if diffs else 0


if __name__ == "__main__":
    sys.exit(main())
