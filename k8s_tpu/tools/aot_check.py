"""AOT compile the north-star configs against virtual TPU topologies.

VERDICT r3 item 1: BASELINE.md configs #4 (BERT-base TP, v5p-64) and
#5 (Llama-3-8B FSDP, multi-slice v5p-128) had only tiny-shape proxies —
nothing validated that the REAL models' sharded HLO compiles, that
per-device HBM fits, or what the collective schedule is. This tool
closes that gap without hardware: ``jax.jit(...).lower().compile()``
against a deviceless TPU topology (`jax.experimental.topologies`) runs
the real XLA TPU compiler (libtpu), yielding the exact per-device
memory breakdown and the collective schedule of the program the chips
would execute.

Multi-slice note: the virtual topology is one ICI domain; config #5's
2-slice mesh is compiled with ``data=2`` as the OUTERMOST mesh axis —
the axis the production job maps across DCN. The HLO collective
schedule (which collectives, over which axes, how many) is identical;
only the link a given all-reduce rides differs at runtime.

Usage::

    python -m k8s_tpu.tools.aot_check --config llama3-8b-v5p128
    python -m k8s_tpu.tools.aot_check --all [--json PATH]

Each config prints one JSON line: per-device argument/temp bytes, the
HBM budget verdict, collective op counts, and FLOPs/step from XLA's
cost analysis. CI runs ``--all`` as a stage (ci/run_ci.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict

import jax

# v5p: 95 GB HBM per chip; leave headroom for XLA's runtime buffers
HBM_BYTES = 95 * 1024**3
HBM_BUDGET = int(HBM_BYTES * 0.95)

COLLECTIVES = (
    "all-gather", "reduce-scatter", "all-reduce", "collective-permute",
    "all-to-all",
)


def _topology_mesh(topology: str, axis_sizes: Dict[str, int]):
    """Virtual TPU mesh: topology string (e.g. ``v5p:4x4x4`` = 64
    chips = the GCP ``v5p-128`` core count) + named axis sizes."""
    from jax.experimental import topologies

    from k8s_tpu.parallel.mesh import AXES, MeshConfig, build_mesh

    topo = topologies.get_topology_desc(topology, "tpu")
    cfg = MeshConfig(**axis_sizes)
    return build_mesh(cfg, devices=list(topo.devices))


def _abstract_sharded_state(model, optimizer, mesh, rules, example):
    """ShapeDtypeStructs (with shardings) of the full TrainState,
    derived WITHOUT materializing anything — eval_shape of the same
    build create_sharded_state runs for real."""
    import flax.linen as nn
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from k8s_tpu.train.trainer_lib import TrainState

    def boxed_init():
        return model.init(jax.random.PRNGKey(0), example)

    abstract_boxed = jax.eval_shape(boxed_init)
    logical = nn.get_partition_spec(abstract_boxed)
    mesh_specs = nn.logical_to_mesh(logical, rules.to_flax())
    var_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s) if isinstance(s, P)
        else NamedSharding(mesh, P()),
        nn.unbox(mesh_specs),
        is_leaf=lambda x: isinstance(x, P),
    )
    abstract = nn.unbox(abstract_boxed)
    params = abstract["params"]
    param_shardings = var_shardings["params"]

    def build_state(params):
        return TrainState.create(
            apply_fn=model.apply, params=params, tx=optimizer,
            batch_stats=abstract.get("batch_stats"),
        )

    abs_state = jax.eval_shape(build_state, params)

    # shardings shaped like the state: params subtrees keep their
    # layout (the ZeRO invariant create_sharded_state enforces),
    # everything else is replicated
    params_treedef = jax.tree_util.tree_structure(params)

    def is_params_like(x):
        try:
            return jax.tree_util.tree_structure(x) == params_treedef
        except Exception:
            return False

    repl = NamedSharding(mesh, P())

    def shardings_like(sub):
        if is_params_like(sub):
            return param_shardings
        return jax.tree_util.tree_map(lambda _: repl, sub)

    state_shardings = jax.tree_util.tree_map(
        shardings_like, abs_state, is_leaf=is_params_like
    )
    return jax.tree_util.tree_map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        abs_state, state_shardings,
    )


def _abstract_batch(batch_shapes, mesh, rules):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    axes = rules["batch"]
    out = {}
    for k, (shape, dtype) in batch_shapes.items():
        spec = P(axes) if len(shape) >= 1 else P()
        out[k] = jax.ShapeDtypeStruct(
            shape, jnp.dtype(dtype), sharding=NamedSharding(mesh, spec)
        )
    return out


def _abstract_rng(mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    a = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    return jax.ShapeDtypeStruct(
        a.shape, a.dtype, sharding=NamedSharding(mesh, P())
    )


def _compile_and_report(name, step_fn, abs_state, abs_batch, mesh, rules,
                        hbm_budget=HBM_BUDGET):
    import flax.linen as nn

    from k8s_tpu.tools.hlo_lint import capture_stderr

    with nn.logical_axis_rules(rules.to_flax()):
        lowered = step_fn.jitted.lower(abs_state, abs_batch, _abstract_rng(mesh))
    # fd-level capture: the SPMD partitioner's involuntary-remat
    # fallback warnings are C++ stderr, invisible to Python redirection
    # and absent from the HLO text — this is the only place to count
    # them (re-emitted on exit, nothing is swallowed)
    with capture_stderr() as cap:
        compiled = lowered.compile()
    return _report_compiled(name, compiled, mesh, hbm_budget,
                            spmd_log=cap.text)


def count_collectives(hlo: str) -> Dict[str, int]:
    """Static collective-op counts from optimized HLO text. Counts
    sync + async (-start) forms, and reclassifies the TPU backend's
    fused reduce-scatter representation (kind=kCustom fusions calling
    %all-reduce-scatter.* computations whose body holds a layout-
    constrained all-reduce) — counting text alone reads those as
    all-reduce and reports RS=0, the round-4 misread. One parser for
    all of that lives in hlo_lint.parse_collectives; this is its
    count-only aggregation (a fix there must not diverge from the
    budget the CI gate enforces)."""
    from k8s_tpu.tools.hlo_lint import parse_collectives

    counts = {op: 0 for op in COLLECTIVES}
    for c in parse_collectives(hlo):
        counts[c.kind] += 1
    return counts


def _report_compiled(name, compiled, mesh, hbm_budget=HBM_BUDGET,
                     spmd_log=""):
    from k8s_tpu.tools.hlo_lint import lint_report

    ma = compiled.memory_analysis()
    hlo = compiled.as_text()
    # note a lax.scan body counts each collective ONCE however many
    # layers iterate through it
    counts = count_collectives(hlo)
    lint = lint_report(hlo, {k: int(v) for k, v in mesh.shape.items()},
                       spmd_log=spmd_log)
    cost = compiled.cost_analysis()
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0)) if cost else 0.0
    # per-device residency: donated state aliases in place (alias_size),
    # so peak = live arguments + temp workspace
    arg = int(ma.argument_size_in_bytes)
    temp = int(ma.temp_size_in_bytes)
    out_b = int(ma.output_size_in_bytes)
    alias = int(ma.alias_size_in_bytes)
    peak = arg + temp
    result = {
        "config": name,
        "devices": int(mesh.size),
        "mesh": {k: int(v) for k, v in mesh.shape.items() if v > 1},
        "arg_bytes_per_device": arg,
        "temp_bytes_per_device": temp,
        "output_bytes_per_device": out_b,
        "aliased_bytes": alias,
        "peak_bytes_per_device": peak,
        "peak_gib_per_device": round(peak / 1024**3, 2),
        "hbm_budget_gib": round(hbm_budget / 1024**3, 2),
        "fits_hbm": peak <= hbm_budget,
        "collectives": counts,
        "flops_per_step_per_device": flops,
        "tflops_per_step_per_device": round(flops / 1e12, 1),
        # the collective-budget linter's view: per-axis / fwd-vs-bwd
        # counts, bytes moved, involuntary-resharding fallbacks — the
        # shape ci/hlo_budgets/ manifests are checked against
        "lint": lint,
    }
    return result


def check_llama3_8b_v5p128():
    """Config #5: Llama-3-8B, FSDP over multi-slice v5p-128 (64 chips,
    2 slices x 32): data=2 outermost (the DCN axis), fsdp=32 inside the
    slice. The REAL production config: 32 layers / 4096 hidden / 128k
    vocab / seq 8192, scan+remat, flash attention kernels, fused-CE
    head — exactly programs/llama_train.py's llama3-8b path."""
    import jax.numpy as jnp
    import optax

    from k8s_tpu.models import LlamaConfig, LlamaForCausalLM
    from k8s_tpu.ops.fused_ce import fused_lm_head_cross_entropy
    from k8s_tpu.parallel import LogicalRules
    from k8s_tpu.train import create_sharded_state, make_train_step  # noqa: F401

    mesh = _topology_mesh("v5p:4x4x4", dict(data=2, fsdp=32))
    rules = LogicalRules(LogicalRules.FSDP)
    cfg = LlamaConfig.llama3_8b(attention="flash", mesh=mesh)
    model = LlamaForCausalLM(cfg)
    batch, seq = 64, cfg.max_seq_len  # 1 sequence per chip at 8192

    def loss_fn(state, params, b, rng):
        hidden = state.apply_fn(
            {"params": params}, b["input_ids"], return_hidden=True
        )
        return fused_lm_head_cross_entropy(
            hidden[:, :-1], params["lm_head"]["kernel"],
            b["input_ids"][:, 1:], z_loss=1e-4, mesh=mesh,
        ), {}

    step_fn = make_train_step(loss_fn, mesh, rules)
    example = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    abs_state = _abstract_sharded_state(
        model, optax.adamw(3e-4, weight_decay=0.1), mesh, rules, example
    )
    abs_batch = _abstract_batch(
        {"input_ids": ((batch, seq), "int32")}, mesh, rules
    )
    return _compile_and_report(
        "llama3-8b-fsdp-v5p128", step_fn, abs_state, abs_batch, mesh, rules
    )


def check_bert_base_v5p64():
    """Config #4: BERT-base MLM pretraining, TP over v5p-64 (32 chips)
    via programs/bert_train.py's model-divisibility-aware tp_layout
    (tensor=4: 12 heads cap the TP degree, vocab 30522 replicates the
    mlm head — the first aot run of this config caught the old blind
    pow2 split trying an impossible 8-way head shard), seq 512,
    masked-position fused-CE head — the production loss path of the
    BERT bench."""
    import jax.numpy as jnp
    import optax

    from k8s_tpu.models import BertConfig, BertForPretraining
    from k8s_tpu.ops.fused_ce import fused_lm_head_cross_entropy
    from k8s_tpu.programs.bert_train import tp_layout
    from k8s_tpu.train import make_train_step

    import dataclasses as _dc

    bcfg = BertConfig.base()
    tensor, data, rules = tp_layout(32, bcfg)
    mesh = _topology_mesh("v5p:4x4x2", dict(data=data, tensor=tensor))
    bcfg = _dc.replace(bcfg, mesh=mesh)
    model = BertForPretraining(bcfg)
    batch, seq = 512, bcfg.max_seq_len  # 16 sequences per chip
    n_pred = max(8, int(seq * 0.15 + 7) // 8 * 8)

    def loss_fn(state, params, b, rng):
        hidden, _ = state.apply_fn(
            {"params": params}, b["input_ids"], return_hidden=True
        )
        gathered = jnp.take_along_axis(
            hidden, b["masked_pos"][:, :, None], axis=1
        )
        return fused_lm_head_cross_entropy(
            gathered, params["mlm_head"]["kernel"], b["masked_labels"],
            mask=b["masked_w"], bias=params["mlm_head"]["bias"], mesh=mesh,
        ), {}

    step_fn = make_train_step(loss_fn, mesh, rules)
    example = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    abs_state = _abstract_sharded_state(
        model, optax.adamw(1e-4), mesh, rules, example
    )
    abs_batch = _abstract_batch(
        {
            "input_ids": ((batch, seq), "int32"),
            "masked_pos": ((batch, n_pred), "int32"),
            "masked_labels": ((batch, n_pred), "int32"),
            "masked_w": ((batch, n_pred), "int32"),
        },
        mesh, rules,
    )
    return _compile_and_report(
        "bert-base-tp-v5p64", step_fn, abs_state, abs_batch, mesh, rules
    )


def check_llama3_8b_pp_fsdp_v5p128():
    """Pipeline parallelism at the 8B scale (VERDICT r4 weak #5): the
    GPipe schedule (train/pipeline_llama.py) composed with manual
    ZeRO-3 FSDP, compiled by the real TPU compiler at production shape
    — 32 layers over stage=4 (8-layer slabs), fsdp=8 inside the slice,
    data=2 outermost (the DCN axis), seq 8192, 4 microbatches. The
    collective schedule must show the stage-hop ppermutes ALONGSIDE the
    FSDP gather/scatter — the same de-risk standard as configs #4/#5."""
    import jax.numpy as jnp
    import optax

    from k8s_tpu.models import LlamaConfig, LlamaForCausalLM
    from k8s_tpu.parallel import LogicalRules
    from k8s_tpu.train import make_pp_llama_loss, make_train_step

    mesh = _topology_mesh("v5p:4x4x4", dict(data=2, fsdp=8, stage=4))
    rules = LogicalRules(LogicalRules.PP_FSDP)
    cfg = LlamaConfig.llama3_8b(attention="flash", mesh=mesh)
    model = LlamaForCausalLM(cfg)
    batch, seq = 64, cfg.max_seq_len
    example = jax.ShapeDtypeStruct((batch, seq), jnp.int32)

    loss_fn, _ = make_pp_llama_loss(
        model, mesh, rules, jnp.zeros((batch, seq), jnp.int32),
        num_microbatches=4, z_loss=1e-4,
    )
    step_fn = make_train_step(loss_fn, mesh, rules)
    abs_state = _abstract_sharded_state(
        model, optax.adamw(3e-4, weight_decay=0.1), mesh, rules, example
    )
    abs_batch = _abstract_batch(
        {"input_ids": ((batch, seq), "int32")}, mesh, rules
    )
    return _compile_and_report(
        "llama3-8b-pp-fsdp-v5p128", step_fn, abs_state, abs_batch, mesh,
        rules,
    )


def _check_llama3_8b_decode(quant: str):
    """The 8B TP-sharded single-token decode step — the config
    ``llama_generate``/``programs.serving`` actually serve (VERDICT r4
    weak #6: decode evidence was 705M-only). tensor=8 over 8 virtual
    v5p chips (kv_heads=8 caps the TP degree, programs/llama_generate
    ``_tp_degree``), batch 8, 4k cache, layer loop UNROLLED (the
    measured-fast serving layout). Multi-device decode rides the XLA
    cached-attention path by design (the pallas decode kernel is
    single-device-gated, models/llama.py ``_use_pallas_decode``) — this
    compile is the proof that path lowers, fits HBM, and shows the
    expected TP collective schedule at 8B."""
    import dataclasses as _dc

    import jax.numpy as jnp

    from k8s_tpu.models import LlamaConfig, LlamaForCausalLM
    from k8s_tpu.parallel import LogicalRules
    from k8s_tpu.train.trainer_lib import shardings_from_logical

    import flax.linen as nn
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _topology_mesh("v5p:2x2x2", dict(tensor=8))
    rules = LogicalRules(LogicalRules.TP)
    batch, max_seq = 8, 4096
    cfg = LlamaConfig.llama3_8b(
        decode=True, remat=False, max_seq_len=max_seq, scan_layers=False,
    )
    if quant:
        cfg = _dc.replace(cfg, quant=quant)
    model = LlamaForCausalLM(cfg)
    tok = jnp.zeros((batch, 1), jnp.int32)

    def boxed_init():
        return model.init(
            jax.random.PRNGKey(0), tok,
            positions=jnp.zeros((batch, 1), jnp.int32),
        )

    with nn.logical_axis_rules(rules.to_flax()):
        shardings = nn.unbox(shardings_from_logical(boxed_init, mesh, rules))
    abstract = jax.eval_shape(lambda: nn.unbox(boxed_init()))
    param_shardings = shardings["params"]
    abs_params = jax.tree_util.tree_map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        abstract["params"], param_shardings,
    )

    # cache vars carry no logical metadata (plain self.variable):
    # shard by leaf name — kv-head axis over tensor, like the params
    def cache_spec(path, a):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("cached_key", "cached_value",
                    "key_scale", "value_scale"):
            spec = P(None, "tensor", None, None)
        elif name == "cache_index":  # scalar decode position
            spec = P()
        else:
            raise ValueError(f"unknown cache leaf {name!r}")
        return jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=NamedSharding(mesh, spec))

    abs_cache = jax.tree_util.tree_map_with_path(
        cache_spec, abstract["cache"])
    repl = NamedSharding(mesh, P())
    abs_tok = jax.ShapeDtypeStruct((batch, 1), jnp.int32, sharding=repl)
    abs_pos = jax.ShapeDtypeStruct((batch, 1), jnp.int32, sharding=repl)

    def decode_step(params, cache, tok, pos):
        logits, mut = model.apply(
            {"params": params, "cache": cache}, tok, positions=pos,
            mutable=["cache"],
        )
        return logits, mut["cache"]

    jitted = jax.jit(decode_step, donate_argnums=(1,))
    with nn.logical_axis_rules(rules.to_flax()):
        lowered = jitted.lower(abs_params, abs_cache, abs_tok, abs_pos)
    compiled = lowered.compile()
    suffix = f"-{quant}" if quant else "-bf16"
    return _report_compiled(f"llama3-8b-decode-tp8{suffix}", compiled, mesh)


def check_llama3_8b_longctx_v5p128():
    """Long-context at scale: Llama-3-8B at seq 32768 with ring
    attention over the ``seq`` mesh axis (context parallelism),
    composed with FSDP+TP — the headline long-context path
    (docs/BENCHMARKS.md long-context rows are single-chip) compiled by
    the real TPU compiler at the multi-slice topology. KV blocks and
    their segment rows rotate the seq ring via ppermute (ICI); the
    collective schedule must show those alongside the FSDP/TP sync."""
    import jax.numpy as jnp
    import optax

    from k8s_tpu.models import LlamaConfig, LlamaForCausalLM
    from k8s_tpu.ops.fused_ce import fused_lm_head_cross_entropy
    from k8s_tpu.parallel import LogicalRules
    from k8s_tpu.train import make_train_step

    mesh = _topology_mesh("v5p:4x4x4", dict(data=2, fsdp=8, tensor=2,
                                            seq=2))
    rules = LogicalRules(LogicalRules.FSDP_TP_SP)
    cfg = LlamaConfig.llama3_8b(attention="ring", mesh=mesh,
                                max_seq_len=32768)
    model = LlamaForCausalLM(cfg)
    batch, seq = 16, cfg.max_seq_len  # 1 row per data×fsdp shard at 32k

    def loss_fn(state, params, b, rng):
        hidden = state.apply_fn(
            {"params": params}, b["input_ids"], return_hidden=True
        )
        return fused_lm_head_cross_entropy(
            hidden[:, :-1], params["lm_head"]["kernel"],
            b["input_ids"][:, 1:], z_loss=1e-4, mesh=mesh,
        ), {}

    step_fn = make_train_step(loss_fn, mesh, rules)
    example = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    abs_state = _abstract_sharded_state(
        model, optax.adamw(3e-4, weight_decay=0.1), mesh, rules, example
    )
    abs_batch = _abstract_batch(
        {"input_ids": ((batch, seq), "int32")}, mesh, rules
    )
    return _compile_and_report(
        "llama3-8b-longctx-sp-v5p128", step_fn, abs_state, abs_batch,
        mesh, rules,
    )


def check_llama_moe_ep_v5p64():
    """Expert parallelism at scale — the last §2.5 parallelism row
    without at-scale compile evidence (MoE was measured single-chip
    only). A mid-size top-2 MoE Llama (hidden 2048 / 16 layers / 8
    experts) with experts sharded over ``expert=8``, composed with
    data=2 × fsdp=2, on 32 virtual v5p chips: proves the sort-based
    static-shape dispatch's expert all-to-all compiles and what it
    costs alongside the FSDP sync."""
    import jax.numpy as jnp
    import optax

    from k8s_tpu.models import LlamaConfig, LlamaForCausalLM
    from k8s_tpu.ops.fused_ce import fused_lm_head_cross_entropy
    from k8s_tpu.parallel import LogicalRules
    from k8s_tpu.train import make_train_step, sum_sown_losses

    mesh = _topology_mesh("v5p:4x4x2", dict(data=2, fsdp=2, expert=8))
    rules = LogicalRules(LogicalRules.MOE)
    cfg = LlamaConfig(
        vocab_size=32768, hidden_size=2048, intermediate_size=1024,
        num_layers=16, num_heads=16, num_kv_heads=8, head_dim=128,
        max_seq_len=4096, num_experts=8, attention="flash", mesh=mesh,
        remat=True,
    )
    model = LlamaForCausalLM(cfg)
    batch, seq = 16, cfg.max_seq_len  # 4 rows per data×fsdp shard

    def loss_fn(state, params, b, rng):
        hidden, mut = state.apply_fn(
            {"params": params}, b["input_ids"], return_hidden=True,
            mutable=["intermediates"],
        )
        ce = fused_lm_head_cross_entropy(
            hidden[:, :-1], params["lm_head"]["kernel"],
            b["input_ids"][:, 1:], z_loss=1e-4, mesh=mesh,
        )
        return ce + sum_sown_losses(mut.get("intermediates", {})), {}

    step_fn = make_train_step(loss_fn, mesh, rules)
    example = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    abs_state = _abstract_sharded_state(
        model, optax.adamw(3e-4, weight_decay=0.1), mesh, rules, example
    )
    abs_batch = _abstract_batch(
        {"input_ids": ((batch, seq), "int32")}, mesh, rules
    )
    return _compile_and_report(
        "llama-moe-ep-v5p64", step_fn, abs_state, abs_batch, mesh, rules
    )


def check_llama3_8b_decode_tp8_bf16():
    return _check_llama3_8b_decode("")


def check_llama3_8b_decode_tp8_int8():
    return _check_llama3_8b_decode("int8_serving")


CONFIGS = {
    "llama3-8b-v5p128": check_llama3_8b_v5p128,
    "bert-base-v5p64": check_bert_base_v5p64,
    "llama3-8b-pp-fsdp-v5p128": check_llama3_8b_pp_fsdp_v5p128,
    "llama3-8b-decode-tp8-bf16": check_llama3_8b_decode_tp8_bf16,
    "llama3-8b-decode-tp8-int8": check_llama3_8b_decode_tp8_int8,
    "llama3-8b-longctx-v5p128": check_llama3_8b_longctx_v5p128,
    "llama-moe-ep-v5p64": check_llama_moe_ep_v5p64,
}


def main(argv=None) -> int:
    # deviceless AOT needs a CPU default backend; the TPU work happens
    # inside the topology compile (libtpu), not on a device. Env vars
    # alone don't stick under backend-hooking shims — pin explicitly
    # before the first device query (the conftest/dryrun approach).
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # pragma: no cover - backend already initialized
        if jax.default_backend() != "cpu":
            print("warning: default backend is not cpu; AOT may "
                  "contend with the real device", file=sys.stderr)

    # the flash-attention gate must select the TPU kernel while the
    # host backend is CPU: lowering happens at trace time, inside the
    # check functions below. CLI-process-scoped on purpose — library
    # importers of this module are not affected.
    os.environ["KTPU_AOT_TPU"] = "1"

    ap = argparse.ArgumentParser("aot-check")
    ap.add_argument("--config", choices=sorted(CONFIGS), action="append")
    ap.add_argument("--all", action="store_true")
    ap.add_argument("--json", help="also write results to this path "
                    "(overwritten per run — stale verdicts must not "
                    "accumulate across CI runs)")
    ap.add_argument("--skip-if-unsupported", action="store_true",
                    help="exit 0 with a skip notice when the deviceless "
                         "TPU compiler (libtpu) is unavailable — for CI "
                         "hosts where that is an environment gap, not a "
                         "regression")
    ap.add_argument("--lint", action="store_true",
                    help="check each config's collective schedule against "
                         "its golden budget manifest (ci/hlo_budgets/); a "
                         "config with no checked-in golden is a notice, "
                         "not a failure")
    ap.add_argument("--write-budgets", action="store_true",
                    help="(re)write the golden budget manifests from this "
                         "run — the update procedure when a schedule "
                         "change is intentional (docs/PERF.md)")
    ap.add_argument("--budget-dir", default=None,
                    help="override the manifest directory "
                         "(default: ci/hlo_budgets)")
    args = ap.parse_args(argv)
    names = sorted(CONFIGS) if (args.all or not args.config) else args.config

    if args.skip_if_unsupported:
        try:
            from jax.experimental import topologies

            topologies.get_topology_desc("v5p:2x2x2", "tpu")
        except Exception as e:
            print(json.dumps({"skipped": True,
                              "reason": f"no deviceless TPU compiler: {e}"}))
            return 0

    from k8s_tpu.tools import hlo_lint as _hl

    budget_dir = args.budget_dir or _hl.DEFAULT_BUDGET_DIR
    ok = True
    results = []
    for name in names:
        res = CONFIGS[name]()
        results.append(res)
        print(json.dumps(res), flush=True)
        if not res["fits_hbm"]:
            ok = False
            print(f"FAIL: {name} exceeds HBM budget "
                  f"({res['peak_gib_per_device']} GiB)", file=sys.stderr)
        if args.write_budgets:
            path = _hl.save_budget(budget_dir, name, res["lint"])
            print(f"wrote budget manifest {path}", file=sys.stderr)
        elif args.lint:
            golden = _hl.load_budget(budget_dir, name)
            if golden is None:
                print(f"lint[{name}]: no golden manifest in {budget_dir} — "
                      f"run with --write-budgets to create one",
                      file=sys.stderr)
            else:
                violations, improvements = _hl.check_budget(
                    res["lint"], golden)
                for v in violations:
                    ok = False
                    print(f"BUDGET VIOLATION [{name}]: {v}", file=sys.stderr)
                for i in improvements:
                    print(f"budget improvement [{name}]: {i}",
                          file=sys.stderr)
    if args.json:
        with open(args.json, "w") as f:
            for res in results:
                f.write(json.dumps(res) + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
