"""JUnit XML writer.

Analogue of reference ``py/test_util.py`` (``TestCase`` +
``create_junit_xml_file``, :8-60): the CI artifact format Gubernator-
style dashboards consume.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import List, Optional
from xml.sax.saxutils import escape


@dataclass
class TestCase:
    class_name: str = ""
    name: str = ""
    time: float = 0.0
    failure: Optional[str] = None


def to_junit_xml(cases: List[TestCase]) -> str:
    failures = sum(1 for c in cases if c.failure)
    total_time = sum(c.time for c in cases)
    lines = [
        '<testsuite failures="{}" tests="{}" time="{}">'.format(
            failures, len(cases), total_time
        )
    ]
    for c in cases:
        attrs = 'classname="{}" name="{}" time="{}"'.format(
            escape(c.class_name, {'"': "&quot;"}),
            escape(c.name, {'"': "&quot;"}),
            c.time,
        )
        if c.failure:
            lines.append(f"  <testcase {attrs}>")
            lines.append(
                '    <failure message="{}"/>'.format(
                    escape(c.failure, {'"': "&quot;"})
                )
            )
            lines.append("  </testcase>")
        else:
            lines.append(f"  <testcase {attrs}/>")
    lines.append("</testsuite>")
    return "\n".join(lines)


def create_junit_xml_file(cases: List[TestCase], output_path: str) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(output_path)), exist_ok=True)
    with open(output_path, "w") as f:
        f.write(to_junit_xml(cases))


class Timer:
    def __enter__(self):
        self.start = time.monotonic()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.monotonic() - self.start
        return False
