"""Operational tooling: e2e binary, test runner, junit writer, local
kubectl, cleanup. Analogues of reference ``test/e2e/main.go``,
``py/test_runner.py``, ``py/test_util.py``, ``scripts/``.
"""
