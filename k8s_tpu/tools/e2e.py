"""E2E verification binary.

Analogue of reference ``test/e2e/main.go``: create a job with
coordinator + workers + TensorBoard (:49-102), poll to Succeeded with a
5-minute default budget (:37,111-123), assert every per-replica
resource exists (:139-151), assert the TensorBoard Deployment+Service
(:153-166), delete, poll for full GC (:168-223), parallel ``--num-jobs``
fan-out (:241-254), TAP output (:277-285).

Runs against the in-process LocalWorld (simulated pods by default;
``--subprocess`` runs the real SPMD launcher processes).
"""

from __future__ import annotations

import argparse
import sys
import threading
import time
from typing import List

from k8s_tpu.api.objects import Container, PodSpec, PodTemplateSpec
from k8s_tpu import spec as S
from k8s_tpu.tools.junit import TestCase, Timer, create_junit_xml_file
from k8s_tpu.tools.local_world import LocalWorld


def build_job(name: str, workers: int = 1) -> S.TpuJob:
    j = S.TpuJob()
    j.metadata.name = name
    j.metadata.namespace = "default"
    j.spec.replica_specs = [
        S.TpuReplicaSpec(
            replica_type="COORDINATOR",
            template=PodTemplateSpec(
                spec=PodSpec(containers=[Container(name="jax", image="img", command=["true"])])
            ),
        ),
        S.TpuReplicaSpec(replica_type="WORKER", replicas=workers),
    ]
    j.spec.tensorboard = S.TensorBoardSpec(log_dir="/tmp/tb")
    return j


def run_one(world: LocalWorld, name: str, timeout: float) -> None:
    job = world.api.create(build_job(name, workers=2))
    job = world.api.wait_for_job("default", name, timeout=timeout)
    if job.status.state != S.TpuJobState.SUCCEEDED:
        raise AssertionError(
            f"job {name} finished {job.status.state}: {job.status.reason}"
        )
    rid = job.spec.runtime_id
    expected_jobs = [
        f"{name}-coordinator-{rid}-0",
        f"{name}-worker-{rid}-0",
        f"{name}-worker-{rid}-1",
    ]
    have = {x.metadata.name for x in world.client.jobs.list("default")}
    for e in expected_jobs:
        if e not in have:
            raise AssertionError(f"expected Job {e} missing (have {sorted(have)})")
    world.client.deployments.get("default", f"{name}-tensorboard-{rid}")
    world.client.services.get("default", f"{name}-tensorboard-{rid}")

    world.api.delete("default", name)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leftover_jobs = [
            x for x in world.client.jobs.list("default")
            if x.metadata.name.startswith(f"{name}-")
        ]
        leftover_deps = [
            x for x in world.client.deployments.list("default")
            if x.metadata.name.startswith(f"{name}-")
        ]
        if not leftover_jobs and not leftover_deps:
            return
        time.sleep(0.05)
    raise AssertionError(f"resources of {name} not garbage-collected")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ktpu-e2e")
    p.add_argument("--num-jobs", type=int, default=1)
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--subprocess", action="store_true",
                   help="run real launcher subprocesses instead of simulated pods")
    p.add_argument("--junit-path", default="")
    args = p.parse_args(argv)

    cases: List[TestCase] = []
    ok = True
    with LocalWorld(subprocess_pods=args.subprocess, log_dir="/tmp/ktpu-e2e-logs") as world:
        errors: List[str] = [None] * args.num_jobs

        def worker(i: int):
            try:
                run_one(world, f"e2e-{i}", args.timeout)
            except Exception as e:  # noqa: BLE001 - report, don't crash
                errors[i] = str(e)

        with Timer() as t:
            threads = [
                threading.Thread(target=worker, args=(i,)) for i in range(args.num_jobs)
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
        for i, err in enumerate(errors):
            cases.append(
                TestCase("e2e", f"job-{i}", t.elapsed / args.num_jobs, err)
            )
            if err:
                ok = False

    if args.junit_path:
        create_junit_xml_file(cases, args.junit_path)
    # TAP output (reference main.go:277-285)
    print(f"1..{len(cases)}")
    for i, c in enumerate(cases, 1):
        if c.failure:
            print(f"not ok {i} - {c.name}: {c.failure}")
        else:
            print(f"ok {i} - {c.name}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
