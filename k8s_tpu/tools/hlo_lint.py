"""Compiled-HLO collective-budget linter.

The collective *schedule* of a sharded train step is the product: which
collectives run, over which mesh axes, in forward or backward, and how
many bytes they move. Before this tool that schedule was asserted
nowhere — a sharding regression (a constraint dropped, a rules-table
reorder) showed up only as XLA SPMD "Involuntary full
rematerialization" warning spew in the multichip dryrun log and a
quietly worse `llama_mfu` (MULTICHIP_r05 / BENCH_r05). This module
parses ``lower().compile()`` output into a structured report and checks
it against per-config golden budget manifests (``ci/hlo_budgets/``), so
CI fails the moment a new all-gather sneaks into the backward pass.

Three layers, separable on purpose:

- **Parsing** (pure, unit-tested against canned HLO text —
  ``tests/test_hlo_lint.py``): :func:`parse_collectives` extracts every
  collective op (sync + async ``-start`` forms, the TPU backend's fused
  ``%all-reduce-scatter`` kCustom representation reclassified), with
  per-op mesh-axis attribution from ``replica_groups`` /
  ``source_target_pairs`` and forward/backward classification from the
  ``op_name`` metadata. :func:`parse_involuntary_remat` structures the
  SPMD partitioner's fallback warnings (captured stderr — the warnings
  never appear in the HLO text itself).
- **Report/budget**: :func:`lint_report` aggregates ops into the budget
  shape; :func:`check_budget` diffs a report against a golden manifest
  and returns human-readable violations (exceeded counts, new axes, new
  kinds, involuntary-remat regressions).
- **Stand-in configs**: tiny sharded train steps compiled against the
  8-device virtual CPU mesh (the multichip-dryrun shapes — FSDP×TP×SP
  ring attention and PP×FSDP GPipe). They compile in seconds with no
  libtpu, so the CI ``hlo-budget`` stage enforces their goldens on
  every run; the full north-star configs get the same treatment through
  ``aot_check --lint`` when the deviceless TPU compiler is available.

CLI::

    python -m k8s_tpu.tools.hlo_lint --check            # lint stand-ins
    python -m k8s_tpu.tools.hlo_lint --check --write    # regenerate goldens

See docs/PERF.md for how to read a budget and the update procedure when
a schedule change is intentional.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys
from itertools import combinations
from typing import Dict, List, Optional, Sequence, Tuple

COLLECTIVE_KINDS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1, "s4": 1, "u4": 1,
}

_INVOLUNTARY_MARKER = "Involuntary full rematerialization"


@dataclasses.dataclass
class Collective:
    """One collective op from optimized HLO text."""

    kind: str           # one of COLLECTIVE_KINDS
    name: str           # HLO value name (without %)
    shape_bytes: int    # size of the op's largest array buffer
    axes: str           # attributed mesh axes ("fsdp", "data+fsdp", "all", "unknown")
    direction: str      # "fwd" | "bwd"
    is_async: bool      # -start form
    op_name: str        # metadata op_name ("" when absent)


# ---------------------------------------------------------------------------
# Replica-group parsing + mesh-axis attribution
# ---------------------------------------------------------------------------


def _parse_group_list(text: str) -> List[List[int]]:
    """``{{0,2},{1,3}}`` → [[0,2],[1,3]] (also source_target_pairs)."""
    return [
        [int(x) for x in grp.split(",") if x.strip() != ""]
        for grp in re.findall(r"\{([0-9, ]*)\}", text)
    ]


def _parse_iota_groups(text: str) -> Optional[List[List[int]]]:
    """HLO v2 iota replica-group list: ``[G,S]<=[d0,d1,...]`` with an
    optional ``T(perm)`` transpose — expand to explicit groups."""
    m = re.match(
        r"\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?", text.strip()
    )
    if not m:
        return None
    import numpy as np

    g, s = int(m.group(1)), int(m.group(2))
    dims = [int(x) for x in m.group(3).split(",")]
    ids = np.arange(int(np.prod(dims))).reshape(dims)
    if m.group(4):
        ids = ids.transpose([int(x) for x in m.group(4).split(",")])
    return ids.reshape(g, s).tolist()


def parse_replica_groups(text: str) -> List[List[int]]:
    """Either explicit ``{{...}}`` or iota ``[G,S]<=[...]`` form."""
    text = text.strip()
    if text.startswith("{"):
        return _parse_group_list(text)
    groups = _parse_iota_groups(text)
    return groups if groups is not None else []


def _canon(groups: Sequence[Sequence[int]]) -> frozenset:
    return frozenset(frozenset(g) for g in groups)


def axis_group_table(mesh_axes: Dict[str, int]) -> Dict[frozenset, str]:
    """Canonical replica-group sets for every combination of >1-sized
    mesh axes → axis label. Device ids are row-major over the full mesh
    shape — exactly how jit numbers the mesh's device assignment."""
    import numpy as np

    names = list(mesh_axes)
    sizes = [mesh_axes[n] for n in names]
    n_dev = int(np.prod(sizes))
    ids = np.arange(n_dev).reshape(sizes)
    real = [n for n in names if mesh_axes[n] > 1]
    table: Dict[frozenset, str] = {}
    for r in range(1, len(real) + 1):
        for combo in combinations(real, r):
            idx = [names.index(c) for c in combo]
            moved = np.moveaxis(ids, idx, range(ids.ndim - len(idx), ids.ndim))
            group_size = int(np.prod([mesh_axes[c] for c in combo]))
            groups = moved.reshape(-1, group_size)
            table.setdefault(_canon(groups.tolist()), "+".join(combo))
    return table


def attribute_axes(
    groups: List[List[int]], table: Dict[frozenset, str], n_devices: int
) -> str:
    """Mesh-axis label for a parsed replica-group set."""
    if not groups or all(len(g) <= 1 for g in groups):
        return "none"
    if len(groups) == 1 and len(groups[0]) == n_devices:
        # a single all-device group is also some axis combo's groups —
        # prefer the named label when the table has one
        return table.get(_canon(groups), "all")
    return table.get(_canon(groups), "unknown")


def attribute_permute(
    pairs: List[List[int]], mesh_axes: Dict[str, int]
) -> str:
    """collective-permute attribution: the axis along whose ring the
    source→target pairs move (each pair differs in exactly that mesh
    coordinate)."""
    import numpy as np

    names = list(mesh_axes)
    sizes = [mesh_axes[n] for n in names]
    if not pairs:
        return "none"
    coords = {}

    def coord(d):
        if d not in coords:
            coords[d] = np.unravel_index(d, sizes)
        return coords[d]

    hit: set = set()
    for p in pairs:
        if len(p) != 2:
            return "unknown"
        a, b = coord(p[0]), coord(p[1])
        diff = [i for i in range(len(sizes)) if a[i] != b[i]]
        if len(diff) != 1:
            return "unknown"
        hit.add(names[diff[0]])
    return "+".join(sorted(hit)) if len(hit) > 1 else next(iter(hit))


# ---------------------------------------------------------------------------
# HLO text parsing
# ---------------------------------------------------------------------------


def _bytes_of(type_str: str) -> int:
    """Largest array buffer in an HLO result type (tuples: the async
    destination dominates; scalars → 0-d = dtype size)."""
    best = 0
    for dt, dims in re.findall(r"([a-z0-9]+)\[([0-9,]*)\]", type_str):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d.strip():
                n *= int(d)
        best = max(best, n * _DTYPE_BYTES[dt])
    return best


_OP_LINE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*(?P<type>.*?)\s"
    r"(?P<kind>all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?P<start>-start)?\(",
    re.M,
)

# the TPU backend's fused reduce-scatter: kCustom fusions calling
# %all-reduce-scatter.* — or, depending on which pass created them,
# plain %reduce-scatter.* — computations whose BODY holds
# layout-constrained all-reduces (see aot_check.count_collectives —
# the round-4 misread). Both spellings reclassify identically, so a
# ZeRO-1 grad reduce-scatter over the DP axis lands in the per-axis
# breakdown no matter which fusion name the backend picked. The name
# must be followed by a parameter list `(`, which only computation
# DEFINITIONS have — a native `%reduce-scatter.N = ...` op line has
# `= ` there and stays an ordinary parsed collective.
_FUSED_RS_BODY = re.compile(
    r"^\s*%?(?:all-)?reduce-scatter[\w.\-]*\s*\(.*?\{(.*?)^\}", re.M | re.S
)
_FUSED_RS_CALL = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*(?P<type>.*?)\sfusion\("
    r".*calls=%?(?P<callee>(?:all-)?reduce-scatter[\w.\-]*)", re.M
)


def _direction(op_name: str) -> str:
    return "bwd" if "transpose(" in op_name else "fwd"


def parse_collectives(
    hlo: str, mesh_axes: Optional[Dict[str, int]] = None
) -> List[Collective]:
    """Every collective op in optimized HLO text, with axis attribution
    when ``mesh_axes`` (ordered name → size) is given."""
    table = axis_group_table(mesh_axes) if mesh_axes else {}
    n_devices = 1
    if mesh_axes:
        for s in mesh_axes.values():
            n_devices *= s

    # spans of fused reduce-scatter computation bodies: collectives
    # inside them are the REPRESENTATION of the fused op, not schedule
    body_spans = [m.span(1) for m in _FUSED_RS_BODY.finditer(hlo)]

    def in_body(pos: int) -> bool:
        return any(a <= pos < b for a, b in body_spans)

    out: List[Collective] = []
    for m in _OP_LINE.finditer(hlo):
        if in_body(m.start()):
            continue
        line_end = hlo.find("\n", m.start())
        line = hlo[m.start(): line_end if line_end != -1 else len(hlo)]
        kind = m.group("kind")
        opn = ""
        om = re.search(r'op_name="([^"]*)"', line)
        if om:
            opn = om.group(1)
        if kind == "collective-permute":
            # source_target_pairs={{0,1},{1,2}} — grab the outer braces
            pm = re.search(r"source_target_pairs=(\{\{.*?\}\})", line)
            axes = (
                attribute_permute(_parse_group_list(pm.group(1)), mesh_axes)
                if (pm and mesh_axes) else ("unknown" if mesh_axes else "none")
            )
        else:
            gm = re.search(r"replica_groups=(\{\{.*?\}\}|\{\}|\[[0-9,]+\]"
                           r"<=\[[0-9,]+\](?:T\([0-9,]+\))?)", line)
            if gm and mesh_axes:
                groups = parse_replica_groups(gm.group(1))
                axes = attribute_axes(groups, table, n_devices)
            else:
                axes = "unknown" if mesh_axes else "none"
        out.append(Collective(
            kind=kind,
            name=m.group("name"),
            shape_bytes=_bytes_of(m.group("type")),
            axes=axes,
            direction=_direction(opn),
            is_async=bool(m.group("start")),
            op_name=opn,
        ))

    for m in _FUSED_RS_CALL.finditer(hlo):
        opn = ""
        line_end = hlo.find("\n", m.start())
        line = hlo[m.start(): line_end if line_end != -1 else len(hlo)]
        om = re.search(r'op_name="([^"]*)"', line)
        if om:
            opn = om.group(1)
        # axis attribution comes from the inner all-reduce's groups
        axes = "unknown" if mesh_axes else "none"
        if mesh_axes:
            bm = re.search(
                r"^\s*%?" + re.escape(m.group("callee")) +
                r"\s*\(.*?\{(.*?)^\}", hlo, re.M | re.S)
            if bm:
                gm = re.search(r"replica_groups=(\{\{.*?\}\}|\{\}|\[[0-9,]+\]"
                               r"<=\[[0-9,]+\](?:T\([0-9,]+\))?)", bm.group(1))
                if gm:
                    axes = attribute_axes(
                        parse_replica_groups(gm.group(1)), table, n_devices)
        out.append(Collective(
            kind="reduce-scatter",
            name=m.group("name"),
            shape_bytes=_bytes_of(m.group("type")),
            axes=axes,
            direction=_direction(opn),
            is_async=False,
            op_name=opn,
        ))
    return out


# ---------------------------------------------------------------------------
# SPMD-warning parsing (involuntary resharding fallbacks)
# ---------------------------------------------------------------------------


def count_involuntary_remat(log_text: str) -> int:
    """Occurrences of the SPMD partitioner's replicate-then-partition
    fallback warning in captured compile stderr."""
    return log_text.count(_INVOLUNTARY_MARKER)


_REMAT_RE = re.compile(
    _INVOLUNTARY_MARKER +
    r".*?from sharding \{(?P<src>[^}]*)\}[^{]*?to \{(?P<dst>[^}]*)\}"
    r".*?HLO operation:?\s*%(?P<op>[\w.\-]+) = (?P<type>[a-z0-9]+\[[0-9,]*\])",
    re.S,
)


def parse_involuntary_remat(log_text: str) -> List[Dict[str, str]]:
    """Structured records of each involuntary-remat warning: the HLO op,
    its array type, and the source/target shardings GSPMD could not
    bridge. Both partitioner wordings (``was not able to go from`` /
    ``cannot go from``) parse."""
    out = []
    for chunk in log_text.split(_INVOLUNTARY_MARKER)[1:]:
        m = _REMAT_RE.match(_INVOLUNTARY_MARKER + chunk)
        if m:
            out.append({
                "op": m.group("op"),
                "type": m.group("type"),
                "from": "{" + m.group("src") + "}",
                "to": "{" + m.group("dst") + "}",
            })
        else:
            out.append({"op": "unparsed", "type": "", "from": "", "to": ""})
    return out


class capture_stderr:
    """fd-level stderr tee: XLA's C++ SPMD warnings bypass Python's
    ``sys.stderr``, so counting them needs the real fd 2 swapped for
    the duration. Captured bytes are re-emitted to the original stderr
    on exit — nothing is swallowed, the machine-parsed stdout line just
    stays clean of them. Usage::

        with capture_stderr() as cap:
            compiled = lowered.compile()
        n = count_involuntary_remat(cap.text)
    """

    def __enter__(self):
        import tempfile

        self.text = ""
        try:
            sys.stderr.flush()
        except Exception:
            pass
        self._tmp = tempfile.TemporaryFile()
        self._saved = os.dup(2)
        os.dup2(self._tmp.fileno(), 2)
        return self

    def __exit__(self, *exc):
        try:
            sys.stderr.flush()
        except Exception:
            pass
        os.dup2(self._saved, 2)
        os.close(self._saved)
        try:
            self._tmp.seek(0)
            self.text = self._tmp.read().decode("utf-8", "replace")
        finally:
            self._tmp.close()
        if self.text:
            try:
                sys.stderr.write(self.text)
                sys.stderr.flush()
            except Exception:
                pass
        return False


# ---------------------------------------------------------------------------
# Report + budget check
# ---------------------------------------------------------------------------


def _bump(d: Dict[str, int], k: str, n: int = 1):
    d[k] = d.get(k, 0) + n


def lint_report(
    hlo: str,
    mesh_axes: Optional[Dict[str, int]] = None,
    spmd_log: str = "",
) -> dict:
    """Aggregate a compiled program's collective schedule into the
    budget shape. ``spmd_log`` is captured compile stderr (see
    :class:`capture_stderr`) — the involuntary-remat warnings live
    there, never in the HLO text."""
    ops = parse_collectives(hlo, mesh_axes)
    collectives: Dict[str, int] = {}
    fwd: Dict[str, int] = {}
    bwd: Dict[str, int] = {}
    by_axis: Dict[str, Dict[str, int]] = {}
    bwd_by_axis: Dict[str, Dict[str, int]] = {}
    bytes_by_kind: Dict[str, int] = {}
    n_async = 0
    for op in ops:
        _bump(collectives, op.kind)
        _bump(fwd if op.direction == "fwd" else bwd, op.kind)
        _bump(by_axis.setdefault(op.axes, {}), op.kind)
        if op.direction == "bwd":
            _bump(bwd_by_axis.setdefault(op.axes, {}), op.kind)
        _bump(bytes_by_kind, op.kind, op.shape_bytes)
        n_async += int(op.is_async)
    total = sum(collectives.values())
    remats = parse_involuntary_remat(spmd_log)
    return {
        "collectives": collectives,
        "forward": fwd,
        "backward": bwd,
        "by_axis": by_axis,
        "backward_by_axis": bwd_by_axis,
        "bytes_by_kind": bytes_by_kind,
        "total_collective_bytes": sum(bytes_by_kind.values()),
        "async_fraction": round(n_async / total, 3) if total else None,
        "involuntary_remat": count_involuntary_remat(spmd_log),
        "remat_fallbacks": remats[:8],
    }


_BUDGET_KEYS = ("collectives", "backward", "by_axis", "backward_by_axis")


def budget_from_report(report: dict, config: str) -> dict:
    """The golden manifest written by ``--write``: exact collective
    counts (XLA is deterministic for a fixed version) + a 25%-headroom
    bytes ceiling (layout/version drift moves bytes a little without a
    schedule change) + the zero-involuntary-remat assertion."""
    return {
        "config": config,
        "budget": {
            **{k: report[k] for k in _BUDGET_KEYS},
            "involuntary_remat": report["involuntary_remat"],
            "max_collective_bytes": int(report["total_collective_bytes"] * 1.25),
        },
    }


def _diff_counts(
    got: Dict[str, int], want: Dict[str, int], label: str,
    violations: List[str], improvements: List[str],
):
    for k in sorted(set(got) | set(want)):
        g, w = got.get(k, 0), want.get(k, 0)
        if g > w:
            violations.append(
                f"{label} {k}: {g} > budget {w} (+{g - w})"
            )
        elif g < w:
            improvements.append(
                f"{label} {k}: {g} < budget {w} (tighten the golden)"
            )


def check_budget(report: dict, golden: dict, strict: bool = False
                 ) -> Tuple[List[str], List[str]]:
    """Diff a lint report against a golden manifest.

    Returns ``(violations, improvements)``. A non-empty violations list
    fails the budget: counts above golden anywhere (total, backward,
    per-axis), involuntary-remat regressions, or bytes above the
    ceiling. Counts BELOW golden are improvements — reported so the
    golden gets tightened, fatal only under ``strict``."""
    budget = golden.get("budget", golden)
    violations: List[str] = []
    improvements: List[str] = []
    _diff_counts(report.get("collectives", {}), budget.get("collectives", {}),
                 "total", violations, improvements)
    _diff_counts(report.get("backward", {}), budget.get("backward", {}),
                 "backward", violations, improvements)
    for scope in ("by_axis", "backward_by_axis"):
        got_ax = report.get(scope, {})
        want_ax = budget.get(scope, {})
        for ax in sorted(set(got_ax) | set(want_ax)):
            _diff_counts(got_ax.get(ax, {}), want_ax.get(ax, {}),
                         f"{scope}[{ax}]", violations, improvements)
    got_remat = report.get("involuntary_remat", 0)
    want_remat = budget.get("involuntary_remat", 0)
    if got_remat > want_remat:
        detail = "; ".join(
            f"{r['op']} {r['type']} {r['from']}->{r['to']}"
            for r in report.get("remat_fallbacks", [])[:3]
        )
        violations.append(
            f"involuntary_remat: {got_remat} > budget {want_remat}"
            + (f" [{detail}]" if detail else "")
        )
    max_bytes = budget.get("max_collective_bytes")
    got_bytes = report.get("total_collective_bytes", 0)
    if max_bytes is not None and got_bytes > max_bytes:
        violations.append(
            f"total_collective_bytes: {got_bytes} > ceiling {max_bytes}"
        )
    if strict:
        violations.extend(improvements)
        improvements = []
    return violations, improvements


def budget_path(budget_dir: str, config: str) -> str:
    return os.path.join(budget_dir, f"{config}.json")


def load_budget(budget_dir: str, config: str) -> Optional[dict]:
    path = budget_path(budget_dir, config)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def save_budget(budget_dir: str, config: str, report: dict) -> str:
    os.makedirs(budget_dir, exist_ok=True)
    path = budget_path(budget_dir, config)
    with open(path, "w") as f:
        json.dump(budget_from_report(report, config), f, indent=1,
                  sort_keys=True)
        f.write("\n")
    return path


DEFAULT_BUDGET_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "ci", "hlo_budgets",
)


# ---------------------------------------------------------------------------
# Stand-in configs: tiny sharded steps on the 8-device virtual CPU mesh
# ---------------------------------------------------------------------------


def _standin_compile(strategy: str):
    """Compile the multichip-dryrun train step for ``strategy`` on 8
    virtual CPU devices; returns (compiled, mesh, spmd_log)."""
    import jax
    import jax.numpy as jnp
    import optax

    from k8s_tpu.models import LlamaConfig, LlamaForCausalLM
    from k8s_tpu.ops.fused_ce import fused_lm_head_cross_entropy
    from k8s_tpu.parallel import LogicalRules, MeshConfig, build_mesh
    from k8s_tpu.train import create_sharded_state, make_train_step

    devices = jax.devices()[:8]
    zero_stage = 0
    if strategy.startswith("zero"):
        zero_stage = int(strategy[4])
    accum_steps = 1
    state_kwargs: dict = {}
    if strategy == "fsdp-tp-sp":
        mesh = build_mesh(MeshConfig(data=-1, fsdp=2, seq=2, tensor=2),
                          devices=devices)
        rules = LogicalRules(LogicalRules.FSDP_TP_SP)
        cfg = LlamaConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=32,
                               attention="ring", mesh=mesh)
    elif strategy == "pp-fsdp":
        mesh = build_mesh(MeshConfig(data=-1, fsdp=2, stage=2),
                          devices=devices)
        rules = LogicalRules(LogicalRules.PP_FSDP)
        cfg = LlamaConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=32,
                               num_layers=2, attention="flash")
    elif strategy == "zero1-dp":
        # the ZeRO-1 signature on a pure-DP mesh: grad sync over `data`
        # + per-leaf all-gathers of the updated params, NOTHING in the
        # backward beyond the sync (a backward all-gather here = the
        # sharded update leaked into the grad computation)
        mesh = build_mesh(MeshConfig(data=8), devices=devices)
        rules = LogicalRules(LogicalRules.DP)
        cfg = LlamaConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=32,
                               attention="flash")
    elif strategy == "zero1-fsdp":
        # ZeRO-1 composed with FSDP: params/grads keep their fsdp dims,
        # the weight update additionally shards over `data`
        mesh = build_mesh(MeshConfig(data=2, fsdp=4), devices=devices)
        rules = LogicalRules(LogicalRules.FSDP)
        cfg = LlamaConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=32,
                               attention="flash", mesh=mesh)
    elif strategy == "zero2-dp":
        # ZeRO-2 under gradient accumulation (the stage's whole point):
        # the f32 accum carry is BORN in the 1/DP layout (the seed pins
        # before the f32 cast) and the per-microbatch sync feeds the
        # sharded accumulator inside the scan — the budget pins the
        # accum-schedule collective counts so a replicated accumulator
        # (an extra gather/slice pair at the optimizer boundary) or a
        # backward all-gather fails CI
        mesh = build_mesh(MeshConfig(data=8), devices=devices)
        rules = LogicalRules(LogicalRules.DP)
        cfg = LlamaConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=32,
                               attention="flash")
        accum_steps = 2
    elif strategy == "zero3-dp":
        # selective ZeRO-3: embedding + lm_head params live 1/DP — the
        # budget pins EXACTLY one forward all-gather per sharded leaf
        # (the just-in-time gather at first use; the epilogue gathers
        # for those leaves disappear) and zero backward all-gathers (a
        # backward gather = the remat'd forward re-gathering the leaf)
        mesh = build_mesh(MeshConfig(data=8), devices=devices)
        rules = LogicalRules(LogicalRules.DP)
        cfg = LlamaConfig.tiny(num_heads=4, num_kv_heads=2, head_dim=32,
                               attention="flash")
        state_kwargs = {"zero3_leaves": ["embedding", "lm_head"]}
    else:
        raise ValueError(f"unknown stand-in strategy {strategy!r}")

    model = LlamaForCausalLM(cfg)
    batch, seq = 8, 64
    example = jnp.zeros((batch, seq), jnp.int32)
    state = create_sharded_state(
        model, optax.adamw(1e-3), mesh, rules, jax.random.PRNGKey(0), example,
        zero_stage=zero_stage, **state_kwargs,
    )

    if strategy == "pp-fsdp":
        from k8s_tpu.train import make_pp_llama_loss

        loss_fn, _ = make_pp_llama_loss(
            model, mesh, rules, example, num_microbatches=2,
        )
    else:
        def loss_fn(st, params, b, rng):
            hidden = st.apply_fn(
                {"params": params}, b["input_ids"], return_hidden=True
            )
            return fused_lm_head_cross_entropy(
                hidden[:, :-1], params["lm_head"]["kernel"],
                b["input_ids"][:, 1:], target_chunk=cfg.vocab_size // 4,
                mesh=mesh,
            ), {}

    step = make_train_step(loss_fn, mesh, rules, zero_stage=zero_stage,
                           accum_steps=accum_steps)
    import flax.linen as nn

    from k8s_tpu.train import make_batch_sharder

    # place the batch exactly as run() does in production: an
    # UNCOMMITTED example leaves jit free to re-choose the batch layout
    # around the step's sharding constraints — under zero1 GSPMD then
    # partitioned the whole forward over the weight-update shardings
    # (embed-dim activations, ring permutes in attention) instead of
    # the data-parallel batch, a program no training run ever executes
    batch = make_batch_sharder(mesh, rules)({"input_ids": example})
    with nn.logical_axis_rules(rules.to_flax()):
        lowered = step.jitted.lower(state, batch, jax.random.PRNGKey(2))
        with capture_stderr() as cap:
            compiled = lowered.compile()
    return compiled, mesh, cap.text


STANDIN_CONFIGS = {
    "standin-fsdp-tp-sp-cpu8": lambda: _standin_compile("fsdp-tp-sp"),
    "standin-pp-fsdp-cpu8": lambda: _standin_compile("pp-fsdp"),
    # ZeRO-1 sharded weight update (ISSUE 6): the budgets pin the
    # sharded-update schedule — per-leaf param all-gathers AFTER the
    # optimizer, zero backward all-gathers. NB the CPU pipeline has no
    # reduce-scatter creator pass, so the grad sync renders as
    # all-reduce + partition slice here; the fused/native
    # %reduce-scatter forms appear on TPU backends and are attributed
    # to the DP axis by the parser (aot_check --lint covers those).
    "standin-zero1-dp-cpu8": lambda: _standin_compile("zero1-dp"),
    "standin-zero1-fsdp-cpu8": lambda: _standin_compile("zero1-fsdp"),
    # ZeRO-2/3 (ISSUE 17): stage 2 pins the accum_steps=2 schedule —
    # the f32 carry sharded 1/DP from birth; stage 3 pins the
    # just-in-time forward gathers of the selectively sharded
    # embedding/lm_head leaves + zero backward all-gathers
    "standin-zero2-dp-cpu8": lambda: _standin_compile("zero2-dp"),
    "standin-zero3-dp-cpu8": lambda: _standin_compile("zero3-dp"),
}


def lint_compiled(compiled, mesh, spmd_log: str = "") -> dict:
    """Lint a jax compiled object against its mesh."""
    mesh_axes = {k: int(v) for k, v in mesh.shape.items()}
    return lint_report(compiled.as_text(), mesh_axes, spmd_log)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser("hlo-lint")
    ap.add_argument("--check", action="store_true",
                    help="compile the stand-in configs and check their "
                         "golden budgets")
    ap.add_argument("--config", action="append",
                    choices=sorted(STANDIN_CONFIGS),
                    help="subset of stand-ins (default: all)")
    ap.add_argument("--write", action="store_true",
                    help="(re)write the golden manifests from this run "
                         "instead of checking")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on counts BELOW budget (stale golden)")
    ap.add_argument("--budget-dir", default=DEFAULT_BUDGET_DIR)
    args = ap.parse_args(argv)

    if not (args.check or args.write):
        ap.error("nothing to do: pass --check and/or --write")

    # virtual CPU mesh before the first device query (the conftest /
    # dryrun approach — env vars alone are too late under shims that
    # import jax at interpreter startup)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

    names = args.config or sorted(STANDIN_CONFIGS)
    ok = True
    for name in names:
        compiled, mesh, spmd_log = STANDIN_CONFIGS[name]()
        report = lint_compiled(compiled, mesh, spmd_log)
        if args.write:
            path = save_budget(args.budget_dir, name, report)
            print(json.dumps({"config": name, "wrote": path,
                              "collectives": report["collectives"],
                              "involuntary_remat": report["involuntary_remat"]}))
            continue
        golden = load_budget(args.budget_dir, name)
        if golden is None:
            ok = False
            print(json.dumps({
                "config": name, "budget": "MISSING",
                "hint": f"run: python -m k8s_tpu.tools.hlo_lint --write "
                        f"--config {name}",
                "collectives": report["collectives"],
            }))
            continue
        violations, improvements = check_budget(report, golden,
                                                strict=args.strict)
        print(json.dumps({
            "config": name,
            "budget": "FAIL" if violations else "ok",
            "collectives": report["collectives"],
            "backward": report["backward"],
            "involuntary_remat": report["involuntary_remat"],
            "violations": violations,
            "improvements": improvements,
        }))
        if violations:
            ok = False
            for v in violations:
                print(f"BUDGET VIOLATION [{name}]: {v}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
