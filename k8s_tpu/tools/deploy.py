"""Ephemeral-cluster deploy tool for CI and operators.

Analogue of reference ``py/deploy.py`` (setup/test/teardown subcommands,
:22-124): create a GKE cluster, install the operator chart, run
``helm test``, tear everything down, recording junit either way.

TPU-first differences: instead of an alpha-GPU ``accelerators=`` flag
on the cluster request (reference ``py/deploy.py:51-61``), ``setup``
creates a dedicated **TPU node pool** sized from the accelerator
topology — GKE TPU slices are all-or-nothing gangs, so the node pool's
``--num-nodes`` must equal the slice's host count and every node gets
the same ``--tpu-topology``. The machine type is derived from the
accelerator family and chips-per-host (``ct5lp-hightpu-8t`` etc.), not
hand-picked.

All gcloud/helm interaction is assembled as argv lists by pure
``*_commands`` functions (unit-testable, ``--dry-run`` prints them),
then executed by :func:`k8s_tpu.tools.release.run`.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
import time
from typing import List, Optional

from k8s_tpu.spec.topology import TpuTopology, parse as parse_topology
from k8s_tpu.tools.junit import TestCase, create_junit_xml_file
from k8s_tpu.tools.release import run

RELEASE_NAME = "tpu-job"


def machine_type(topo: TpuTopology) -> str:
    return topo.gke_machine_type


def cluster_create_commands(args) -> List[List[str]]:
    """CPU system pool + (optional) one TPU node pool per accelerator."""
    cmds = [[
        "gcloud", "container", "clusters", "create", args.cluster,
        "--project", args.project,
        "--zone", args.zone,
        "--num-nodes", str(args.system_nodes),
        "--machine-type", args.system_machine_type,
        "--release-channel", "rapid",
        "--scopes", "cloud-platform",
    ]]
    for accelerator in args.accelerators or []:
        topo = parse_topology(accelerator)
        cmds.append([
            "gcloud", "container", "node-pools", "create",
            f"tpu-{topo.accelerator}",
            "--project", args.project,
            "--zone", args.zone,
            "--cluster", args.cluster,
            "--machine-type", machine_type(topo),
            "--tpu-topology", topo.topology_label,
            # gang: one node per slice host, no autoscaling
            "--num-nodes", str(topo.num_hosts),
            "--node-labels", f"ktpu/accelerator={topo.accelerator}",
        ])
    cmds.append([
        "gcloud", "container", "clusters", "get-credentials", args.cluster,
        "--project", args.project,
        "--zone", args.zone,
    ])
    return cmds


def helm_install_commands(args) -> List[List[str]]:
    cmd = [
        "helm", "install", RELEASE_NAME, args.chart,
        "--wait",
        "--set", "rbac.install=true,cloud=gke",
    ]
    if args.image:
        cmd += ["--set", f"image={args.image}"]
    return [cmd]


def helm_test_commands(args) -> List[List[str]]:
    return [["helm", "test", RELEASE_NAME, "--timeout", f"{int(args.timeout)}s"]]


def teardown_commands(args) -> List[List[str]]:
    return [[
        "gcloud", "container", "clusters", "delete", args.cluster,
        "--project", args.project,
        "--zone", args.zone,
        "--quiet",
    ]]


def _run_stage(name: str, cmds: List[List[str]], cases: List[TestCase],
               dry_run: bool) -> bool:
    """Run a command list, appending one junit case for the stage
    (reference deploy.py records helm-install / e2e-test cases)."""
    failure = None
    start = time.time()
    try:
        for cmd in cmds:
            run(cmd, dry_run=dry_run)
    except subprocess.CalledProcessError as e:
        failure = f"{name} failed:\n{e.stderr or e.stdout or e}"
    except OSError as e:  # binary not on PATH, etc.
        failure = f"{name} failed to exec {cmd[0]!r}: {e}"
    cases.append(TestCase("deploy", name, time.time() - start, failure))
    if failure:
        print(failure, file=sys.stderr)
    return failure is None


def _finish(cases: List[TestCase], args, ok: bool) -> int:
    if args.junit_path:
        create_junit_xml_file(cases, args.junit_path)
    return 0 if ok else 1


def setup(args) -> int:
    cases: List[TestCase] = []
    try:
        create_cmds = cluster_create_commands(args)
    except ValueError as e:  # unknown accelerator → recorded, not raised
        cases.append(TestCase("deploy", "cluster-create", 0.0, str(e)))
        print(e, file=sys.stderr)
        return _finish(cases, args, ok=False)
    ok = _run_stage("cluster-create", create_cmds, cases, args.dry_run)
    if ok:
        ok = _run_stage(
            "helm-tpujob-install", helm_install_commands(args), cases,
            args.dry_run,
        )
    return _finish(cases, args, ok)


def test(args) -> int:
    cases: List[TestCase] = []
    ok = _run_stage("e2e-helm-test", helm_test_commands(args), cases, args.dry_run)
    return _finish(cases, args, ok)


def teardown(args) -> int:
    cases: List[TestCase] = []
    ok = _run_stage("cluster-delete", teardown_commands(args), cases, args.dry_run)
    return _finish(cases, args, ok)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ktpu-deploy", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    def common(sp):
        sp.add_argument("--project", required=True)
        sp.add_argument("--zone", default="us-east5-a")
        sp.add_argument("--cluster", default="ktpu-e2e")
        sp.add_argument("--junit-path", default=None)
        sp.add_argument("--dry-run", action="store_true")

    sp = sub.add_parser("setup", help="create cluster + install chart")
    common(sp)
    sp.add_argument("--chart", default="./chart")
    sp.add_argument("--image", default=None, help="operator image override")
    sp.add_argument("--system-nodes", type=int, default=1)
    sp.add_argument("--system-machine-type", default="e2-standard-8")
    sp.add_argument(
        "--accelerators", action="append", default=None, metavar="TYPE",
        help="TPU slice type to add a node pool for (e.g. v5e-8); repeatable",
    )
    sp.set_defaults(func=setup)

    sp = sub.add_parser("test", help="helm test the installed release")
    common(sp)
    sp.add_argument("--timeout", type=float, default=600.0)
    sp.set_defaults(func=test)

    sp = sub.add_parser("teardown", help="delete the cluster")
    common(sp)
    sp.set_defaults(func=teardown)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
