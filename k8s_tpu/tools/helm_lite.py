"""Minimal helm-template renderer for the bundled charts.

The reference ships its example job as a helm chart
(``/root/reference/examples/tf_job/`` — ``Chart.yaml`` + ``values.yaml``
+ ``templates/tf_job.yaml``) so users template image/replicas per
environment. This repo's CI hosts have no ``helm`` binary, so this
module renders the SUBSET of Go-template syntax those charts use —
enough for ``render() | kubectl_local validate`` to gate every bundled
chart in CI, and for users without helm to stamp out manifests:

- ``{{ .Values.<dotted.path> }}`` — values.yaml lookups (overridable)
- ``{{ .Release.Name }}``, ``{{ .Chart.Name }}``, ``{{ .Chart.Version }}``
- ``{{ <ref> | default <literal> }}`` — the one pipeline the reference's
  ``_helpers.tpl`` relies on

Anything else (conditionals, loops, includes) raises loudly rather than
rendering garbage — real helm remains the production path; this is the
validation/bootstrap path.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

import yaml

_TAG = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}")
_NUMBER = re.compile(r"^-?\d+(\.\d+)?$")


def _lookup(root: Dict, dotted: str):
    cur = root
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            raise KeyError(dotted)
        cur = cur[part]
    return cur


def _eval_expr(expr: str, ctx: Dict) -> str:
    """One ``{{ ... }}`` body: a reference, optionally piped through
    ``default``/``quote``."""
    parts = [p.strip() for p in expr.split("|")]
    head = parts[0]
    if head.startswith("."):
        try:
            val = _lookup(ctx, head[1:])
        except KeyError:
            val = None
    elif head.startswith('"') and head.endswith('"'):
        val = head[1:-1]
    else:
        raise ValueError(f"unsupported template expression: {expr!r}")
    for pipe in parts[1:]:
        if pipe.startswith("default "):
            arg = pipe[len("default "):].strip()
            if val in (None, ""):
                if arg.startswith('"') and arg.endswith('"'):
                    val = arg[1:-1]
                elif arg in ("true", "false") or _NUMBER.match(arg):
                    # bare literals render verbatim, like real helm
                    # (`default 3`, `default true`)
                    val = arg
                else:
                    val = _eval_expr(arg, ctx)
        elif pipe == "quote":
            # escape embedded quotes/backslashes like real helm — an
            # unescaped inner quote would render invalid YAML silently,
            # against this module's raise-loudly-or-render-faithfully
            # contract
            escaped = str(val).replace("\\", "\\\\").replace('"', '\\"')
            val = f'"{escaped}"'
        else:
            raise ValueError(f"unsupported template pipe: {pipe!r}")
    if val is None:
        raise KeyError(f"unresolved template reference: {expr!r}")
    return str(val)


def render_chart(
    chart_dir: str,
    release_name: str = "release",
    values: Optional[Dict] = None,
) -> Dict[str, str]:
    """Render every ``templates/*.yaml`` of a chart. ``values`` deep-
    overrides ``values.yaml`` (the ``--set``/-f analogue). Returns
    {template filename: rendered manifest text}."""
    with open(os.path.join(chart_dir, "Chart.yaml")) as f:
        chart_meta = yaml.safe_load(f)
    vals_path = os.path.join(chart_dir, "values.yaml")
    base_vals: Dict = {}
    if os.path.exists(vals_path):
        with open(vals_path) as f:
            base_vals = yaml.safe_load(f) or {}

    def deep_merge(dst, src):
        for k, v in (src or {}).items():
            if isinstance(v, dict) and isinstance(dst.get(k), dict):
                deep_merge(dst[k], v)
            else:
                dst[k] = v
        return dst

    ctx = {
        "Values": deep_merge(dict(base_vals), values or {}),
        "Release": {"Name": release_name},
        "Chart": {"Name": chart_meta.get("name", ""),
                  "Version": str(chart_meta.get("version", ""))},
    }
    out: Dict[str, str] = {}
    tdir = os.path.join(chart_dir, "templates")
    for fname in sorted(os.listdir(tdir)):
        if not (fname.endswith(".yaml") or fname.endswith(".yml")):
            continue  # _helpers.tpl etc. — defines only, nothing rendered
        with open(os.path.join(tdir, fname)) as f:
            text = f.read()

        def sub(m: "re.Match") -> str:
            if m.group(1) or m.group(3):
                # real helm's {{- -}} eats adjacent whitespace; silently
                # rendering without the trim would diverge from helm's
                # output — raise-loudly is this module's contract
                raise ValueError(
                    f"unsupported trim marker in {fname}: {m.group(0)!r} "
                    "({{- -}} whitespace trimming is not implemented; "
                    "use real helm for charts that need it)"
                )
            return _eval_expr(m.group(2), ctx)

        out[fname] = _TAG.sub(sub, text)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        "helm-lite", description="render a bundled chart (value "
        "substitution only; use real helm for production)")
    ap.add_argument("chart_dir")
    ap.add_argument("--release", default="release")
    ap.add_argument("--set", action="append", default=[],
                    metavar="path.key=value")
    args = ap.parse_args(argv)
    overrides: Dict = {}
    for kv in args.set:
        path, _, val = kv.partition("=")
        cur = overrides
        keys = path.split(".")
        for k in keys[:-1]:
            cur = cur.setdefault(k, {})
        cur[keys[-1]] = val
    for fname, text in render_chart(
            args.chart_dir, args.release, overrides).items():
        sys.stdout.write(f"---\n# Source: {fname}\n{text}\n")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
