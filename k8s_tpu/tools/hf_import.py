"""HuggingFace → k8s_tpu Llama checkpoint conversion.

A user of this framework should be able to bring real pretrained
weights: this maps a ``transformers`` Llama ``state_dict`` onto the
``LlamaForCausalLM`` params tree (scan-stacked layers, [in, out]
kernels, GQA head splits). Verified by logit equivalence against the
torch model in ``tests/test_tools.py``.

Conventions bridged:
- torch ``nn.Linear.weight`` is ``[out, in]`` → flax kernels are
  ``[in, out]`` (plus head reshapes for q/k/v/o);
- per-layer HF modules → one leading ``layers`` axis (the ``nn.scan``
  stack), stacked in layer order;
- rotary embedding: both use the rotate-half (GPT-NeoX) convention, so
  q/k weights transfer with no permutation.

Usage::

    from transformers import LlamaForCausalLM as HfLlama
    hf = HfLlama.from_pretrained("meta-llama/Meta-Llama-3-8B")
    params = convert_hf_llama(hf.state_dict(), lcfg)
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import jax.numpy as jnp
import numpy as np


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


def _take(sd: Mapping[str, Any], name: str, shape) -> np.ndarray:
    """Fetch + shape-check one weight (shared by all converters)."""
    w = _np(sd[name])
    if tuple(w.shape) != tuple(shape):
        raise ValueError(
            f"{name}: HF shape {tuple(w.shape)} != expected {shape}"
        )
    return w


def convert_hf_llama(state_dict: Mapping[str, Any], cfg) -> Dict[str, Any]:
    """Convert a HF Llama ``state_dict`` to a ``LlamaForCausalLM``
    params tree for ``cfg`` (``LlamaConfig``). Requires
    ``cfg.scan_layers=True`` layout (the default). Raises KeyError on
    missing weights and ValueError on shape mismatches."""
    if not cfg.scan_layers:
        raise ValueError(
            "convert_hf_llama targets the scan-stacked layout; set "
            "LlamaConfig(scan_layers=True) (the default)"
        )
    e, h, kv, d = (
        cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
    )
    L = cfg.num_layers

    sd = dict(state_dict)

    def take(name, shape):
        return _take(sd, name, shape)

    def stack(fmt, convert):
        return jnp.asarray(
            np.stack([convert(fmt.format(i)) for i in range(L)])
        )

    def linear(name, out_features):  # [out, in] -> [in, out]
        return take(name, (out_features, e)).T

    def heads_proj(name, n_heads):  # [n*d, E] -> [E, n, d]
        return take(name, (n_heads * d, e)).T.reshape(e, n_heads, d)

    def o_proj(name):  # [E, H*d] -> [H, d, E]
        return take(name, (e, h * d)).T.reshape(h, d, e)

    p = "model.layers.{}."
    block = {
        "attn": {
            "q_proj": {"kernel": stack(
                p + "self_attn.q_proj.weight", lambda n: heads_proj(n, h))},
            "k_proj": {"kernel": stack(
                p + "self_attn.k_proj.weight", lambda n: heads_proj(n, kv))},
            "v_proj": {"kernel": stack(
                p + "self_attn.v_proj.weight", lambda n: heads_proj(n, kv))},
            "o_proj": {"kernel": stack(p + "self_attn.o_proj.weight", o_proj)},
        },
        "mlp": {
            "gate_proj": {"kernel": stack(
                p + "mlp.gate_proj.weight",
                lambda n: linear(n, cfg.intermediate_size))},
            "up_proj": {"kernel": stack(
                p + "mlp.up_proj.weight",
                lambda n: linear(n, cfg.intermediate_size))},
            "down_proj": {"kernel": stack(
                p + "mlp.down_proj.weight",
                lambda n: take(n, (e, cfg.intermediate_size)).T)},
        },
        "input_norm": {"weight": stack(
            p + "input_layernorm.weight", lambda n: take(n, (e,)))},
        "post_attn_norm": {"weight": stack(
            p + "post_attention_layernorm.weight", lambda n: take(n, (e,)))},
    }
    # tied embeddings (e.g. Llama-3.2-1B): no separate lm_head weight
    head_name = (
        "lm_head.weight" if "lm_head.weight" in sd
        else "model.embed_tokens.weight"
    )
    params = {
        "embed_tokens": {"embedding": jnp.asarray(
            take("model.embed_tokens.weight", (cfg.vocab_size, e)))},
        "layers": {"block": block},
        "final_norm": {"weight": jnp.asarray(take("model.norm.weight", (e,)))},
        "lm_head": {"kernel": jnp.asarray(
            take(head_name, (cfg.vocab_size, e)).T)},
    }
    return params


def convert_hf_bert(state_dict: Mapping[str, Any], cfg) -> Dict[str, Any]:
    """Convert a HF ``BertForPreTraining`` state_dict to a
    ``BertForPretraining`` params tree. Requires
    ``BertConfig(hf_head=True)`` (the HF MLM transform + NSP pooler
    exist only in that mode)."""
    if not getattr(cfg, "hf_head", False):
        raise ValueError(
            "convert_hf_bert needs BertConfig(hf_head=True) — the plain "
            "heads have no HF-equivalent transform/pooler weights"
        )
    e, h, d = cfg.hidden_size, cfg.num_heads, cfg.head_dim
    sd = dict(state_dict)

    def take(name, shape):
        return _take(sd, name, shape)

    def dense(prefix, out_f, in_f):  # torch [out, in] -> flax kernel/bias
        return {
            "kernel": jnp.asarray(take(prefix + ".weight", (out_f, in_f)).T),
            "bias": jnp.asarray(take(prefix + ".bias", (out_f,))),
        }

    def heads_dense(prefix):  # [H*d, E] -> kernel [E, H, d], bias [H, d]
        return {
            "kernel": jnp.asarray(
                take(prefix + ".weight", (h * d, e)).T.reshape(e, h, d)
            ),
            "bias": jnp.asarray(take(prefix + ".bias", (h * d,)).reshape(h, d)),
        }

    def ln(prefix):
        return {
            "scale": jnp.asarray(take(prefix + ".weight", (e,))),
            "bias": jnp.asarray(take(prefix + ".bias", (e,))),
        }

    params: Dict[str, Any] = {
        "tok_embed": {"embedding": jnp.asarray(take(
            "bert.embeddings.word_embeddings.weight", (cfg.vocab_size, e)))},
        "pos_embed": {"embedding": jnp.asarray(take(
            "bert.embeddings.position_embeddings.weight",
            (cfg.max_seq_len, e)))},
        "type_embed": {"embedding": jnp.asarray(take(
            "bert.embeddings.token_type_embeddings.weight",
            (cfg.type_vocab_size, e)))},
        "ln_embed": ln("bert.embeddings.LayerNorm"),
        "mlm_transform": dense("cls.predictions.transform.dense", e, e),
        "mlm_transform_ln": ln("cls.predictions.transform.LayerNorm"),
        "pooler": dense("bert.pooler.dense", e, e),
        "nsp_head": dense("cls.seq_relationship", 2, e),
    }
    # decoder: weight may be tied to word embeddings; bias lives at
    # cls.predictions.bias (and/or cls.predictions.decoder.bias)
    dec_w = (
        "cls.predictions.decoder.weight"
        if "cls.predictions.decoder.weight" in sd
        else "bert.embeddings.word_embeddings.weight"
    )
    dec_b = (
        "cls.predictions.decoder.bias"
        if "cls.predictions.decoder.bias" in sd
        else "cls.predictions.bias"
    )
    params["mlm_head"] = {
        "kernel": jnp.asarray(take(dec_w, (cfg.vocab_size, e)).T),
        "bias": jnp.asarray(take(dec_b, (cfg.vocab_size,))),
    }
    p = "bert.encoder.layer.{}."
    for i in range(cfg.num_layers):
        q = p.format(i)
        params[f"layer_{i}"] = {
            "q_proj": heads_dense(q + "attention.self.query"),
            "k_proj": heads_dense(q + "attention.self.key"),
            "v_proj": heads_dense(q + "attention.self.value"),
            "o_proj": {
                "kernel": jnp.asarray(
                    take(q + "attention.output.dense.weight", (e, e))
                    .T.reshape(h, d, e)
                ),
                "bias": jnp.asarray(
                    take(q + "attention.output.dense.bias", (e,))
                ),
            },
            "ln_attn": ln(q + "attention.output.LayerNorm"),
            "fc_in": dense(q + "intermediate.dense",
                           cfg.intermediate_size, e),
            "fc_out": dense(q + "output.dense", e, cfg.intermediate_size),
            "ln_mlp": ln(q + "output.LayerNorm"),
        }
    return params
