"""User-facing clients: the python job client + YAML loading.

Analogue of reference ``py/tf_job_client.py`` and the kubectl YAML
surface (``examples/*.yaml``).
"""

from k8s_tpu.client.job_client import TpuJobApi, load_tpu_job_yaml  # noqa: F401
