"""Python TpuJob client.

Analogue of reference ``py/tf_job_client.py``: ``create_tf_job`` via
the custom-objects API (:18-40) and the ``wait_for_job`` poll loop with
timeout + status callback (:43-96) — here against the framework's CRD
client (in-memory local mode or a real apiserver adapter).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

import yaml

from k8s_tpu.api.crd_client import TpuJobClient
from k8s_tpu.spec import TpuJob, TpuJobPhase

log = logging.getLogger(__name__)

DEFAULT_TIMEOUT = 300.0  # reference py/tf_job_client.py:64 (5 min)
DEFAULT_POLL = 1.0


def load_tpu_job_yaml(text: str) -> TpuJob:
    """Parse a TpuJob manifest (the kubectl-facing YAML schema)."""
    doc = yaml.safe_load(text)
    if not isinstance(doc, dict):
        raise ValueError("manifest must be a mapping")
    kind = doc.get("kind")
    if kind and kind != "TpuJob":
        raise ValueError(f"manifest kind is {kind!r}, want TpuJob")
    return TpuJob.from_dict(doc)


class TpuJobApi:
    """Thin convenience wrapper for scripts and test harnesses."""

    def __init__(self, crd_client: TpuJobClient):
        self.client = crd_client

    def create(self, job: TpuJob) -> TpuJob:
        created = self.client.create(job)
        log.info("created TpuJob %s", created.key)
        return created

    def create_from_yaml(self, text: str) -> TpuJob:
        return self.create(load_tpu_job_yaml(text))

    def get(self, namespace: str, name: str) -> TpuJob:
        return self.client.get(namespace, name)

    def delete(self, namespace: str, name: str) -> None:
        self.client.delete(namespace, name)

    def wait_for_job(
        self,
        namespace: str,
        name: str,
        timeout: float = DEFAULT_TIMEOUT,
        polling_interval: float = DEFAULT_POLL,
        status_callback: Optional[Callable[[TpuJob], None]] = None,
    ) -> TpuJob:
        """Poll until the job reaches a terminal phase (reference
        wait_for_job semantics: TimeoutError past the budget)."""
        deadline = time.monotonic() + timeout
        while True:
            job = self.client.get(namespace, name)
            if status_callback is not None:
                status_callback(job)
            if job.status.phase in (TpuJobPhase.DONE, TpuJobPhase.FAILED):
                return job
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"timeout waiting for TpuJob {namespace}/{name}; "
                    f"phase={job.status.phase!r}"
                )
            time.sleep(polling_interval)
