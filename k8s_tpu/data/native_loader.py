"""Python bindings for the native data loader (``native/data_loader.cc``).

Fixed-size binary records → batched numpy arrays, with the IO, shuffle
and batch assembly running on C++ threads outside the GIL. Feed the
result through :func:`k8s_tpu.data.prefetch.device_prefetch` for the
host→device double-buffered edge.

The reference had no in-repo input pipeline at all (user containers
brought TF readers); this is the native-equivalent component the TPU
framework ships itself.
"""

from __future__ import annotations

import ctypes
from typing import Iterator, Optional, Sequence

import numpy as np

from k8s_tpu.runtime import native as _native


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    if getattr(lib, "_loader_bound", False):
        return lib
    lib.ktpu_loader_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.ktpu_loader_open.restype = ctypes.c_int
    lib.ktpu_loader_next.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_int,
    ]
    lib.ktpu_loader_next.restype = ctypes.c_int
    lib.ktpu_loader_register_buffers.argtypes = [
        ctypes.c_int, ctypes.POINTER(ctypes.c_void_p), ctypes.c_int,
    ]
    lib.ktpu_loader_register_buffers.restype = ctypes.c_int
    lib.ktpu_loader_next_slot.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ctypes.c_void_p, ctypes.c_int,
    ]
    lib.ktpu_loader_next_slot.restype = ctypes.c_int
    lib.ktpu_loader_stats.argtypes = [
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.ktpu_loader_close.argtypes = [ctypes.c_int]
    lib._loader_bound = True
    return lib


class NativeRecordLoader:
    """Iterate batches of fixed-size records from a sharded file list.

    Each batch is a ``[n, record_bytes]`` uint8 array (n == ``batch``
    except possibly the last when ``drop_remainder=False``); reshape /
    view-cast to the actual record dtype at the call site (records are
    static-shape by construction — the TPU-idiomatic format).
    """

    def __init__(
        self,
        paths: Sequence[str],
        record_bytes: int,
        batch: int,
        *,
        queue_depth: int = 4,
        num_threads: int = 4,
        shuffle_buffer: int = 0,
        seed: int = 0,
        shard_id: int = 0,
        num_shards: int = 1,
        drop_remainder: bool = False,
        loop: bool = False,
    ):
        self._lib = _bind(_native.load())
        self.record_bytes = record_bytes
        self.batch = batch
        joined = "\n".join(paths).encode()
        h = self._lib.ktpu_loader_open(
            joined, record_bytes, batch, queue_depth, num_threads,
            shuffle_buffer, seed, shard_id, num_shards,
            int(drop_remainder), int(loop),
        )
        if h < 0:
            raise ValueError(f"ktpu_loader_open failed: errno {-h}")
        self._handle: Optional[int] = h
        self._queue_depth = queue_depth
        self._ring: Optional[np.ndarray] = None  # zero-copy buffers
        self._prev_slot = -1

    def next(self, timeout_s: float = 60.0) -> Optional[np.ndarray]:
        """One batch, or None at end-of-data. Raises on timeout."""
        if self._handle is None:
            raise RuntimeError("loader is closed")
        buf = np.empty((self.batch, self.record_bytes), np.uint8)
        n = self._lib.ktpu_loader_next(
            self._handle, buf.ctypes.data_as(ctypes.c_void_p),
            int(timeout_s * 1000),
        )
        if n == 0:
            return None
        if n == -110:
            raise TimeoutError(f"no batch within {timeout_s}s")
        if n < 0:
            raise OSError(-n, "ktpu_loader_next")
        return buf[:n]

    def next_zero_copy(self, timeout_s: float = 60.0) -> Optional[np.ndarray]:
        """One batch with NO consumer-side copy: producers assemble
        batches directly into a ring of numpy buffers owned by this
        loader. The returned array is a view into that ring and is
        VALID ONLY UNTIL THE NEXT CALL (its slot is then recycled) —
        consume it synchronously (e.g. ``jax.device_put`` + block, or
        feed a jitted step) or copy. On a bandwidth-bound host this
        halves the consumer cost vs :meth:`next`.
        """
        if self._handle is None:
            raise RuntimeError("loader is closed")
        if self._ring is None:
            n = self._queue_depth + 4  # > queue_depth: producers never starve
            self._ring = np.empty((n, self.batch, self.record_bytes), np.uint8)
            ptrs = (ctypes.c_void_p * n)(
                *(self._ring[i].ctypes.data for i in range(n))
            )
            rc = self._lib.ktpu_loader_register_buffers(self._handle, ptrs, n)
            if rc < 0:
                raise OSError(-rc, "ktpu_loader_register_buffers")
            self._fallback = np.empty((self.batch, self.record_bytes), np.uint8)
        slot = ctypes.c_int(-1)
        n = self._lib.ktpu_loader_next_slot(
            self._handle, self._prev_slot, ctypes.byref(slot),
            self._fallback.ctypes.data_as(ctypes.c_void_p),
            int(timeout_s * 1000),
        )
        self._prev_slot = slot.value
        if n == 0:
            return None
        if n == -110:
            raise TimeoutError(f"no batch within {timeout_s}s")
        if n < 0:
            raise OSError(-n, "ktpu_loader_next_slot")
        if slot.value < 0:  # pre-registration batch, copied to fallback
            return self._fallback[:n]
        return self._ring[slot.value, :n]

    def iter_zero_copy(self) -> Iterator[np.ndarray]:
        """Iterate batches via :meth:`next_zero_copy` (each yielded
        array is invalidated by the following iteration)."""
        while True:
            b = self.next_zero_copy()
            if b is None:
                return
            yield b

    def stats(self) -> dict:
        if self._handle is None:
            raise RuntimeError("loader is closed")
        b = ctypes.c_uint64()
        r = ctypes.c_uint64()
        s = ctypes.c_uint64()
        self._lib.ktpu_loader_stats(
            self._handle, ctypes.byref(b), ctypes.byref(r), ctypes.byref(s)
        )
        return {
            "batches": b.value,
            "records": r.value,
            "skipped_files": s.value,
        }

    def close(self) -> None:
        if self._handle is not None:
            self._lib.ktpu_loader_close(self._handle)
            self._handle = None
            self._ring = None  # safe to release only after close joins

    def __del__(self):
        # zero-copy mode registers numpy ring buffers with the C++
        # producer threads; dropping the object without close() would
        # free memory those threads still write into. close() joins
        # them first. Guard: ctypes/libc may be torn down at
        # interpreter exit.
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            b = self.next()
            if b is None:
                return
            yield b
