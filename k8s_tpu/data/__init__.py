"""Input pipelines: synthetic benchmark data + real record loaders."""

from k8s_tpu.data.records import (  # noqa: F401
    image_record_batches,
    write_image_shards,
)
from k8s_tpu.data.synthetic import (  # noqa: F401
    learnable_token_batches,
    synthetic_image_batches,
    synthetic_mnist,
    synthetic_token_batches,
)
