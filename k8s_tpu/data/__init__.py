"""Input pipelines: synthetic benchmark data + simple real loaders."""

from k8s_tpu.data.synthetic import (  # noqa: F401
    synthetic_image_batches,
    synthetic_mnist,
    synthetic_token_batches,
)
