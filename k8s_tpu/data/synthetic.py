"""Synthetic data generators for benchmarks and tests.

Host-side numpy generation (no device work in the input path), double-
buffered onto device by the caller via ``jax.device_put`` with the
batch sharding — the minimal input pipeline that keeps the TPU fed for
steps/sec measurement without an I/O dependency.
"""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np


def synthetic_image_batches(
    batch_size: int,
    image_size: int = 224,
    num_classes: int = 1000,
    seed: int = 0,
) -> Iterator[dict]:
    rng = np.random.default_rng(seed)
    images = rng.standard_normal((batch_size, image_size, image_size, 3), np.float32)
    labels = rng.integers(0, num_classes, (batch_size,), np.int32)
    while True:
        yield {"images": images, "labels": labels}


def synthetic_mnist(batch_size: int, seed: int = 0) -> Iterator[dict]:
    rng = np.random.default_rng(seed)
    while True:
        yield {
            "images": rng.standard_normal((batch_size, 28, 28, 1), np.float32),
            "labels": rng.integers(0, 10, (batch_size,), np.int32),
        }


def synthetic_token_batches(
    batch_size: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
) -> Iterator[dict]:
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab_size, (batch_size, seq_len), np.int32)
    while True:
        yield {"input_ids": ids}


def learnable_token_batches(
    batch_size: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
) -> Iterator[dict]:
    """FRESH batches of a deterministic next-token rule (same family as
    tests/llm_fixtures.py): token t is a fixed affine function of the
    row's start token, so a working optimizer+sharding stack drives the
    loss well below its random-init value within tens of steps — and a
    silently broken gradient path does not. This is the data source the
    convergence gates train on (``llama_train --data=learnable``);
    memorizing one fixed random batch (the ``synthetic_*`` generators)
    cannot distinguish learning from noise."""
    rng = np.random.default_rng(seed)
    steps = np.arange(seq_len)
    while True:
        start = rng.integers(0, vocab_size, (batch_size, 1))
        yield {
            "input_ids": (
                (start * (steps + 1) * 3 + 7 * steps) % vocab_size
            ).astype(np.int32)
        }
