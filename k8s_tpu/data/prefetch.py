"""Double-buffered device prefetch.

Keeps the TPU fed: a background thread runs ``device_put`` (with the
batch sharding) ahead of consumption so host→HBM transfer overlaps the
previous step's compute — the input-pipeline half of the steps/sec
story on real data.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, Optional


def prefetch_to_device(
    it: Iterator,
    sharder: Callable,
    buffer_size: int = 2,
) -> Iterator:
    """Wrap a host-batch iterator; yields device-resident batches.
    ``sharder`` is typically ``make_batch_sharder(mesh, rules)``."""
    q: "queue.Queue" = queue.Queue(maxsize=buffer_size)
    stop = threading.Event()
    _SENTINEL = object()

    def producer():
        try:
            for batch in it:
                if stop.is_set():
                    return
                q.put(sharder(batch))
        except Exception as e:  # propagate into the consumer
            q.put(e)
        finally:
            q.put(_SENTINEL)

    t = threading.Thread(target=producer, daemon=True, name="prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, Exception):
                raise item
            yield item
    finally:
        stop.set()
        # drain so the producer unblocks
        while not q.empty():
            q.get_nowait()
