"""Double-buffered device prefetch.

Keeps the TPU fed: a background thread runs ``device_put`` (with the
batch sharding) ahead of consumption so host→HBM transfer overlaps the
previous step's compute — the input-pipeline half of the steps/sec
story on real data.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, Optional


def prefetch_to_device(
    it: Iterator,
    sharder: Callable,
    buffer_size: int = 2,
) -> Iterator:
    """Wrap a host-batch iterator; yields device-resident batches.
    ``sharder`` is typically ``make_batch_sharder(mesh, rules)``."""
    q: "queue.Queue" = queue.Queue(maxsize=buffer_size)
    stop = threading.Event()
    _SENTINEL = object()

    def _put(item) -> bool:
        """Bounded put that a departed consumer cannot wedge: an
        abandoning consumer sets ``stop`` and walks away, so a plain
        blocking ``q.put`` into a full queue would park the producer
        thread forever (the old shutdown leak — worse, its sentinel
        put in ``finally`` could block too, pinning the thread, the
        iterator, and every device batch in the queue for the process
        lifetime). Timeout-put + stop-check keeps the producer's exit
        latency bounded by one timeout tick."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for batch in it:
                if stop.is_set():
                    return
                if not _put(sharder(batch)):
                    return
        except Exception as e:  # propagate into the consumer
            _put(e)
        finally:
            _put(_SENTINEL)

    t = threading.Thread(target=producer, daemon=True, name="prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, Exception):
                raise item
            yield item
    finally:
        stop.set()
        # free queued device batches promptly (the producer no longer
        # needs this drain to unblock — _put checks stop — but batches
        # sitting in an orphaned queue would pin HBM until GC)
        while not q.empty():
            try:
                q.get_nowait()
            except queue.Empty:
                break
