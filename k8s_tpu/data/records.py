"""Fixed-size image-record format: the on-disk contract between the
native loader and the training programs.

Record layout (static shapes — the TPU-idiomatic format; no per-record
parsing, a batch is one reshape + view-cast away from a numpy array):

    [0:8)                int64 little-endian label
    [8:8+H*W*C)          uint8 HWC image

The reference shipped no input pipeline at all (user containers brought
TF readers, SURVEY §0); this module + ``native_loader`` (C++ threads)
+ ``prefetch`` (host→device double-buffering) is the in-repo
equivalent: disk → batched numpy → sharded device arrays.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence

import numpy as np

from k8s_tpu.data.native_loader import NativeRecordLoader

_HEADER = 8


def record_bytes(image_size: int, channels: int = 3) -> int:
    return _HEADER + image_size * image_size * channels


def write_image_shards(
    out_dir: str,
    images: np.ndarray,  # [N, H, W, C] uint8
    labels: np.ndarray,  # [N] int
    num_shards: int = 1,
    prefix: str = "train",
) -> List[str]:
    """Write images+labels as sharded fixed-size record files."""
    n, h, w, c = images.shape
    assert h == w, "square images only"
    os.makedirs(out_dir, exist_ok=True)
    rb = record_bytes(h, c)
    paths = []
    for s in range(num_shards):
        idx = range(s, n, num_shards)
        buf = np.empty((len(list(idx)), rb), np.uint8)
        for row, i in enumerate(range(s, n, num_shards)):
            buf[row, :_HEADER] = np.frombuffer(
                np.int64(labels[i]).tobytes(), np.uint8
            )
            buf[row, _HEADER:] = images[i].reshape(-1)
        path = os.path.join(out_dir, f"{prefix}-{s:05d}-of-{num_shards:05d}.rec")
        buf.tofile(path)
        paths.append(path)
    return paths


def image_record_batches(
    paths: Sequence[str],
    batch_size: int,
    image_size: int,
    channels: int = 3,
    *,
    shuffle_buffer: int = 0,
    seed: int = 0,
    shard_id: int = 0,
    num_shards: int = 1,
    loop: bool = True,
    num_threads: int = 4,
    normalize: bool = False,
    drop_remainder: Optional[bool] = None,
) -> Iterator[dict]:
    """Stream ``{"images": [B,H,W,C], "labels": i32 [B]}`` batches from
    record shards through the native loader (zero-copy ring; the decode
    below copies out of the ring, so yielded batches are safe to hold).

    Images stay **uint8** by default: normalize ON DEVICE inside the
    jitted step (see resnet_train's loss_fn) — host-side f32 would 4x
    the host→device transfer, which is the narrow edge (PCIe on real
    hosts, ~70 MB/s on the remote-tunnel dev chip). ``normalize=True``
    does the f32 ``/127.5 - 1`` on host for non-jit consumers.

    ``drop_remainder`` defaults by use: True when ``loop`` (training
    wants static batch shapes; the tail re-appears next epoch anyway),
    False otherwise (eval/one-pass must see every record — the final
    short batch is yielded)."""
    if drop_remainder is None:
        drop_remainder = loop
    rb = record_bytes(image_size, channels)
    loader = NativeRecordLoader(
        paths, rb, batch_size,
        shuffle_buffer=shuffle_buffer, seed=seed,
        shard_id=shard_id, num_shards=num_shards,
        loop=loop, drop_remainder=drop_remainder, num_threads=num_threads,
    )
    try:
        for raw in loader.iter_zero_copy():
            labels = (
                raw[:, :_HEADER].reshape(-1).view(np.int64).astype(np.int32)
            )
            images = raw[:, _HEADER:].reshape(
                raw.shape[0], image_size, image_size, channels
            )
            if normalize:
                images = images.astype(np.float32) / 127.5 - 1.0
            else:
                images = images.copy()  # off the zero-copy ring
            yield {"images": images, "labels": labels}
    finally:
        loader.close()
