"""Elastic-resize decision core (docs/ELASTIC.md).

Pure decision logic in the StragglerDetector/HealthMonitor/
SloAutoscaler idiom: an injected clock, no I/O, no threads — the
reconciler feeds :meth:`ElasticResizer.observe` one observation per
obs tick and acts on the verdict. That is what makes the whole
decision table unit-testable on a fake clock.

Decision rules, in order, per observation:

1. **cooldown** — within ``cooldown_s`` of the last acted-on resize
   (``note_resized``) nothing fires: a resize is a whole-gang restart,
   and back-to-back resizes are churn, not recovery (no-flap).
2. **shrink (inventory)** — the scheduler's attainable-slice view says
   this job can hold fewer slices than its current DP degree (a slice
   was revoked / a node pool shrank under the gang). Decisive — the
   ledger already knows the capacity is gone, there is nothing to wait
   out. Target = attainable, clamped to ``[min_dp, max_dp]``; below
   ``min_dp`` the job cannot run at any legal shape and the verdict
   says so (the caller falls through to the plain restart/Failed
   path rather than resizing into the floor).
3. **shrink (dead heartbeat)** — a host that WAS answering and then
   went silent for ``dead_after_s`` while at least one peer still
   answers (an operator-wide outage must not read as host death) is
   presumed permanently lost along with its slice. Target = surviving
   slices, same clamping. Requires ``resize_on_permanent_loss``.
   A host never seen this episode is *starting*, not dead — pod
   scheduling/image pulls routinely exceed any honest silence window,
   and a fresh post-resize gang must not be shrunk for booting slowly
   (an actually-failed pod surfaces through the degraded-pod gang
   path, and a revoked slice through the inventory trigger).
4. **grow** — attainable slices exceed the current DP degree for
   ``grow_hold_s`` of sustained clock time (a capacity blip shorter
   than the hold moves nothing — hysteresis mirrors the
   SloAutoscaler's breach streaks). Target = attainable, capped at
   ``max_dp``.

Every verdict carries the **health-gated restore ceiling**: when the
freshest numerics block is poisoned (non-finite loss/grads — the PR-9
``step_health`` contract), ``restore_ceiling`` is the last *healthy*
step, which the caller threads into the restarted gang as
``KTPU_CKPT_RESTORE_MAX_STEP`` so a NaN step is never the resize
restore point. ``budget_left <= 0`` turns any would-be action into
``"exhausted"`` — resizes are budget-counted like divergence restarts,
and a fleet that keeps losing slices must eventually fail the job,
not resize forever.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

ACTION_SHRINK = "shrink"
ACTION_GROW = "grow"
ACTION_EXHAUSTED = "exhausted"


def _finite(x) -> bool:
    try:
        return math.isfinite(float(x))
    except (TypeError, ValueError):
        return False


@dataclass
class ResizeVerdict:
    """One observation's outcome. ``action`` is None when the gang
    should keep its shape (``reason`` says why a trigger that looked
    armed did not fire); ``target_dp`` accompanies shrink/grow.
    ``restore_ceiling`` is set iff the freshest numerics are poisoned —
    the last healthy step the resize restore must not exceed."""

    action: Optional[str] = None
    target_dp: int = 0
    reason: str = ""
    restore_ceiling: Optional[int] = None
    dead_hosts: Tuple[int, ...] = field(default_factory=tuple)
    # which rule fired: "inventory" | "dead-hosts" | "capacity-return".
    # The ledger callback uses it to re-verify an inventory-triggered
    # shrink against the LIVE pool deficit inside its critical section
    # (two gangs sharing a pool must not both surrender a slice for
    # one revocation).
    trigger: str = ""


class ElasticResizer:
    """Pure shrink/grow decision over heartbeat + inventory signals.

    ``min_dp``/``max_dp`` bound the legal DP degrees (from
    ``spec.elastic``); the window knobs come from the same block so a
    chaos e2e can run the whole cycle in seconds while production
    defaults ride out transient blips."""

    def __init__(
        self,
        min_dp: int,
        max_dp: int,
        dead_after_s: float = 10.0,
        grow_hold_s: float = 10.0,
        cooldown_s: float = 30.0,
        resize_on_permanent_loss: bool = True,
        clock: Callable[[], float] = time.monotonic,
    ):
        if min_dp < 1 or max_dp < min_dp:
            raise ValueError(
                f"need 1 <= min_dp <= max_dp, got [{min_dp}, {max_dp}]")
        self.min_dp = int(min_dp)
        self.max_dp = int(max_dp)
        self.dead_after_s = float(dead_after_s)
        self.grow_hold_s = float(grow_hold_s)
        self.cooldown_s = float(cooldown_s)
        self.resize_on_permanent_loss = bool(resize_on_permanent_loss)
        self.clock = clock
        # host -> last time it answered a sweep; a host with no entry
        # has never answered THIS episode and is treated as starting,
        # never as dead (see _maybe_shrink)
        self._last_seen: Dict[int, float] = {}
        self._grow_since: Optional[float] = None
        self._last_resize_at: Optional[float] = None
        self._last_healthy_step: Optional[int] = None

    # -------------------------------------------------------------- intake

    def note_resized(self, new_dp: int) -> None:
        """The caller ACTED on a verdict: arm the cooldown and clear
        every streak — the new gang is a new episode (its host set and
        heartbeat cadence have nothing to do with the old one's)."""
        self._last_resize_at = self.clock()
        self._grow_since = None
        self._last_seen.clear()

    # -------------------------------------------------------------- decide

    def observe(
        self,
        dp: int,
        hosts: int,
        stats: Optional[Dict[int, dict]] = None,
        attainable: Optional[int] = None,
        budget_left: Optional[int] = None,
        health: Optional[dict] = None,
    ) -> ResizeVerdict:
        """Judge one observation.

        ``dp``: the gang's current DP degree (slices held).
        ``hosts``: expected live host count at this degree.
        ``stats``: the obs tick's heartbeat sweep (host → heartbeat);
        hosts absent from it did not answer.
        ``attainable``: slices this job could hold right now = held +
        pool free (None = no scheduler; inventory triggers disabled).
        ``budget_left``: remaining resize/restart budget (None =
        unbounded).
        ``health``: the freshest ``step_health`` block, for the
        restore-ceiling gate.
        """
        now = self.clock()
        v = ResizeVerdict()
        for h, hb in (stats or {}).items():
            if isinstance(hb, dict):
                self._last_seen[int(h)] = now
        # drop hosts beyond the current width (stale entries from a
        # wider incarnation must not read as deaths)
        for h in [h for h in self._last_seen if h >= hosts]:
            del self._last_seen[h]

        v.restore_ceiling = self._health_ceiling(health)

        # the inventory shrink is DECISIVE and bypasses the cooldown:
        # the capacity is gone, waiting cannot help, and a degraded
        # gang falling through to a same-shape restart could never
        # place — the cooldown exists to damp flappy evidence, and a
        # ledger deficit is not flappy evidence
        verdict = self._inventory_shrink(v, dp, attainable)
        if verdict is None:
            if (self._last_resize_at is not None
                    and now - self._last_resize_at < self.cooldown_s):
                v.reason = (
                    f"resize cooldown "
                    f"({self._last_resize_at + self.cooldown_s - now:.1f}s"
                    f" left)")
                return v
            verdict = self._dead_host_shrink(v, dp, hosts, now)
        if verdict is None:
            verdict = self._maybe_grow(v, dp, now, attainable)
        if verdict is None:
            return v
        if budget_left is not None and budget_left <= 0:
            if verdict == ACTION_GROW:
                # a blocked GROW must never hurt the running gang: it
                # keeps training at its current width — only a shrink
                # the budget cannot back turns terminal (the gang
                # cannot run at its current shape at all)
                v.reason = (f"grow to DP={v.target_dp} wanted but the "
                            f"restart budget is spent; keeping DP={dp}")
                v.action = None
                v.target_dp = 0
                return v
            v.action = ACTION_EXHAUSTED
            v.reason = (f"resize wanted ({verdict}: DP={dp} -> "
                        f"DP={v.target_dp}) but the restart budget is spent")
            return v
        v.action = verdict
        return v

    # -------------------------------------------------------------- rules

    def _health_ceiling(self, health: Optional[dict]) -> Optional[int]:
        """Track the last healthy step off the freshest numerics block;
        return it as the restore ceiling iff the CURRENT block is
        poisoned (the PR-9 rule: a NaN step must never be the restore
        point — healthy runs get no ceiling at all)."""
        if not isinstance(health, dict):
            return None
        try:
            step = int(health.get("step", -1))
        except (TypeError, ValueError):
            return None
        nonfinite = 0.0
        try:
            nonfinite = float(health.get("nonfinite_grads", 0) or 0)
        except (TypeError, ValueError):
            nonfinite = 0.0
        bad = (nonfinite > 0
               or not _finite(health.get("loss"))
               or not _finite(health.get("grad_norm", 0.0)))
        if not bad:
            if step >= 0:
                # track the run, not a max(): a restore regresses the
                # step, and the ceiling must follow it DOWN — a stale
                # pre-resize high-water mark would exclude nothing of
                # the new run's poisoned window
                self._last_healthy_step = step
            return None
        return self._last_healthy_step if self._last_healthy_step is not None \
            else 0

    def _clamp_target(self, v: ResizeVerdict, want: int, dp: int,
                      why: str) -> Optional[str]:
        target = min(self.max_dp, want)
        if target < self.min_dp:
            v.reason = (f"{why}, but DP={target} is below minDpDegree="
                        f"{self.min_dp} — no legal shape fits; not resizing")
            return None
        if target == dp:
            v.reason = f"{why}, already at DP={dp}"
            return None
        v.target_dp = target
        v.reason = why
        return ACTION_SHRINK if target < dp else ACTION_GROW

    def _inventory_shrink(self, v: ResizeVerdict, dp: int,
                          attainable: Optional[int]) -> Optional[str]:
        """Inventory trigger: the ledger says the capacity is gone."""
        if not self.resize_on_permanent_loss:
            return None
        if attainable is None or attainable >= dp:
            return None
        got = self._clamp_target(
            v, attainable, dp,
            f"inventory shrink: {attainable} attainable slice(s) "
            f"< DP={dp}")
        if got == ACTION_SHRINK:
            v.trigger = "inventory"
            return got
        return None

    def _dead_host_shrink(self, v: ResizeVerdict, dp: int, hosts: int,
                          now: float) -> Optional[str]:
        if not self.resize_on_permanent_loss:
            return None
        # dead-heartbeat trigger: a host that WAS answering went silent
        # past the window while a peer still answers. Never-seen hosts
        # are STARTING, not dead — judging them from the monitor floor
        # would declare a pod that boots slower than the window (image
        # pull, TPU init) permanently lost and flap a fresh grow right
        # back into a shrink.
        stats_alive = [h for h, t in self._last_seen.items()
                       if now - t < self.dead_after_s]
        if not stats_alive:
            return None  # nobody answering: outage or startup, not loss
        dead = tuple(sorted(
            h for h, t in self._last_seen.items()
            if now - t >= self.dead_after_s))
        if not dead:
            return None
        hosts_per_slice = max(1, hosts // max(1, dp))
        lost_slices = len({h // hosts_per_slice for h in dead})
        v.dead_hosts = dead
        got = self._clamp_target(
            v, dp - lost_slices, dp,
            f"host(s) {list(dead)} heartbeat-dead for >= "
            f"{self.dead_after_s:g}s ({lost_slices} slice(s) presumed "
            f"permanently lost)")
        if got == ACTION_SHRINK:
            v.trigger = "dead-hosts"
            return got
        return None

    def _maybe_grow(self, v: ResizeVerdict, dp: int, now: float,
                    attainable: Optional[int]) -> Optional[str]:
        if attainable is None or attainable <= dp or dp >= self.max_dp:
            self._grow_since = None
            return None
        if self._grow_since is None:
            self._grow_since = now
        held = now - self._grow_since
        if held < self.grow_hold_s:
            v.reason = (f"capacity returned ({attainable} attainable > "
                        f"DP={dp}); holding {self.grow_hold_s - held:.1f}s "
                        f"more for stability")
            return None
        got = self._clamp_target(
            v, attainable, dp,
            f"capacity returned: {attainable} attainable slice(s) held "
            f"for >= {self.grow_hold_s:g}s")
        if got is not None:
            v.trigger = "capacity-return"
        return got
