"""Elastic gang resize (docs/ELASTIC.md).

The layer between "a pod died permanently" and "training continues at
DP−1": a pure, clock-injected decision core
(:mod:`k8s_tpu.resize.elastic`) the reconciler feeds with the PR-9
observe→act signals — per-host heartbeat freshness (dead-host
detection), the scheduler inventory's attainable-slice view (shrink
when a slice is gone for good), and the capacity-return tick (grow
back when the fleet frees slices). Verdicts are data; the operator
acts on them by driving the ``Resizing`` TpuJob transition
(flush-teardown → re-plan the restore at the new DP degree → re-admit
the reshaped footprint through the scheduler ledger).
"""

from k8s_tpu.resize.elastic import (  # noqa: F401
    ElasticResizer,
    ResizeVerdict,
)
