"""Pipeline parallelism: GPipe-style microbatched stages over the
``stage`` mesh axis.

Completes the PP row of SURVEY §2.5 (absent in the reference). Stage
weights are stacked on a leading axis sharded over ``stage``; inside
``shard_map`` each device runs its stage function while activations
hop stage→stage via ``jax.lax.ppermute``. The steady state keeps every
stage busy; bubble fraction is (S-1)/(M+S-1) for S stages and M
microbatches. The schedule is a ``lax.scan`` (reverse-differentiable,
single compiled loop).

Schedule note (why GPipe, not 1F1B): differentiating the scan yields
GPipe's all-forward-then-all-backward order automatically; 1F1B would
need hand-orchestrated per-microbatch VJPs. 1F1B's win is activation
memory at LARGE M — here remat bounds per-microbatch activation
storage and the at-scale compile (aot_check llama3-8b-pp-fsdp,
stage=4 M=4) peaks at 14.7 of 90 GiB/chip, so the memory case hasn't
arrived. The bubble is managed by raising M (e.g. S=4: M=4 → 43%,
M=16 → 16%), which the headroom accommodates; revisit 1F1B only if a
config is simultaneously bubble-bound and memory-bound.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax

from k8s_tpu.utils import axis_size_compat
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _stage_body(
    params,  # this stage's params (leading stage axis peeled)
    microbatches,  # [M, mb, ...] same on every stage (stage 0 consumes)
    aux_mbs,  # [M, mb, ...] per-microbatch aux (segment_ids) or None
    fn: Callable,
    axis_name: str,
):
    n = axis_size_compat(axis_name)
    idx = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    steps = m + n - 1

    if aux_mbs is None:
        out_shape = jax.eval_shape(fn, params, microbatches[0])
    else:
        out_shape = jax.eval_shape(fn, params, microbatches[0], aux_mbs[0])
    outputs0 = jnp.zeros((m, *out_shape.shape), out_shape.dtype)
    carry0 = jnp.zeros(out_shape.shape, out_shape.dtype)

    def step(state, t):
        carry, outputs = state
        mb_idx = jnp.clip(t, 0, m - 1)
        x_in = jnp.where(idx == 0, microbatches[mb_idx], carry)
        if aux_mbs is None:
            y = fn(params, x_in)
        else:
            # stage `idx` is processing microbatch t-idx at step t, so
            # its aux (segment_ids) is indexed by THAT, not by t: the
            # activations hop stages via ppermute but the aux array is
            # local to every stage (the batch is not stage-sharded).
            # Bubble steps (t-idx out of range) compute on clamped aux
            # and their outputs are discarded by the emit mask below.
            y = fn(params, x_in, aux_mbs[jnp.clip(t - idx, 0, m - 1)])
        # send my activation to the next stage (last stage's output
        # falls off the end of the line)
        perm = [(i, i + 1) for i in range(n - 1)]
        carry_next = jax.lax.ppermute(y, axis_name, perm)
        # the last stage emits microbatch t-(n-1) at step t
        out_t = t - (n - 1)
        is_emit = (idx == n - 1) & (out_t >= 0)
        safe_t = jnp.clip(out_t, 0, m - 1)
        outputs = jnp.where(
            is_emit,
            outputs.at[safe_t].set(y),
            outputs,
        )
        return (carry_next, outputs), None

    (_, outputs), _ = jax.lax.scan(step, (carry0, outputs0), jnp.arange(steps))
    # only the last stage holds real outputs; share them ring-wide so
    # the loss is computable anywhere (psum of one-hot contribution)
    outputs = jax.lax.psum(
        jnp.where(idx == n - 1, outputs, jnp.zeros_like(outputs)), axis_name
    )
    return outputs


def pipeline_apply(
    fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,  # leaves [n_stages*, ...], sharded on "stage"
    x: jax.Array,  # [batch, ...] global
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = "stage",
    batch_axes=("data", "fsdp"),
    param_specs: Any = None,
    peel_stage_axis: bool = True,
    aux: Any = None,
) -> jax.Array:
    """Run ``fn`` as a pipeline: ``fn(stage_params, x) -> y`` must be
    shape-preserving across stages (classic transformer-block stack).
    Returns fn's output for the full batch, microbatched through the
    stages.

    ``aux`` (optional, [batch, ...]) rides the same microbatch split as
    ``x`` and is handed to ``fn(stage_params, x, aux_mb)`` — the packed-
    document segment_ids path: unlike the activations it never hops
    stages (every stage holds the full local aux and indexes the
    microbatch it is currently processing).

    ``param_specs`` (default: every leaf ``P(axis_name)``) is a pytree
    of PartitionSpecs matching ``stacked_params`` whose FIRST entry
    must shard the leading (layer) axis over ``axis_name``; extra
    entries carry through other axes (e.g. ``fsdp``-sharded embed dims
    for the manual-FSDP composition — the stage body all-gathers those
    per layer and the transpose becomes a reduce-scatter, i.e. ZeRO-3).

    ``peel_stage_axis=True`` is the one-layer-per-stage contract
    (leaves ``[n_stages, ...]``, fn sees one layer's params);
    ``False`` hands fn the full local ``[layers_per_stage, ...]`` slab
    to scan over itself (the transformer-stack case)."""
    from k8s_tpu.utils import shard_map_compat

    n_stages = mesh.shape[axis_name]
    b = x.shape[0]
    dp = 1
    for a in (batch_axes if isinstance(batch_axes, (tuple, list)) else (batch_axes,)):
        dp *= mesh.shape[a]
    if b % dp or (b // dp) % num_microbatches:
        raise ValueError(
            f"global batch {b} must split into {dp} data shards x "
            f"{num_microbatches} microbatches"
        )

    if param_specs is None:
        param_specs = jax.tree_util.tree_map(
            lambda _: P(axis_name), stacked_params
        )
    x_spec = P(batch_axes, *([None] * (x.ndim - 1)))

    def body(params, xs, *rest):
        if peel_stage_axis:
            params = jax.tree_util.tree_map(lambda p: p[0], params)
        mbs = xs.reshape(num_microbatches, -1, *xs.shape[1:])
        aux_mbs = (
            rest[0].reshape(num_microbatches, -1, *rest[0].shape[1:])
            if rest else None
        )
        out = _stage_body(params, mbs, aux_mbs, fn, axis_name)
        return out.reshape(-1, *out.shape[2:])

    if aux is None:
        in_specs = (param_specs, x_spec)
        operands = (stacked_params, x)
    else:
        aux_spec = P(batch_axes, *([None] * (aux.ndim - 1)))
        in_specs = (param_specs, x_spec, aux_spec)
        operands = (stacked_params, x, aux)
    return shard_map_compat(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=x_spec,
        check_vma=False,
    )(*operands)
