"""Device-mesh construction.

The scaling recipe (jax-ml.github.io/scaling-book): pick a mesh whose
axes name the parallelism dimensions, annotate shardings, let XLA
insert the collectives. Axes used throughout the framework:

- ``data``   — pure data parallelism (gradient all-reduce over ICI/DCN)
- ``fsdp``   — fully-sharded data parallelism (params/opt-state sharded,
  all-gathered per layer; ZeRO-3 analogue)
- ``tensor`` — tensor/model parallelism (Megatron-style, activations
  all-reduced per block; keep inside one ICI domain)
- ``seq``    — sequence/context parallelism (ring attention over ICI)
- ``expert`` — expert parallelism for MoE layers (all-to-all)
- ``stage``  — pipeline stages (ppermute microbatches)

Multi-slice jobs put ``data`` (gradient sync) across DCN and everything
bandwidth-hungry inside a slice, matching the megascale guidance.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import numpy as np

AXES = ("data", "fsdp", "stage", "expert", "seq", "tensor")


@dataclass(frozen=True)
class MeshConfig:
    """Sizes per logical axis; -1 on ``data`` means "absorb the rest"."""

    data: int = -1
    fsdp: int = 1
    stage: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    def resolved(self, n_devices: int) -> "MeshConfig":
        known = self.fsdp * self.stage * self.expert * self.seq * self.tensor
        data = self.data
        if data == -1:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by non-data axes product {known}"
                )
            data = n_devices // known
        if data * known != n_devices:
            raise ValueError(
                f"mesh {self} needs {data * known} devices, have {n_devices}"
            )
        return MeshConfig(data, self.fsdp, self.stage, self.expert, self.seq, self.tensor)

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.data, self.fsdp, self.stage, self.expert, self.seq, self.tensor)


def build_mesh(
    config: MeshConfig,
    devices: Optional[Sequence] = None,
    allow_split_physical_axes: bool = True,
):
    """Build a ``jax.sharding.Mesh`` with the six named axes.

    Uses ``mesh_utils.create_device_mesh`` so the logical axes land on
    the physical ICI torus contiguously (nearest-neighbor collectives
    ride ICI links, not DCN), falling back to a plain reshape off-TPU.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    cfg = config.resolved(len(devices))
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            cfg.shape,
            devices=devices,
            allow_split_physical_axes=allow_split_physical_axes,
        )
    except Exception:
        dev_array = np.array(devices).reshape(cfg.shape)
    return Mesh(dev_array, AXES)


def data_parallel_degree(mesh) -> int:
    """The mesh's ``data`` axis size — the DP degree the elastic-resize
    loop (docs/ELASTIC.md) reasons in. Re-deriving a mesh for a resized
    world is just ``build_mesh`` over the new device set: every layout
    downstream (``logical_sharding``, ``zero1_shardings``) is a pure
    function of the mesh, so the new world's shardings need no state
    from the old one — the cross-degree checkpoint math lives in
    ``ckpt.local.union_covering_plan`` instead."""
    try:
        return int(dict(mesh.shape).get("data", 1) or 1)
    except Exception:
        return 1


def mesh_for_topology(accelerator: str, num_slices: int = 1, **axis_sizes):
    """Mesh sized from a named TPU topology (spec layer vocabulary),
    e.g. ``mesh_for_topology("v5p-16", tensor=4)``."""
    import jax

    from k8s_tpu.spec import topology as topo

    t = topo.parse(accelerator)
    n = t.chips * num_slices
    avail = len(jax.devices())
    if avail < n:
        raise ValueError(
            f"{accelerator}×{num_slices} wants {n} devices, runtime has {avail}"
        )
    cfg = MeshConfig(**axis_sizes)
    return build_mesh(cfg, devices=jax.devices()[:n])


def best_pow2_split(n: int, max_first: int) -> Tuple[int, int]:
    """Largest power-of-two ≤ max_first dividing n, and the cofactor."""
    first = 1
    while first * 2 <= max_first and n % (first * 2) == 0:
        first *= 2
    return first, n // first


# ---------------------------------------------------------------------------
# Latency-hiding scheduler (async collectives)
# ---------------------------------------------------------------------------

# XLA:TPU's latency-hiding scheduler turns the blocking collectives the
# SPMD partitioner emits (FSDP per-layer all-gathers, TP activation
# all-reduces, the gradient reduce-scatter) into async start/done pairs
# and schedules compute between them — the megascale recipe for hiding
# ICI/DCN time behind the MXU. These are the curated libtpu flags; they
# are read ONCE at TPU-backend init, hence the env-var route (the knob
# must be set before the first device query).
LATENCY_HIDING_LIBTPU_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_tpu_enable_data_parallel_all_reduce_opt=true",
    "--xla_tpu_data_parallel_opt_different_sized_ops=true",
)


def enable_latency_hiding(env=None) -> bool:
    """Turn on XLA's latency-hiding scheduler for this process's TPU
    backend by appending the flag set to ``LIBTPU_INIT_ARGS``.

    Idempotent; returns False (and changes nothing) when the jax
    backend is already initialized — libtpu has read the env var by
    then, so a late call would silently do nothing, which is worse than
    an honest refusal. Call it before the first device query (programs
    do this at startup under ``KTPU_LATENCY_HIDING=1``). Off-TPU the
    env var is ignored by every other backend — safe to set
    unconditionally in launch configs."""
    import os

    if env is None:
        env = os.environ
    current = env.get("LIBTPU_INIT_ARGS", "")
    missing = [f for f in LATENCY_HIDING_LIBTPU_FLAGS if f not in current]
    if not missing:
        return True
    try:
        import jax.extend.backend as _jeb  # noqa: F401

        import jax

        initialized = jax._src.xla_bridge._backends  # type: ignore[attr-defined]
        if initialized:
            return False
    except Exception:
        pass  # cannot introspect: set the env var anyway
    env["LIBTPU_INIT_ARGS"] = (current + " " + " ".join(missing)).strip()
    return True


def latency_hiding_compiler_options() -> dict:
    """The same scheduler knobs as per-compile XLA options — for AOT
    paths (``lowered.compile(compiler_options=...)``) where backend-init
    env vars are already too late. TPU compiles only; other backends
    reject the unknown flags."""
    return {
        f.lstrip("-").split("=")[0]: f.split("=")[1]
        for f in LATENCY_HIDING_LIBTPU_FLAGS
    }
