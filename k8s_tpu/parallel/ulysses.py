"""Ulysses-style all-to-all sequence parallelism.

The second long-context strategy next to ring attention (SURVEY §5:
the reference has no sequence parallelism of any kind; SURVEY §2.5 row
"SP/CP" names both ring and Ulysses/all-to-all as the TPU-native
capability to build). Where ring attention keeps Q local and rotates
KV around the ``seq`` ICI ring, Ulysses re-shards: activations arrive
sequence-sharded ``[B, S/n, H, D]``, one ``all_to_all`` over the
``seq`` axis turns them head-sharded ``[B, S, H/n, D]``, each device
runs ordinary (flash) attention over the FULL sequence for its head
subset, and a second ``all_to_all`` restores sequence sharding.

Trade-off vs ring (why both exist):

- Ulysses moves each activation tensor twice (2 all-to-alls of the
  local shard) regardless of sequence length — O(S·H·D/n) bytes —
  while ring moves K and V ``n-1`` times; for long S with small KV
  (GQA) ring wins, for moderate S and many heads Ulysses wins and
  composes with the unmodified flash kernel (full-sequence causal
  masking needs no cross-device bookkeeping).
- Ulysses parallelism degree is capped by the head counts: ``n`` must
  divide both Hq and Hkv. Ring has no head constraint.

Both run over the same ``seq`` mesh axis, so models can pick per-layer
via config (``attention="ulysses"`` in LlamaConfig).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax

from k8s_tpu.utils import axis_size_compat
from jax.sharding import Mesh

from k8s_tpu.ops.attention import flash_attention


def ulysses_attention_sharded(
    q: jax.Array,  # local [B, Sq/n, Hq, D]
    k: jax.Array,  # local [B, Sk/n, Hkv, D]
    v: jax.Array,
    segment_ids: Optional[jax.Array] = None,  # local [B, Sq/n]
    axis_name: str = "seq",
    causal: bool = True,
    scale: Optional[float] = None,
    use_pallas: Optional[bool] = None,
):
    """Per-device body — call inside ``shard_map`` (or use
    :func:`ulysses_attention` for the wrapped form). After the
    all-to-all each device holds the FULL sequence for its head
    subset, so packed/padded masking just needs the full segment row:
    one cheap int all-gather."""
    n = axis_size_compat(axis_name)
    hq, hkv = q.shape[2], k.shape[2]
    if hq % n or hkv % n:
        raise ValueError(
            f"ulysses degree {n} must divide q heads {hq} and kv heads {hkv}"
        )
    # seq-sharded -> head-sharded: split heads (axis 2), gather seq (axis 1)
    a2a = partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
        tiled=True,
    )
    qh, kh, vh = a2a(q), a2a(k), a2a(v)  # [B, S, H/n, D]
    seg_full = None
    if segment_ids is not None:
        seg_full = jax.lax.all_gather(
            segment_ids, axis_name, axis=1, tiled=True
        )  # [B, S]
    out = flash_attention(
        qh, kh, vh, causal=causal, scale=scale, use_pallas=use_pallas,
        segment_ids=seg_full,
    )
    # head-sharded -> seq-sharded: split seq (axis 1), gather heads (axis 2)
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q: jax.Array,  # global [B, S, Hq, D]
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    scale: Optional[float] = None,
    axis_name: str = "seq",
    batch_axes=("data", "fsdp"),
    head_axis: str = "tensor",
    use_pallas: Optional[bool] = None,
    segment_ids: Optional[jax.Array] = None,  # global [B, S]
):
    """Global-array form mirroring :func:`ring_attention`: length over
    ``seq``, batch over data/fsdp, heads over tensor — or whatever the
    ambient logical-rules scope maps those names to (the hand-off stays
    consistent with the model's boundary constraints by construction;
    see ``ring_attention._resolve_seq_parallel_axes``)."""
    from k8s_tpu.parallel.ring_attention import (
        _resolve_seq_parallel_axes,
        seq_parallel_call,
    )

    axis_name, batch_axes, head_axis = _resolve_seq_parallel_axes(
        axis_name, batch_axes, head_axis)

    body = partial(
        ulysses_attention_sharded,
        axis_name=axis_name,
        causal=causal,
        scale=scale,
        use_pallas=use_pallas,
    )
    return seq_parallel_call(
        body, mesh, axis_name=axis_name, batch_axes=batch_axes,
        head_axis=head_axis, segment_ids=segment_ids,
    )(q, k, v)
