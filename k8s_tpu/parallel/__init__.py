"""Parallelism library: mesh construction, sharding rules, collectives,
ring attention, pipeline stages.

This is the capability column of SURVEY §2.5: the reference scaled only
by adding PS/WORKER replicas over TF-gRPC; the TPU-native framework
scales by laying a logical mesh (data / fsdp / tensor / seq / expert /
stage axes) over ICI+DCN and letting XLA insert collectives.
"""

from k8s_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    build_mesh,
    data_parallel_degree,
    mesh_for_topology,
)
from k8s_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention,
    ulysses_attention_sharded,
)
from k8s_tpu.parallel.sharding import (  # noqa: F401
    LogicalRules,
    logical_constraint,
    logical_sharding,
    resolve_logical_axes,
    shard_init,
    with_sharding,
    zero1_partition_spec,
    zero1_sharding,
    zero1_shardings,
    zero3_param_shardings,
)
