"""Ring attention: sequence/context parallelism over an ICI ring.

New capability vs the reference (SURVEY §5: "long-context / sequence
parallelism entirely absent"). Sequence-sharded Q/K/V live on the
``seq`` mesh axis; each device computes blockwise attention of its
local queries against the KV chunk it currently holds while the chunks
rotate around the ring via ``jax.lax.ppermute`` — XLA overlaps the
ppermute with the local compute, so per-step communication hides
behind the matmuls (the RingAttention/blockwise-parallel formulation).

Online-softmax accumulation keeps the math exact: running max ``m``,
normalizer ``l`` and unnormalized accumulator in f32, renormalized once
at the end. Causal masking is block-granular on global positions, so
chunks entirely in the future contribute nothing (their exp() terms
vanish against the running max).

Differentiable by construction (scan + ppermute autodiff); a fused
pallas ring kernel with RDMA double-buffering is the round-2 upgrade
path (pallas guide "Ring Collectives" pattern).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def ring_attention_sharded(
    q: jax.Array,  # local [B, Sq_local, Hq, D]
    k: jax.Array,  # local [B, Sk_local, Hkv, D]
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = True,
    scale: Optional[float] = None,
):
    """Per-device body — call inside ``shard_map`` (or use
    :func:`ring_attention` for the wrapped form)."""
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    groups = hq // hkv
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)

    qf = (q.astype(jnp.float32) * scale).reshape(b, sq, hkv, groups, d)
    q_pos = my * sq + jnp.arange(sq)  # global query positions

    def step_fn(carry, step):
        m, l, acc, k_cur, v_cur = carry
        src = (my - step) % n  # who this KV chunk belongs to
        s = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qf, k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )  # [B, Hkv, G, Sq, Sk]
        if causal:
            k_pos = src * sk + jnp.arange(sk)
            mask = q_pos[:, None] >= k_pos[None, :]  # [Sq, Sk]
            s = jnp.where(mask[None, None, None], s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)  # [B,Hkv,G,Sq]
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum(
            "bhgqk,bkhd->bhgqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * corr[..., None] + pv
        # rotate KV to the next neighbor (ring over ICI)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_next, v_next), None

    m0 = jnp.full((b, hkv, groups, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, groups, sq), jnp.float32)
    acc0 = jnp.zeros((b, hkv, groups, sq, d), jnp.float32)
    (m, l, acc, _, _), _ = jax.lax.scan(
        step_fn, (m0, l0, acc0, k, v), jnp.arange(n)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B,Hkv,G,Sq,D]
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, sq, hq, d)
    return out.astype(q.dtype)


def seq_parallel_call(
    body,
    mesh: Mesh,
    axis_name: str = "seq",
    batch_axes=("data", "fsdp"),
    head_axis: str = "tensor",
):
    """Shared shard_map wrapper for sequence-parallel attention bodies
    (ring and Ulysses): q/k/v and the output are laid out
    ``[batch@data/fsdp, length@seq, heads@tensor, head_dim]``."""
    from jax import shard_map

    spec = P(batch_axes, axis_name, head_axis, None)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )


def ring_attention(
    q: jax.Array,  # global [B, S, Hq, D]
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    scale: Optional[float] = None,
    axis_name: str = "seq",
    batch_axes=("data", "fsdp"),
    head_axis: str = "tensor",
):
    """Global-array form: shards length over ``seq``, batch over
    data/fsdp, heads over tensor, and runs the ring body."""
    body = partial(
        ring_attention_sharded, axis_name=axis_name, causal=causal, scale=scale
    )
    return seq_parallel_call(
        body, mesh, axis_name=axis_name, batch_axes=batch_axes,
        head_axis=head_axis,
    )(q, k, v)
