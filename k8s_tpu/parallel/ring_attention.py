"""Ring attention: sequence/context parallelism over an ICI ring.

New capability vs the reference (SURVEY §5: "long-context / sequence
parallelism entirely absent"). Sequence-sharded Q/K/V live on the
``seq`` mesh axis; each device computes blockwise attention of its
local queries against the KV chunk it currently holds while the chunks
rotate around the ring via ``jax.lax.ppermute`` — XLA overlaps the
ppermute with the local compute, so per-step communication hides
behind the matmuls (the RingAttention/blockwise-parallel formulation).

Online-softmax accumulation keeps the math exact: running max ``m``,
normalizer ``l`` and unnormalized accumulator in f32, renormalized once
at the end. Causal masking is block-granular on global positions, so
chunks entirely in the future contribute nothing (their exp() terms
vanish against the running max).

Two interchangeable per-device bodies:

- :func:`ring_attention_sharded` — XLA einsum blockwise attention,
  differentiable by construction (scan + ppermute autodiff). Runs
  anywhere; materializes local [Sq_local, Sk_local] score blocks.
- :func:`ring_flash_attention_sharded` — each ring step runs the
  pallas flash kernels (`ops.attention`) on the resident KV chunk and
  the per-chunk outputs are merged exactly in log space via the
  kernels' saved logsumexp. The backward is a hand-written ring pass
  under ``jax.custom_vjp``: dq accumulates locally while dk/dv partials
  ride around the ring with their KV chunk and arrive home after a full
  cycle — per-block P is recomputed from the *global* lse, so gradients
  are exact, never materializing S² on any device.

An RDMA double-buffered fused kernel (pallas guide "Ring Collectives")
remains the next upgrade once multi-chip hardware is available to
validate it.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax

from k8s_tpu.utils import axis_size_compat
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from k8s_tpu.ops.attention import (
    _flash_backward,
    _flash_forward,
    compute_dd,
    int_zero_cotangent,
    resolve_blocks,
    resolve_bwd_blocks,
)

NEG_INF = -1e30


def ring_attention_sharded(
    q: jax.Array,  # local [B, Sq_local, Hq, D]
    k: jax.Array,  # local [B, Sk_local, Hkv, D]
    v: jax.Array,
    segment_ids: Optional[jax.Array] = None,  # local [B, Sq_local]
    axis_name: str = "seq",
    causal: bool = True,
    scale: Optional[float] = None,
):
    """Per-device body — call inside ``shard_map`` (or use
    :func:`ring_attention` for the wrapped form). ``segment_ids``
    chunks rotate around the ring alongside their KV chunk, masking
    cross-document attention exactly as the flash kernel does."""
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    groups = hq // hkv
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    n = axis_size_compat(axis_name)
    my = jax.lax.axis_index(axis_name)

    qf = (q.astype(jnp.float32) * scale).reshape(b, sq, hkv, groups, d)
    q_pos = my * sq + jnp.arange(sq)  # global query positions
    seg_q = segment_ids

    def step_fn(carry, step):
        m, l, acc, k_cur, v_cur, seg_cur = carry
        src = (my - step) % n  # who this KV chunk belongs to
        s = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qf, k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )  # [B, Hkv, G, Sq, Sk]
        if causal:
            k_pos = src * sk + jnp.arange(sk)
            mask = q_pos[:, None] >= k_pos[None, :]  # [Sq, Sk]
            s = jnp.where(mask[None, None, None], s, NEG_INF)
        if seg_cur is not None:
            visible = seg_q[:, :, None] == seg_cur[:, None, :]  # [B,Sq,Sk]
            s = jnp.where(visible[:, None, None], s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)  # [B,Hkv,G,Sq]
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum(
            "bhgqk,bkhd->bhgqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * corr[..., None] + pv
        # rotate KV (and its segment ids) to the next neighbor (ICI ring)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        seg_next = (
            jax.lax.ppermute(seg_cur, axis_name, perm)
            if seg_cur is not None else None
        )
        return (m_new, l_new, acc_new, k_next, v_next, seg_next), None

    m0 = jnp.full((b, hkv, groups, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, groups, sq), jnp.float32)
    acc0 = jnp.zeros((b, hkv, groups, sq, d), jnp.float32)
    (m, l, acc, _, _, _), _ = jax.lax.scan(
        step_fn, (m0, l0, acc0, k, v, segment_ids), jnp.arange(n)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B,Hkv,G,Sq,D]
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, sq, hq, d)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas-flash ring body
# ---------------------------------------------------------------------------


def _lse_to_out_layout(lse: jax.Array, b: int, hq: int, sq: int) -> jax.Array:
    """[B*H, 1, Sq] kernel row layout → [B, Sq, Hq, 1] broadcastable
    against the [B, Sq, Hq, D] output."""
    return lse.reshape(b, hq, sq).transpose(0, 2, 1)[..., None]


def _merge_partial(out_acc, lse_acc, out_i, lse_i):
    """Exact log-space merge of two self-normalized attention partials.

    out_* are [B, Sq, Hq, D] f32 normalized by their own lse_*
    ([B*H, 1, Sq] f32); an empty partial is (0, NEG_INF) and drops out
    of the merge since exp(NEG_INF - lse_new) == 0.
    """
    b, sq, hq, _ = out_acc.shape
    m = jnp.maximum(lse_acc, lse_i)
    lse_new = m + jnp.log(jnp.exp(lse_acc - m) + jnp.exp(lse_i - m))
    w_acc = jnp.exp(_lse_to_out_layout(lse_acc - lse_new, b, hq, sq))
    w_i = jnp.exp(_lse_to_out_layout(lse_i - lse_new, b, hq, sq))
    return out_acc * w_acc + out_i * w_i, lse_new


def _rotate(x, axis_name: str):
    n = axis_size_compat(axis_name)
    return jax.lax.ppermute(x, axis_name, [(i, (i + 1) % n) for i in range(n)])


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _ring_flash(q, k, v, seg, axis_name, causal, scale, block_q, block_k,
                interpret):
    out, _ = _ring_flash_fwd(
        q, k, v, seg, axis_name, causal, scale, block_q, block_k, interpret
    )
    return out


def _ring_flash_fwd(
    q, k, v, seg, axis_name, causal, scale, block_q, block_k, interpret
):
    b, sq, hq, d = q.shape
    n = axis_size_compat(axis_name)
    # only the causal mask needs the device's ring position; an unused
    # axis_index leaves a dangling partition-id op that the SPMD
    # partitioner rejects on jax 0.4.x
    my = jax.lax.axis_index(axis_name) if causal else None
    with_seg = seg is not None

    def block_fwd(k_blk, v_blk, seg_blk, blk_causal):
        # out_f32: partials stay f32 through the log-space merge; the
        # single cast to q.dtype happens after the last ring step.
        # seg (local q row) vs seg_blk (the resident KV chunk's row):
        # the kernels mask on both sides, so packed documents compose
        # with the ring exactly as on a single device
        return _flash_forward(
            q, k_blk, v_blk, blk_causal, scale, block_q, block_k, interpret,
            with_residuals=True, out_f32=True,
            segment_ids=seg, segment_ids_kv=seg_blk if with_seg else None,
        )

    # step 0: the diagonal chunk (kv home) — statically causal; the
    # kv-side segment row IS the local row here
    out_acc, lse_acc = block_fwd(k, v, seg, causal)

    def step_fn(carry, step):
        out_acc, lse_acc, k_cur, v_cur, seg_cur = carry
        k_cur = _rotate(k_cur, axis_name)
        v_cur = _rotate(v_cur, axis_name)
        seg_cur = _rotate(seg_cur, axis_name) if with_seg else seg_cur
        if causal:
            src = (my - step) % n  # owner of the chunk now resident
            # past chunks attend fully; future chunks contribute nothing
            out_i, lse_i = jax.lax.cond(
                src < my,
                lambda: block_fwd(k_cur, v_cur, seg_cur, False),
                lambda: (
                    jnp.zeros((b, sq, hq, d), jnp.float32),
                    jnp.full((b * hq, 1, sq), NEG_INF, jnp.float32),
                ),
            )
        else:
            out_i, lse_i = block_fwd(k_cur, v_cur, seg_cur, False)
        out_acc, lse_acc = _merge_partial(out_acc, lse_acc, out_i, lse_i)
        return (out_acc, lse_acc, k_cur, v_cur, seg_cur), None

    if n > 1:
        (out_acc, lse_acc, _, _, _), _ = jax.lax.scan(
            step_fn,
            (out_acc, lse_acc, k, v, seg if with_seg else jnp.zeros((), jnp.int32)),
            jnp.arange(1, n),
        )
    out = out_acc.astype(q.dtype)
    return out, (q, k, v, seg, out, lse_acc)


def _ring_flash_bwd(
    axis_name, causal, scale, block_q, block_k, interpret, res, g
):
    q, k, v, seg, out, lse = res
    b, sq, hq, d = q.shape
    n = axis_size_compat(axis_name)
    # see _ring_flash_fwd: axis_index only when the causal mask uses it
    my = jax.lax.axis_index(axis_name) if causal else None
    with_seg = seg is not None
    dd = compute_dd(out, g)  # GLOBAL rowsum(dO*O) — not per-chunk

    def block_bwd(k_blk, v_blk, seg_blk, blk_causal):
        # per-block P recomputed from the global lse → exact global grads
        # same bwd-block resolution (incl. tuning overrides) as the
        # single-device path, against the LOCAL per-shard lengths
        bwd_bq, bwd_bk = resolve_bwd_blocks(
            q.shape[1], block_q, block_k, sk=k_blk.shape[1]
        )
        return _flash_backward(
            q, k_blk, v_blk, dd, lse, g, blk_causal, scale, bwd_bq, bwd_bk,
            interpret, grads_f32=True,
            segment_ids=seg, segment_ids_kv=seg_blk if with_seg else None,
        )

    # step 0: diagonal chunk; its dk/dv partials start the ring ride
    dq_acc, dk_cur, dv_cur = block_bwd(k, v, seg, causal)

    def step_fn(carry, step):
        dq_acc, k_cur, v_cur, seg_cur, dk_cur, dv_cur = carry
        k_cur = _rotate(k_cur, axis_name)
        v_cur = _rotate(v_cur, axis_name)
        seg_cur = _rotate(seg_cur, axis_name) if with_seg else seg_cur
        dk_cur = _rotate(dk_cur, axis_name)
        dv_cur = _rotate(dv_cur, axis_name)

        def compute():
            dq_i, dk_i, dv_i = block_bwd(k_cur, v_cur, seg_cur, False)
            return dq_acc + dq_i, dk_cur + dk_i, dv_cur + dv_i

        if causal:
            src = (my - step) % n
            dq_acc, dk_cur, dv_cur = jax.lax.cond(
                src < my, compute, lambda: (dq_acc, dk_cur, dv_cur)
            )
        else:
            dq_acc, dk_cur, dv_cur = compute()
        return (dq_acc, k_cur, v_cur, seg_cur, dk_cur, dv_cur), None

    if n > 1:
        (dq_acc, _, _, _, dk_cur, dv_cur), _ = jax.lax.scan(
            step_fn,
            (dq_acc, k, v, seg if with_seg else jnp.zeros((), jnp.int32),
             dk_cur, dv_cur),
            jnp.arange(1, n),
        )
        # chunks have rotated n-1 times; one more brings dk/dv home
        dk_cur = _rotate(dk_cur, axis_name)
        dv_cur = _rotate(dv_cur, axis_name)
    dseg = int_zero_cotangent(seg) if with_seg else None
    return (
        dq_acc.astype(q.dtype),
        dk_cur.astype(k.dtype),
        dv_cur.astype(v.dtype),
        dseg,
    )


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention_sharded(
    q: jax.Array,  # local [B, Sq_local, Hq, D]
    k: jax.Array,  # local [B, Sk_local, Hkv, D]
    v: jax.Array,
    segment_ids: Optional[jax.Array] = None,  # local [B, Sq_local]
    axis_name: str = "seq",
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
):
    """Per-device flash ring body — call inside ``shard_map``.

    Causal masking assumes equal-size chunks laid out contiguously over
    the ring (chunk r holds global positions [r*S_local, (r+1)*S_local))
    with q and kv sharded identically, so the diagonal chunk is exactly
    local causal self-attention. ``segment_ids`` chunks (packed/padded
    rows) rotate around the ring with their KV chunk; the kernels mask
    q-side vs kv-side rows independently, so cross-document attention
    is masked exactly as on a single device.
    """
    if q.shape[1] != k.shape[1]:
        raise ValueError(
            f"ring flash needs equal q/kv chunk sizes, got {q.shape[1]} "
            f"vs {k.shape[1]}"
        )
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    # seq-dependent block defaults against the LOCAL shard length
    block_q, block_k = resolve_blocks(q.shape[1], block_q, block_k)
    return _ring_flash(
        q, k, v, segment_ids, axis_name, causal, scale, block_q, block_k,
        interpret,
    )


def _resolve_seq_parallel_axes(axis_name, batch_axes, head_axis):
    """Consistent logical rules at the shard_map hand-off: when a
    ``nn.logical_axis_rules`` scope is active, derive the ring/Ulysses
    boundary layout (batch/length/heads mesh axes) from the SAME rules
    table the model's boundary constraints resolve against — a rules
    change then moves both sides together instead of the hardcoded
    defaults silently diverging and forcing a reshard (or an
    involuntary-remat fallback) at the hand-off. Without a rules scope
    the defaults stand (the manual-caller contract)."""
    from k8s_tpu.parallel.sharding import resolve_logical_axes

    spec = resolve_logical_axes(("batch", "length", "heads"))
    if spec is None:
        return axis_name, batch_axes, head_axis
    b_ax, l_ax, h_ax = tuple(spec)
    if b_ax is not None:
        batch_axes = b_ax
    if isinstance(l_ax, str):
        axis_name = l_ax
    if isinstance(h_ax, str):
        head_axis = h_ax
    return axis_name, batch_axes, head_axis


def seq_parallel_call(
    body,
    mesh: Mesh,
    axis_name: str = "seq",
    batch_axes=("data", "fsdp"),
    head_axis: str = "tensor",
    segment_ids: Optional[jax.Array] = None,  # global [B, S]
):
    """Shared shard_map wrapper for sequence-parallel attention bodies
    (ring and Ulysses): q/k/v and the output are laid out
    ``[batch@data/fsdp, length@seq, heads@tensor, head_dim]``. With
    ``segment_ids`` the body takes them as a 4th arg, sharded
    ``[batch@data/fsdp, length@seq]``; returns the ready-to-call
    closure over (q, k, v)."""
    from k8s_tpu.utils import shard_map_compat

    spec = P(batch_axes, axis_name, head_axis, None)
    seg_spec = P(batch_axes, axis_name)
    with_segments = segment_ids is not None
    in_specs = (spec, spec, spec) + ((seg_spec,) if with_segments else ())
    wrapped = shard_map_compat(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=spec,
        check_vma=False,
    )
    if with_segments:
        seg = segment_ids.astype(jnp.int32)
        return lambda q, k, v: wrapped(q, k, v, seg)
    return wrapped


def ring_attention(
    q: jax.Array,  # global [B, S, Hq, D]
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    scale: Optional[float] = None,
    axis_name: str = "seq",
    batch_axes=("data", "fsdp"),
    head_axis: str = "tensor",
    impl: Optional[str] = None,  # "flash" | "xla" | None = auto
    interpret: bool = False,
    segment_ids: Optional[jax.Array] = None,  # global [B, S]
):
    """Global-array form: shards length over ``seq``, batch over
    data/fsdp, heads over tensor, and runs the ring body.

    ``impl=None`` auto-selects the pallas-flash body on TPU when the
    local chunk is lane-aligned, the XLA einsum body otherwise.
    ``segment_ids`` (packed/padded batches) work on both bodies: the
    flash kernels take separate q-side/kv-side rows, so segment chunks
    rotate around the ring with their KV chunk.
    """
    axis_name, batch_axes, head_axis = _resolve_seq_parallel_axes(
        axis_name, batch_axes, head_axis)
    if impl is None:
        d = q.shape[-1]
        n = mesh.shape[axis_name]
        local = q.shape[1] // max(n, 1)
        flash_ok = (
            q.shape[1] == k.shape[1] and d % 128 == 0 and local % 128 == 0
        )
        # the mesh's devices decide, not the default backend — they can
        # differ (e.g. a CPU mesh on a TPU-backed host in dryruns)
        on_tpu = mesh.devices.flat[0].platform == "tpu"
        impl = "flash" if (flash_ok and (on_tpu or interpret)) else "xla"
    if impl == "flash":
        body = partial(
            ring_flash_attention_sharded, axis_name=axis_name, causal=causal,
            scale=scale, interpret=interpret,
        )
    elif impl == "xla":
        body = partial(
            ring_attention_sharded, axis_name=axis_name, causal=causal,
            scale=scale,
        )
    else:
        raise ValueError(f"unknown ring attention impl {impl!r}")
    return seq_parallel_call(
        body, mesh, axis_name=axis_name, batch_axes=batch_axes,
        head_axis=head_axis, segment_ids=segment_ids,
    )(q, k, v)
