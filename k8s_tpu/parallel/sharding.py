"""Logical-axis sharding rules.

Models annotate arrays with *logical* axis names ("batch", "embed",
"mlp", "heads", "kv", "length", "vocab", "expert", "layers"); a rules
table maps logical names to mesh axes. Changing the parallelism
strategy = changing the rules table, not the model — the pjit idiom
that replaces the reference's PS/worker device placement
(``tf.train.replica_device_setter``).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes = Union[None, str, Tuple[str, ...]]


class LogicalRules:
    """Ordered logical-name → mesh-axes mapping."""

    # Standard strategy presets. ("fsdp" shards both batch and params;
    # "tensor" cuts heads/mlp; "seq" cuts sequence length.)
    DP = (
        ("batch", ("data", "fsdp")),
        ("length", None),
    )
    # ZeRO layout note: flax applies the rules table in ORDER, and a
    # mesh axis consumed earlier in an array's spec is skipped later —
    # so "embed"-only sharding puts down_proj [mlp, embed] and o_proj
    # [heads, head_dim, embed] shards on their LAST dim. The TPU
    # backend's reduce-scatter emitter only scatters major dims
    # (sharding_type 2nd-minor in the HLO collective config), so those
    # two gradients compiled to full-size all-reduce — 2x the bytes —
    # while q/k/v/gate/up reduce-scattered (verified via the v5p-128
    # AOT compile, docs/BENCHMARKS.md AOT table). The output
    # projections carry dedicated logical names ("mlp_down",
    # "heads_out", models/llama.py) listed BEFORE "embed" here, so
    # their dim-0 wins the fsdp axis and every projection gradient
    # reduce-scatters. TP tables map the same names to "tensor",
    # preserving the megatron row-parallel layout.
    FSDP = (
        ("batch", ("data", "fsdp")),
        ("mlp_down", "fsdp"),
        ("heads_out", "fsdp"),
        ("embed", "fsdp"),
        ("length", None),
    )
    TP = (
        ("batch", ("data", "fsdp")),
        ("heads", "tensor"),
        ("heads_out", "tensor"),
        ("kv_heads", "tensor"),
        ("mlp", "tensor"),
        ("mlp_down", "tensor"),
        ("vocab", "tensor"),
        ("length", None),
    )
    FSDP_TP = (
        ("batch", ("data", "fsdp")),
        ("embed", "fsdp"),
        ("heads", "tensor"),
        ("heads_out", "tensor"),
        ("kv_heads", "tensor"),
        ("mlp", "tensor"),
        ("mlp_down", "tensor"),
        ("vocab", "tensor"),
        ("length", None),
    )
    FSDP_TP_SP = (
        ("batch", ("data", "fsdp")),
        ("embed", "fsdp"),
        ("heads", "tensor"),
        ("heads_out", "tensor"),
        ("kv_heads", "tensor"),
        ("mlp", "tensor"),
        ("mlp_down", "tensor"),
        ("vocab", "tensor"),
        ("length", "seq"),
    )
    # Pipeline parallelism: the scan-stacked layer axis ("layers", the
    # flax PARTITION_NAME of the block scan) shards over `stage`, so
    # each pipeline stage holds a contiguous [L/S, ...] slab of layer
    # params — exactly the shard_map in_spec the GPipe schedule wants
    # (k8s_tpu.parallel.pipeline). PP_FSDP additionally fsdp-shards the
    # embed dims; the stage body all-gathers them per layer (manual
    # ZeRO-3 — XLA can't insert those collectives inside shard_map).
    PP = (
        ("batch", ("data", "fsdp")),
        ("layers", "stage"),
        ("length", None),
    )
    PP_FSDP = (
        ("batch", ("data", "fsdp")),
        ("layers", "stage"),
        ("mlp_down", "fsdp"),
        ("heads_out", "fsdp"),
        ("embed", "fsdp"),
        ("length", None),
    )
    MOE = (
        ("batch", ("data", "fsdp")),
        ("embed", "fsdp"),
        ("expert", "expert"),
        ("heads", "tensor"),
        ("heads_out", "tensor"),
        ("kv_heads", "tensor"),
        ("mlp", "tensor"),
        ("mlp_down", "tensor"),
        ("vocab", "tensor"),
        ("length", "seq"),
    )

    def __init__(self, rules: Sequence[Tuple[str, MeshAxes]]):
        self._rules: Dict[str, MeshAxes] = dict(rules)

    def spec(self, logical_axes: Sequence[Optional[str]]) -> P:
        return P(*[self._rules.get(a) if a else None for a in logical_axes])

    def to_flax(self) -> Tuple[Tuple[str, MeshAxes], ...]:
        """Rules in the shape flax.linen.spmd expects (plus the scan
        layer axis, always replicated)."""
        base = tuple(self._rules.items())
        if "layers" not in self._rules:
            base = base + (("layers", None),)
        if "head_dim" not in self._rules:
            base = base + (("head_dim", None),)
        return base

    def extend(self, rules: Sequence[Tuple[str, MeshAxes]]) -> "LogicalRules":
        merged = dict(self._rules)
        merged.update(dict(rules))
        return LogicalRules(tuple(merged.items()))

    def __getitem__(self, name: str) -> MeshAxes:
        return self._rules.get(name)


def logical_sharding(
    mesh: Mesh, rules: LogicalRules, logical_axes: Sequence[Optional[str]]
) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(logical_axes))


def with_sharding(mesh: Mesh, rules: LogicalRules, x, logical_axes):
    """In-jit sharding constraint by logical names."""
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, rules, logical_axes)
    )


def shard_init(mesh: Mesh, rules: LogicalRules, init_fn, annotations):
    """Eval-shape ``init_fn`` and produce NamedShardings for its pytree.

    ``annotations`` maps pytree paths (joined by '/') to logical-axes
    tuples; unmatched leaves are replicated. Returns (shardings pytree
    shaped like the params, abstract shapes)."""
    abstract = jax.eval_shape(init_fn)

    def path_str(path) -> str:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            else:
                parts.append(str(p))
        return "/".join(parts)

    def leaf_sharding(path, leaf):
        axes = annotations.get(path_str(path))
        if axes is None:
            return NamedSharding(mesh, P())
        return logical_sharding(mesh, rules, axes)

    shardings = jax.tree_util.tree_map_with_path(leaf_sharding, abstract)
    return shardings, abstract
