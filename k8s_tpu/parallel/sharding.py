"""Logical-axis sharding rules.

Models annotate arrays with *logical* axis names ("batch", "embed",
"mlp", "heads", "kv", "length", "vocab", "expert", "layers"); a rules
table maps logical names to mesh axes. Changing the parallelism
strategy = changing the rules table, not the model — the pjit idiom
that replaces the reference's PS/worker device placement
(``tf.train.replica_device_setter``).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes = Union[None, str, Tuple[str, ...]]


class LogicalRules:
    """Ordered logical-name → mesh-axes mapping."""

    # Standard strategy presets. ("fsdp" shards both batch and params;
    # "tensor" cuts heads/mlp; "seq" cuts sequence length.)
    DP = (
        ("batch", ("data", "fsdp")),
        ("length", None),
    )
    # ZeRO layout note: flax applies the rules table in ORDER, and a
    # mesh axis consumed earlier in an array's spec is skipped later —
    # so "embed"-only sharding puts down_proj [mlp, embed] and o_proj
    # [heads, head_dim, embed] shards on their LAST dim. The TPU
    # backend's reduce-scatter emitter only scatters major dims
    # (sharding_type 2nd-minor in the HLO collective config), so those
    # two gradients compiled to full-size all-reduce — 2x the bytes —
    # while q/k/v/gate/up reduce-scattered (verified via the v5p-128
    # AOT compile, docs/BENCHMARKS.md AOT table). The output
    # projections carry dedicated logical names ("mlp_down",
    # "heads_out", models/llama.py) listed BEFORE "embed" here, so
    # their dim-0 wins the fsdp axis and every projection gradient
    # reduce-scatters. TP tables map the same names to "tensor",
    # preserving the megatron row-parallel layout.
    FSDP = (
        ("batch", ("data", "fsdp")),
        ("mlp_down", "fsdp"),
        ("heads_out", "fsdp"),
        ("embed", "fsdp"),
        ("length", None),
    )
    TP = (
        ("batch", ("data", "fsdp")),
        ("heads", "tensor"),
        ("heads_out", "tensor"),
        ("kv_heads", "tensor"),
        ("mlp", "tensor"),
        ("mlp_down", "tensor"),
        ("vocab", "tensor"),
        ("length", None),
    )
    FSDP_TP = (
        ("batch", ("data", "fsdp")),
        ("embed", "fsdp"),
        ("heads", "tensor"),
        ("heads_out", "tensor"),
        ("kv_heads", "tensor"),
        ("mlp", "tensor"),
        ("mlp_down", "tensor"),
        ("vocab", "tensor"),
        ("length", None),
    )
    FSDP_TP_SP = (
        ("batch", ("data", "fsdp")),
        ("embed", "fsdp"),
        ("heads", "tensor"),
        ("heads_out", "tensor"),
        ("kv_heads", "tensor"),
        ("mlp", "tensor"),
        ("mlp_down", "tensor"),
        ("vocab", "tensor"),
        ("length", "seq"),
    )
    # Pipeline parallelism: the scan-stacked layer axis ("layers", the
    # flax PARTITION_NAME of the block scan) shards over `stage`, so
    # each pipeline stage holds a contiguous [L/S, ...] slab of layer
    # params — exactly the shard_map in_spec the GPipe schedule wants
    # (k8s_tpu.parallel.pipeline). PP_FSDP additionally fsdp-shards the
    # embed dims; the stage body all-gathers them per layer (manual
    # ZeRO-3 — XLA can't insert those collectives inside shard_map).
    PP = (
        ("batch", ("data", "fsdp")),
        ("layers", "stage"),
        ("length", None),
    )
    PP_FSDP = (
        ("batch", ("data", "fsdp")),
        ("layers", "stage"),
        ("mlp_down", "fsdp"),
        ("heads_out", "fsdp"),
        ("embed", "fsdp"),
        ("length", None),
    )
    MOE = (
        ("batch", ("data", "fsdp")),
        ("embed", "fsdp"),
        ("expert", "expert"),
        ("heads", "tensor"),
        ("heads_out", "tensor"),
        ("kv_heads", "tensor"),
        ("mlp", "tensor"),
        ("mlp_down", "tensor"),
        ("vocab", "tensor"),
        ("length", "seq"),
    )

    def __init__(self, rules: Sequence[Tuple[str, MeshAxes]]):
        self._rules: Dict[str, MeshAxes] = dict(rules)

    def spec(self, logical_axes: Sequence[Optional[str]]) -> P:
        return P(*[self._rules.get(a) if a else None for a in logical_axes])

    def to_flax(self) -> Tuple[Tuple[str, MeshAxes], ...]:
        """Rules in the shape flax.linen.spmd expects (plus the scan
        layer axis, always replicated)."""
        base = tuple(self._rules.items())
        if "layers" not in self._rules:
            base = base + (("layers", None),)
        if "head_dim" not in self._rules:
            base = base + (("head_dim", None),)
        return base

    def extend(self, rules: Sequence[Tuple[str, MeshAxes]]) -> "LogicalRules":
        merged = dict(self._rules)
        merged.update(dict(rules))
        return LogicalRules(tuple(merged.items()))

    def __getitem__(self, name: str) -> MeshAxes:
        return self._rules.get(name)


def logical_sharding(
    mesh: Mesh, rules: LogicalRules, logical_axes: Sequence[Optional[str]]
) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(logical_axes))


def resolve_logical_axes(logical_axes: Sequence[Optional[str]]) -> Optional[P]:
    """Resolve logical axis names against the AMBIENT flax rules scope
    (``nn.logical_axis_rules``) into a PartitionSpec, with flax's exact
    once-per-spec mesh-axis semantics (a mesh axis consumed by an
    earlier rule is skipped later — the ZeRO layout trick the FSDP
    table relies on). Returns None when no rules are in scope."""
    from flax.linen import spmd as _spmd

    rules = _spmd._axis_rules.rules
    if not rules:
        return None
    axes = _spmd._logical_to_mesh_axes(tuple(logical_axes), rules)
    if axes is None:
        return None
    # unmatched names fall back to unsharded (flax AXIS_IS_UNSHARDED)
    clean = [a if isinstance(a, (str, tuple)) or a is None else None
             for a in axes]
    return P(*clean)


def logical_constraint(x, logical_axes: Sequence[Optional[str]],
                       mesh: Optional[Mesh] = None):
    """``nn.with_logical_constraint`` that is NOT a silent no-op on CPU.

    flax's helper short-circuits whenever ``jax.devices()[0]`` is a CPU
    — which is exactly where the multichip dryruns and the virtual-mesh
    test harness compile, so every in-model boundary annotation
    vanished there and GSPMD had to re-derive activation layouts from
    the params alone: the source of the "Involuntary full
    rematerialization" spew in MULTICHIP_r05. With an explicit ``mesh``
    this resolves the ambient logical-rules scope and applies a real
    ``NamedSharding`` constraint on every backend; with ``mesh=None``
    it defers to flax (the single-chip / no-mesh case, where there is
    nothing to constrain anyway)."""
    import flax.linen as _nn

    if mesh is None:
        return _nn.with_logical_constraint(x, tuple(logical_axes))
    spec = resolve_logical_axes(logical_axes)
    if spec is None:  # no rules scope (e.g. inside a manual shard_map)
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def with_sharding(mesh: Mesh, rules: LogicalRules, x, logical_axes):
    """In-jit sharding constraint by logical names."""
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, rules, logical_axes)
    )


def sharded_embedding_lookup(table, ids, mesh: Optional[Mesh],
                             dtype=None):
    """Embedding lookup with explicit boundary shardings: gather the
    table's sharded embed dim AT USE (ZeRO-style use-site gather of the
    small ``[V, E]`` tensor) so the take partitions over the indices'
    batch/length sharding. Left to propagation, the gather output
    inherits the TABLE's embed sharding and GSPMD falls back to
    involuntary full rematerialization (replicate-then-partition) of
    the ``[B, S, E]`` activations — forward and again in the
    scatter-add transpose (the MULTICHIP_r05 ``jvp(_take)`` spew).
    Shared by the model forward and the pipeline apply path so the two
    lookups cannot drift."""
    import jax.numpy as jnp

    table = logical_constraint(table, ("vocab", None), mesh)
    if dtype is not None:
        table = table.astype(dtype)
    x = jnp.take(table, ids, axis=0)  # [B, S, E]
    return logical_constraint(x, ("batch", "length", "embed"), mesh)


# ---------------------------------------------------------------------------
# ZeRO-1: optimizer-state / weight-update sharding across the DP axis
# ---------------------------------------------------------------------------

ZERO1_AXIS = "data"


def zero1_partition_spec(
    spec: P, shape: Sequence[int], mesh: Mesh, axis: str = ZERO1_AXIS
) -> Optional[P]:
    """The leaf's PartitionSpec with the data-parallel mesh axis added
    to the first dimension whose per-shard size it divides — the ZeRO-1
    layout ("Automatic Cross-Replica Sharding of Weight Update in
    Data-Parallel Training", PAPERS.md): optimizer moments, the f32
    accum-grad carry, and the gradient reduce-scatter output all live
    1/DP per replica instead of fully replicated.

    Existing axes are preserved (FSDP params keep their ``fsdp`` dims —
    ZeRO-1 composes with them by appending ``data`` to the same dim or
    claiming a later one). Returns None when nothing can be sharded:
    ``axis`` missing or size 1 on this mesh, already consumed by the
    spec, a sub-matrix leaf, or no dimension divisible by the DP degree
    (odd-shaped leaves simply stay in their existing layout — ZeRO is
    best-effort per leaf, never a constraint violation).

    Elastic-resize contract (docs/ELASTIC.md): this derivation is a
    pure function of (leaf shape, mesh), so a resized gang simply
    re-runs it against the new world's mesh — a DP=2 checkpoint whose
    zero1 tiles no longer match the DP=1 template is rebuilt shard by
    shard from the union of peer manifests at restore time
    (``ckpt.local.union_covering_plan``), never by any layout state
    carried across the resize.

    Only rank >= 2 leaves shard: norm scales and biases are a rounding
    error of the moment bytes, and constraining their gradients
    propagates the 1-D data sharding backward through the broadcasts
    that consume them — GSPMD then involuntarily rematerializes the
    [B, S, E] activations (observed: 5 remat fallbacks on the llama
    stand-in) and the resharded reductions even perturb bf16 numerics.
    """
    dp = int(dict(mesh.shape).get(axis, 1))
    if dp <= 1 or len(shape) < 2:
        return None
    axes = list(spec) + [None] * (len(shape) - len(spec))
    used = set()
    for entry in axes:
        if isinstance(entry, str):
            used.add(entry)
        elif isinstance(entry, tuple):
            used.update(entry)
    if axis in used:
        return None
    sizes = dict(mesh.shape)
    for i, dim in enumerate(shape):
        entry = axes[i]
        names = (
            () if entry is None
            else (entry,) if isinstance(entry, str) else tuple(entry)
        )
        shard = dim
        for n in names:
            shard //= max(1, int(sizes.get(n, 1)))
        if shard and shard % dp == 0:
            axes[i] = (*names, axis) if names else axis
            return P(*axes)
    return None


def zero1_sharding(leaf, mesh: Mesh, axis: str = ZERO1_AXIS) -> NamedSharding:
    """The ZeRO-1 NamedSharding of one params-shaped leaf (a concrete
    array or ShapeDtypeStruct carrying ``.sharding``), falling back to
    the leaf's own layout when no dim divides the DP degree."""
    own = getattr(leaf, "sharding", None)
    own_spec = own.spec if isinstance(own, NamedSharding) else P()
    zspec = zero1_partition_spec(
        own_spec, tuple(getattr(leaf, "shape", ())), mesh, axis=axis
    )
    if zspec is None:
        return own if isinstance(own, NamedSharding) else NamedSharding(mesh, P())
    return NamedSharding(mesh, zspec)


def zero1_shardings(params, mesh: Mesh, axis: str = ZERO1_AXIS):
    """Params-shaped tree of ZeRO-1 NamedShardings — the layout the
    trainer pins gradients, optimizer state, and the accum-grad carry
    to when ``zero1=True`` (trainer_lib.make_train_step)."""
    return jax.tree_util.tree_map(
        lambda x: zero1_sharding(x, mesh, axis=axis), params
    )


def zero3_param_shardings(
    params,
    mesh: Mesh,
    min_leaf_size: int = 0,
    leaves: Optional[Sequence[str]] = None,
    axis: str = ZERO1_AXIS,
):
    """Selective ZeRO-3 layout: the zero1 partition applied to the
    params THEMSELVES, for the selected leaves only — a params-shaped
    tree of NamedShardings with None for every leaf left in place.

    Selection is deliberately coarse: a leaf is sharded when its
    '/'-joined tree path contains any substring in ``leaves``
    (``["embedding", "lm_head"]``), or when its element count is at
    least ``min_leaf_size`` (> 0). ZeRO-3 pays one just-in-time
    all-gather per sharded leaf per forward, so only the leaves that
    dominate param bytes (embedding / lm_head — a third of a small
    llama) are worth the traffic; the scanned transformer blocks stay
    in their rules layout. Leaves whose shape the DP degree cannot
    divide fall back to None (unselected) — same best-effort contract
    as :func:`zero1_partition_spec`.
    """
    sel = tuple(leaves or ())

    def path_str(path) -> str:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            else:
                parts.append(str(p))
        return "/".join(parts)

    def pick(path, x):
        p = path_str(path)
        chosen = any(s in p for s in sel) or (
            min_leaf_size and int(getattr(x, "size", 0)) >= int(min_leaf_size)
        )
        if not chosen:
            return None
        own = getattr(x, "sharding", None)
        own_spec = own.spec if isinstance(own, NamedSharding) else P()
        zspec = zero1_partition_spec(
            own_spec, tuple(getattr(x, "shape", ())), mesh, axis=axis
        )
        return NamedSharding(mesh, zspec) if zspec is not None else None

    return jax.tree_util.tree_map_with_path(pick, params)


def shard_init(mesh: Mesh, rules: LogicalRules, init_fn, annotations):
    """Eval-shape ``init_fn`` and produce NamedShardings for its pytree.

    ``annotations`` maps pytree paths (joined by '/') to logical-axes
    tuples; unmatched leaves are replicated. Returns (shardings pytree
    shaped like the params, abstract shapes)."""
    abstract = jax.eval_shape(init_fn)

    def path_str(path) -> str:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            else:
                parts.append(str(p))
        return "/".join(parts)

    def leaf_sharding(path, leaf):
        axes = annotations.get(path_str(path))
        if axes is None:
            return NamedSharding(mesh, P())
        return logical_sharding(mesh, rules, axes)

    shardings = jax.tree_util.tree_map_with_path(leaf_sharding, abstract)
    return shardings, abstract
