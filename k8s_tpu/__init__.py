"""k8s_tpu — a TPU-native distributed-training job framework.

A brand-new framework with the capabilities of the early ``tensorflow/k8s``
TfJob operator (reference: ``/root/reference``), re-designed TPU-first:

- **Control plane**: a CRD-style ``TpuJob`` spec + operator (controller,
  per-job reconciler, replica materializer, leader election, TensorBoard
  aux, exit-code retry policy) — the analogue of the reference's Go
  operator (``cmd/tf_operator``, ``pkg/controller``, ``pkg/trainer``,
  ``pkg/spec``).
- **Data plane**: JAX/XLA SPMD over `jax.sharding.Mesh` — DP / TP / FSDP /
  sequence(context) / expert / pipeline parallelism via ``pjit`` and
  ``shard_map`` with XLA collectives over ICI/DCN, replacing the
  reference's TensorFlow gRPC parameter-server ring
  (``grpc_tensorflow_server/grpc_tensorflow_server.py``).
- **Rendezvous contract**: the operator injects ``KTPU_COORDINATOR_ADDRESS``
  / ``KTPU_PROCESS_ID`` / ``KTPU_NUM_PROCESSES`` (+ megascale env for
  multi-slice) instead of ``TF_CONFIG`` (reference
  ``pkg/trainer/replicas.go:188-255``).
"""

from k8s_tpu.version import VERSION, GIT_SHA  # noqa: F401

__version__ = VERSION
