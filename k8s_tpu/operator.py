"""Operator process entrypoint.

Analogue of reference ``cmd/tf_operator/main.go``: flag parsing
(:48-54), YAML ControllerConfig loading (:68-84), the
``MY_POD_NAMESPACE``/``MY_POD_NAME`` env contract (:89-96), leader
election with 15s/5s/3s lease timing (:40-46,125-148), and the
restart-on-stale-watch run loop (:153-169). The ``--chaos-level`` flag
exists like the reference's (stubbed there, ``main.go:171-207``) but is
wired to the in-repo chaos monkey for local mode.

Local single-host mode (``--local``) additionally starts the in-process
kubelet with the subprocess executor, so ``python -m k8s_tpu.operator
--local`` is a fully working single-node control+data plane.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading

from k8s_tpu import version
from k8s_tpu.api.client import KubeClient, get_cluster_client
from k8s_tpu.api.crd_client import TpuJobClient
from k8s_tpu.api.election import LeaderElector
from k8s_tpu.controller.controller import Controller
from k8s_tpu.spec import ControllerConfig

log = logging.getLogger("k8s_tpu.operator")

LEASE_DURATION = 15.0  # reference main.go:42-44
RENEW_DEADLINE = 5.0
RETRY_PERIOD = 3.0


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="tpu-operator")
    p.add_argument("--controller-config-file", default="",
                   help="YAML ControllerConfig (accelerators map, launcher module)")
    p.add_argument("--chaos-level", type=int, default=-1,
                   help="chaos matrix profile: -1 disables, 0 gentle pod "
                        "kills, 1 aggressive pod kills, 2 + apiserver "
                        "flakes/watch drops/slow handlers, 3 + checkpoint "
                        "faults and lease loss (see docs/ROBUSTNESS.md)")
    p.add_argument("--chaos-interval", type=float, default=30.0,
                   help="seconds between chaos scheduling ticks")
    p.add_argument("--chaos-seed", type=int, default=None,
                   help="seed the chaos RNG for reproducible fault runs")
    p.add_argument("--gc-interval", type=float, default=600.0)
    p.add_argument("--health-port", type=int, default=8080,
                   help="liveness + /metrics listener; matches the chart's "
                        "livenessProbe. -1 disables, 0 = ephemeral port")
    p.add_argument("--namespace", default=None)
    p.add_argument("--kubeconfig", default=None,
                   help="kubeconfig path for out-of-cluster runs (reference "
                        "developer_guide.md local-run path); default: "
                        "KTPU_APISERVER_URL / KUBECONFIG env, in-cluster "
                        "serviceaccount, then local in-memory mode")
    p.add_argument("--local", action="store_true",
                   help="single-host mode: in-memory cluster + local kubelet")
    p.add_argument("--version", action="store_true")
    return p.parse_args(argv)


def load_config(path: str) -> ControllerConfig:
    if not path:
        return ControllerConfig()
    with open(path) as f:
        return ControllerConfig.from_yaml(f.read())


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s] %(message)s",
    )
    args = parse_args(argv)
    if args.version:
        print(f"tpu-operator {version.VERSION} (git {version.GIT_SHA})")
        return 0

    config = load_config(args.controller_config_file)

    # env contract (reference main.go:89-96)
    namespace = os.environ.get("MY_POD_NAMESPACE", "default" if args.local else "")
    name = os.environ.get("MY_POD_NAME", f"tpu-operator-{os.getpid()}" if args.local else "")
    if not namespace or not name:
        log.error("MY_POD_NAMESPACE and MY_POD_NAME must be set")
        return 1

    # --local forces the in-memory backend: the in-process kubelet hangs
    # off its synchronous hooks, which no remote apiserver can provide
    client = KubeClient() if args.local else get_cluster_client(args.kubeconfig)
    faulty = None
    if args.chaos_level >= 2:
        # levels >= 2 inject apiserver-facing faults, which ride on the
        # FaultyCluster wrapper — it must be in place before anything
        # (informer, kubelet, job client) binds to the backend
        from k8s_tpu.runtime.chaos import FaultyCluster

        faulty = FaultyCluster(client.cluster)
        client = KubeClient(faulty)
    job_client = TpuJobClient(client.cluster)

    health = None
    if args.health_port >= 0:
        from k8s_tpu.controller.health import HealthServer

        health = HealthServer(args.health_port).start()

    kubelet = None
    if args.local:
        from k8s_tpu.runtime.kubelet import LocalKubelet, SubprocessExecutor

        kubelet = LocalKubelet(client, SubprocessExecutor(log_dir="/tmp/ktpu-logs"))
        kubelet.start()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    elector = LeaderElector(
        client.cluster,
        namespace,
        "tpu-operator",
        identity=name,
        lease_duration=LEASE_DURATION,
        renew_deadline=RENEW_DEADLINE,
        retry_period=RETRY_PERIOD,
    )

    def on_started_leading(lost: threading.Event):
        controller = Controller(client, job_client, config, args.namespace)
        if health is not None:
            # pushed obs heartbeats (POST /v1/heartbeat/...) route to
            # the owning reconciler instead of waiting for a poll
            health.heartbeat_sink = controller.ingest_heartbeat
        if args.chaos_level >= 0:
            from k8s_tpu.runtime.chaos import ChaosMonkey

            ChaosMonkey.from_level(
                client, args.chaos_level, seed=args.chaos_seed,
                interval=args.chaos_interval, faulty=faulty,
                lease_namespace=namespace,
                # forced preemptions (sched-preempt) only make sense
                # when this controller runs the cluster scheduler
                scheduler=(controller if controller.scheduler is not None
                           else None),
            ).start()
        controller.start()
        while not stop.is_set() and not lost.is_set():
            stop.wait(0.5)
        controller.stop()

    def on_stopped_leading():
        # Reference main.go Fatalf-exits here; we additionally flip the
        # liveness endpoint so the kubelet restarts us even if shutdown wedges.
        log.info("leader election lost")
        if health is not None:
            health.set_unhealthy()

    try:
        elector.run(on_started_leading, on_stopped_leading, stop=stop)
    finally:
        if kubelet is not None:
            kubelet.stop()
        if health is not None:
            health.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
