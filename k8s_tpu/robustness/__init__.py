"""Robustness layer: the single retry/backoff policy every retry site
in the operator routes through, plus helpers for fault-tolerant calls.

Before this package existed, backoff logic was scattered ad-hoc
(RestWatcher re-dials, informer relists, the controller's fixed 30s
init retry) and gang restarts fired back-to-back with **zero** delay —
a crashing-image job would burn its whole ``maxGangRestarts`` budget in
under a minute (a restart storm). Everything now shares
:class:`~k8s_tpu.robustness.backoff.Backoff`.
"""

from k8s_tpu.robustness.backoff import (  # noqa: F401
    Backoff,
    BackoffPolicy,
    retry_call,
)
