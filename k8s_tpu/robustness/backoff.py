"""Unified exponential backoff with full jitter, cap, and
reset-after-stable-period.

One policy object, one mutable state object, adopted by every retry
site in the repo (gang restart, controller requeue, watch re-dial,
410 relist, informer resync, checkpoint-save retry). The semantics are
the CrashLoopBackOff / client-go-wait.Backoff hybrid the operators
literature converges on ("TensorFlow: large-scale ML" §4.2 coordinated
restart; Podracer architectures' restart-with-backoff):

- delay grows ``base * factor**(failures-1)``, capped at ``cap``;
- *full jitter* (AWS architecture-blog sense): the actual delay is
  uniform in ``[raw*(1-jitter), raw]`` — decorrelates a gang of
  restarting jobs so they don't thundering-herd the apiserver;
- after ``reset_after`` seconds without a failure the streak resets,
  so a job that ran stably for a while earns back a fast first retry.

Everything is injectable for tests: ``clock`` (fake monotonic time —
tier-1 asserts restart spacing with zero wall-clock sleeps), ``seed``
(deterministic jitter), ``sleep`` in :func:`retry_call`.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type


@dataclass(frozen=True)
class BackoffPolicy:
    """Immutable knobs of one backoff schedule."""

    base: float = 1.0          # delay after the first failure (seconds)
    factor: float = 2.0        # growth per consecutive failure
    cap: float = 300.0         # delay ceiling
    jitter: float = 1.0        # randomized fraction of the raw delay [0, 1]
    reset_after: float = 600.0 # stable window that clears the streak; 0 = never

    def validate(self) -> None:
        if self.base < 0:
            raise ValueError(f"backoff base must be >= 0, got {self.base}")
        if self.factor < 1.0:
            raise ValueError(f"backoff factor must be >= 1, got {self.factor}")
        if self.cap < self.base:
            raise ValueError(
                f"backoff cap ({self.cap}) must be >= base ({self.base})")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(f"backoff jitter must be in [0, 1], got {self.jitter}")
        if self.reset_after < 0:
            raise ValueError(
                f"backoff reset_after must be >= 0, got {self.reset_after}")

    def raw_delay(self, failures: int) -> float:
        """Un-jittered delay for the Nth consecutive failure (N >= 1)."""
        if failures <= 0:
            return 0.0
        return min(self.cap, self.base * self.factor ** (failures - 1))

    def delay(self, failures: int, rng: random.Random) -> float:
        """Jittered delay: uniform in ``[raw*(1-jitter), raw]``."""
        raw = self.raw_delay(failures)
        if raw <= 0.0 or self.jitter <= 0.0:
            return raw
        low = raw * (1.0 - self.jitter)
        return rng.uniform(low, raw)


class Backoff:
    """Mutable backoff state for ONE retry site.

    Contract: call :meth:`note_failure` when the protected operation
    fails (returns the delay to hold off); gate the next attempt on
    :meth:`ready` / :meth:`remaining` (tick-driven reconcilers) or
    block with :meth:`wait` (dedicated threads); call
    :meth:`note_success` — or just let ``reset_after`` elapse — once
    the operation is healthy again.
    """

    def __init__(
        self,
        policy: Optional[BackoffPolicy] = None,
        seed: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.policy = policy or BackoffPolicy()
        self.rng = random.Random(seed)
        self.clock = clock
        self.failures = 0
        self.current_delay = 0.0
        self._not_before: Optional[float] = None
        self._last_failure: Optional[float] = None

    def _maybe_reset(self, now: float) -> None:
        if (
            self._last_failure is not None
            and self.policy.reset_after > 0
            and now - self._last_failure >= self.policy.reset_after
        ):
            self.note_success()

    def note_failure(self) -> float:
        """Record one failure; returns the jittered delay before the
        next attempt may run."""
        now = self.clock()
        self._maybe_reset(now)
        self.failures += 1
        self.current_delay = self.policy.delay(self.failures, self.rng)
        self._not_before = now + self.current_delay
        self._last_failure = now
        return self.current_delay

    def note_success(self) -> None:
        """Clear the streak (stable again)."""
        self.failures = 0
        self.current_delay = 0.0
        self._not_before = None
        self._last_failure = None

    # alias: sites that think in reset() terms
    reset = note_success

    def remaining(self) -> float:
        """Seconds left before the next attempt is allowed (0 = go)."""
        now = self.clock()
        self._maybe_reset(now)
        if self._not_before is None:
            return 0.0
        return max(0.0, self._not_before - now)

    def ready(self) -> bool:
        return self.remaining() <= 0.0

    def wait(self, stop: Optional[threading.Event] = None) -> bool:
        """Block out the current hold-off. With a stop event, waits on
        it (interruptible) and returns True if stop fired; plain sleep
        otherwise (returns False)."""
        delay = self.remaining()
        if delay <= 0:
            return False
        if stop is not None:
            return stop.wait(delay)
        time.sleep(delay)
        return False


def retry_call(
    fn: Callable,
    *,
    policy: Optional[BackoffPolicy] = None,
    max_attempts: int = 3,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    should_retry: Optional[Callable[[BaseException], bool]] = None,
    seed: Optional[int] = None,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
):
    """Call ``fn()`` up to ``max_attempts`` times, sleeping the policy's
    backoff between attempts. An exception not matching ``retry_on`` —
    or rejected by the ``should_retry`` predicate — propagates
    immediately; the last attempt's exception always propagates.
    ``on_retry`` (attempt#, exception, upcoming delay) lets callers
    log/count."""
    if max_attempts < 1:
        raise ValueError("max_attempts must be >= 1")
    bo = Backoff(policy or BackoffPolicy(base=0.1, cap=5.0), seed=seed)
    for attempt in range(1, max_attempts + 1):
        try:
            return fn()
        except retry_on as e:
            if should_retry is not None and not should_retry(e):
                raise
            if attempt >= max_attempts:
                raise
            delay = bo.note_failure()
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)
