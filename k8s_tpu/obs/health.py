"""Training-health monitoring: numerics classification, HBM pressure,
and on-demand profiling helpers.

The numerics half of the observability layer (docs/OBSERVABILITY.md,
"Training health"). The training step computes a fused on-device
health block (loss, global grad norm, nonfinite-grad count,
update/param norm ratio — ``make_train_step(health=True)``); the
program reads it at its existing log points (no extra host syncs),
emits it as the ``step_health`` event, and carries it on the per-host
heartbeat. The reconciler's obs tick feeds those observations into
:class:`HealthMonitor` — pure decision logic in the same
injected-clock/hysteresis style as
:class:`k8s_tpu.obs.straggler.StragglerDetector` — and acts on the
verdict per ``spec.observability.onDivergence`` (restart from the last
*healthy* checkpoint / halt / observe only).

Classification rules, deliberately simple and fully deterministic (the
unit-test surface):

- **NaN/Inf** — a non-finite loss or grad norm, or any nonfinite grad
  element, trips ``diverged`` in ONE observation (there is no honest
  hysteresis for NaN: the params are poisoned from the next update on).
  The verdict carries ``first_bad_step`` and ``last_healthy_step`` —
  the restore ceiling the operator threads into the PR-4 planner so a
  NaN step is never the restore target.
- **Loss spike vs EMA** — loss >= ``spike_factor`` x the running EMA of
  healthy losses for ``spike_steps`` consecutive FRESH observations
  (an observation counts only when the reported step advanced) raises
  a ``loss_spike`` warning; an optional ``min_window_s`` of clock time
  must span the streak (burst guard, injected clock).
- **Plateau** — over the last ``plateau_window`` healthy observations
  the relative loss improvement stays under ``plateau_rel`` → a
  ``plateau`` warning. 0 disables.
- Hysteresis both ways: one warning per episode, cleared after
  ``clear_after`` clean fresh observations; a step REGRESSION (the gang
  restarted and replays from a restored step) resets the divergence
  episode so the monitor can judge the recovered run afresh.

This module also hosts two device-facing helpers shared by the trainer
obs endpoint and the serving frontend (imported lazily — the monitor
itself must stay importable on device-less operator processes):

- :func:`hbm_block` — per-device ``jax`` ``memory_stats()`` gauges
  (``ktpu_obs_hbm_bytes_in_use`` / ``_peak`` / ``_limit``) plus an
  aggregate heartbeat block with the worst-device peak fraction the
  reconciler's MemoryPressure check reads;
- :func:`capture_profile` — a bounded ``jax.profiler`` trace into the
  flight-recorder dir, behind ``GET /debug/profile?seconds=N`` on the
  per-host obs server (the on-demand successor of the env-gated
  ``maybe_profile``).

Chaos: the ``nan-grad`` fault arms here (:func:`arm_nan_grad` in
process, ``KTPU_CHAOS_NAN_GRAD="<step>"`` for subprocess gangs — the
same split as the slow-host hook in ``obs.trace``); the training
program consumes it per step and poisons that step's gradients with a
NaN loss scale, making the whole divergence→restore path drivable
deterministically in e2e.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

# health-block keys the step emits / the heartbeat carries
HEALTH_KEYS = ("loss", "grad_norm", "nonfinite_grads", "update_ratio")


# -- chaos nan-grad hook (process-local arm; see runtime/chaos.py) --------

_NAN_LOCK = threading.Lock()
# armed step: None = off, -1 = "the next consumed step", N = exactly N
_NAN_ARMED: Dict[str, Optional[int]] = {"step": None}


def arm_nan_grad(step: int = -1) -> None:
    """Poison the gradients of train step ``step`` of this process
    (-1 = the next step that polls) — the in-process arm of the
    ``nan-grad`` chaos fault. Subprocess gangs arm the same poison at
    spawn via ``KTPU_CHAOS_NAN_GRAD="<step>"``."""
    with _NAN_LOCK:
        _NAN_ARMED["step"] = int(step)


def nan_grad_armed(env=None) -> Optional[int]:
    """The armed poison step, from the process hook or the env
    contract (None when the fault is not armed at all — programs use
    this to decide whether the chaos-scale leaf rides the batch)."""
    with _NAN_LOCK:
        if _NAN_ARMED["step"] is not None:
            return _NAN_ARMED["step"]
    env = env if env is not None else os.environ
    spec = env.get("KTPU_CHAOS_NAN_GRAD", "")
    if spec:
        try:
            return int(spec)
        except ValueError:
            return None
    return None


def consume_nan_grad(step: int, env=None) -> bool:
    """True exactly once, at the armed step (or the first polled step
    for ``-1``): the caller must poison THIS step's gradients. The
    env arm clears process-locally so a poisoned run never re-fires."""
    armed = nan_grad_armed(env)
    if armed is None:
        return False
    if armed != -1 and armed != int(step):
        return False
    with _NAN_LOCK:
        _NAN_ARMED["step"] = None
    # the env stays set for the process lifetime — mask it so the next
    # poll sees the fault as spent (a restarted pod re-reads the real
    # env, which is exactly the once-per-pod-lifetime contract)
    if env is None and os.environ.get("KTPU_CHAOS_NAN_GRAD"):
        os.environ["KTPU_CHAOS_NAN_GRAD_FIRED"] = \
            os.environ.pop("KTPU_CHAOS_NAN_GRAD")
    return True


# -- pure health classification ------------------------------------------


def _finite(x) -> bool:
    try:
        return math.isfinite(float(x))
    except (TypeError, ValueError):
        return False


@dataclass
class HealthVerdict:
    """One observation's outcome. ``new_divergence`` fires exactly once
    per episode (the observation that tripped it); ``diverged`` holds
    while the episode lasts (until a restart's step regression resets
    it). ``new_warning``/``warning_cleared`` bracket a warning episode
    the same way."""

    observed_step: int = -1
    fresh: bool = False
    restarted: bool = False          # step regressed: a restart replayed
    new_divergence: bool = False
    diverged: bool = False
    first_bad_step: Optional[int] = None
    last_healthy_step: Optional[int] = None
    new_warning: Optional[str] = None   # "loss_spike" | "plateau"
    warning: Optional[str] = None       # active warning kind
    warning_cleared: Optional[str] = None
    reason: str = ""
    loss: Optional[float] = None


class HealthMonitor:
    def __init__(
        self,
        spike_factor: float = 3.0,
        spike_steps: int = 2,
        ema_alpha: float = 0.3,
        warmup_obs: int = 3,
        plateau_window: int = 0,
        plateau_rel: float = 1e-3,
        clear_after: int = 3,
        min_window_s: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if spike_factor <= 1.0:
            raise ValueError("spike_factor must be > 1.0")
        self.spike_factor = float(spike_factor)
        self.spike_steps = max(1, int(spike_steps))
        self.ema_alpha = float(ema_alpha)
        self.warmup_obs = max(1, int(warmup_obs))
        self.plateau_window = max(0, int(plateau_window))
        self.plateau_rel = float(plateau_rel)
        self.clear_after = max(1, int(clear_after))
        self.min_window_s = float(min_window_s)
        self.clock = clock
        self._last_step = -1
        self._last_healthy: Optional[int] = None
        self._diverged = False
        self._first_bad: Optional[int] = None
        self._ema: Optional[float] = None
        self._healthy_obs = 0
        self._spike_streak = 0
        self._spike_started_at = 0.0
        self._warning: Optional[str] = None
        self._clear_streak = 0
        self._plateau: deque = deque(maxlen=max(1, self.plateau_window))

    def reset(self, floor_step: int) -> None:
        """Start a new episode after the CALLER acted on a verdict (the
        reconciler's divergence restart): clears the divergence/warning
        streaks and raises the fresh-observation floor to
        ``floor_step`` — the gang's progress at verdict time — so the
        torn-down gang's stale heartbeats (and the replay below the
        floor) can't re-trip on old evidence, while a RECURRING fault
        past the floor raises a new ``new_divergence`` (bounded by the
        caller's restart budget). Without this, a replay that resumes
        exactly at the old max step would never regress the step
        counter and a persistent fault would never re-raise.
        ``last_healthy_step`` survives — it is still the best-known
        restore ceiling."""
        self._diverged = False
        self._first_bad = None
        self._spike_streak = 0
        self._clear_streak = 0
        self._plateau.clear()
        self._last_step = max(self._last_step, int(floor_step))

    def observe(self, health: Dict) -> HealthVerdict:
        """Judge one health observation
        (``{"step", "loss", "grad_norm", "nonfinite_grads",
        "update_ratio"}`` — the ``step_health`` block). Observations
        with a non-advancing step are ignored (a reconciler re-polling
        an unchanged heartbeat must not inflate any streak)."""
        v = HealthVerdict(diverged=self._diverged,
                          first_bad_step=self._first_bad,
                          last_healthy_step=self._last_healthy,
                          warning=self._warning)
        try:
            step = int(health.get("step", -1))
        except (TypeError, ValueError):
            return v
        if step < 0:
            return v
        v.observed_step = step
        if step < self._last_step:
            # the gang restarted and replays from a restored step: the
            # old episode's evidence describes a state that no longer
            # exists — reset so the recovered run is judged afresh
            v.restarted = True
            self._diverged = False
            self._first_bad = None
            self._spike_streak = 0
            self._clear_streak = 0
            self._plateau.clear()
            self._last_step = step - 1
            v.diverged = False
            v.first_bad_step = None
        if step <= self._last_step:
            return v
        self._last_step = step
        v.fresh = True

        loss = health.get("loss")
        v.loss = float(loss) if _finite(loss) else None
        nonfinite = 0.0
        try:
            nf = float(health.get("nonfinite_grads", 0) or 0)
            nonfinite = nf if math.isfinite(nf) else 1.0
        except (TypeError, ValueError):
            nonfinite = 0.0
        bad = (
            nonfinite > 0
            or not _finite(loss)
            or not _finite(health.get("grad_norm", 0.0))
        )
        if bad:
            if not self._diverged:
                self._diverged = True
                self._first_bad = step
                v.new_divergence = True
                v.reason = (
                    f"non-finite numerics at step {step} "
                    f"(loss={health.get('loss')}, "
                    f"grad_norm={health.get('grad_norm')}, "
                    f"nonfinite_grads={nonfinite:g}); "
                    f"last healthy step: {self._last_healthy}"
                )
            v.diverged = True
            v.first_bad_step = self._first_bad
            return v

        # healthy observation
        self._last_healthy = step
        v.last_healthy_step = step
        if self._diverged:
            # NaN params cannot heal without a restore, so a healthy
            # observation while diverged means the evidence is mixed
            # (e.g. a host restarted without a step regression we saw)
            # — count toward clearing rather than trusting one sample
            self._clear_streak += 1
            if self._clear_streak >= self.clear_after:
                self._diverged = False
                self._first_bad = None
                self._clear_streak = 0
            v.diverged = self._diverged
            v.first_bad_step = self._first_bad
            return v

        lf = float(loss)
        self._healthy_obs += 1
        spiking = (
            self._ema is not None
            and self._healthy_obs > self.warmup_obs
            and lf >= self.spike_factor * self._ema
        )
        if spiking:
            if self._spike_streak == 0:
                self._spike_started_at = self.clock()
            self._spike_streak += 1
        else:
            self._spike_streak = 0
        # EMA freezes while spike evidence accumulates PRE-verdict
        # (updating it with the spiked samples would pull the baseline
        # up and kill the streak before the bar); once the warning is
        # raised it tracks again, so a sustained new loss level becomes
        # the baseline and the warning self-clears (hysteresis).
        if not (spiking and self._warning is None):
            self._ema = (lf if self._ema is None
                         else (1 - self.ema_alpha) * self._ema
                         + self.ema_alpha * lf)

        plateaued = False
        if self.plateau_window > 0 and not spiking:
            self._plateau.append(lf)
            if len(self._plateau) == self.plateau_window:
                first, last = self._plateau[0], self._plateau[-1]
                denom = max(abs(first), 1e-12)
                plateaued = (first - last) / denom < self.plateau_rel

        if (
            spiking
            and self._spike_streak >= self.spike_steps
            and self._warning != "loss_spike"
            and self.clock() - self._spike_started_at >= self.min_window_s
        ):
            self._warning = "loss_spike"
            self._clear_streak = 0
            v.new_warning = "loss_spike"
            v.reason = (
                f"loss {lf:.4g} >= {self.spike_factor:g}x EMA "
                f"{self._ema:.4g} for {self._spike_streak} consecutive "
                f"steps (step {step})"
            )
        elif plateaued and self._warning != "plateau":
            self._warning = "plateau"
            self._clear_streak = 0
            v.new_warning = "plateau"
            v.reason = (
                f"loss improvement under {self.plateau_rel:g} over the "
                f"last {self.plateau_window} observations (step {step})"
            )
        elif self._warning is not None and not spiking and not plateaued:
            self._clear_streak += 1
            if self._clear_streak >= self.clear_after:
                v.warning_cleared = self._warning
                self._warning = None
                self._clear_streak = 0
        v.warning = self._warning
        return v


# -- device memory (HBM) gauges ------------------------------------------


def device_memory_stats() -> List[Dict]:
    """Per-local-device allocator stats from ``jax``'s
    ``Device.memory_stats()`` — empty on backends that don't report
    (CPU returns None) and on any error: memory telemetry is
    best-effort everywhere."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return []
    out: List[Dict] = []
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        out.append({
            "device": int(getattr(d, "id", len(out))),
            "bytes_in_use": int(ms.get("bytes_in_use", 0) or 0),
            "peak_bytes_in_use": int(ms.get("peak_bytes_in_use", 0) or 0),
            "bytes_limit": int(ms.get("bytes_limit", 0) or 0),
        })
    return out


def hbm_block(stats: Optional[List[Dict]] = None,
              export_gauges: bool = True,
              task: str = "") -> Optional[Dict]:
    """The heartbeat/healthz ``hbm`` block: per-device stats plus the
    aggregate the reconciler's MemoryPressure check reads (worst-device
    ``peak_fraction``). ``export_gauges`` also sets the process-global
    ``ktpu_obs_hbm_*`` series (one label set per device). Returns None
    when the backend reports nothing (CPU) — the block is simply absent
    from the heartbeat then."""
    stats = device_memory_stats() if stats is None else stats
    if not stats:
        return None
    if export_gauges:
        from k8s_tpu.controller import metrics

        for s in stats:
            lbl = {"device": str(s["device"])}
            if task:
                lbl["task"] = task
            metrics.OBS_HBM_IN_USE.set(float(s["bytes_in_use"]), lbl)
            metrics.OBS_HBM_PEAK.set(float(s["peak_bytes_in_use"]), lbl)
            metrics.OBS_HBM_LIMIT.set(float(s["bytes_limit"]), lbl)
    peak_fraction = max(
        (s["peak_bytes_in_use"] / s["bytes_limit"]
         for s in stats if s["bytes_limit"] > 0),
        default=0.0,
    )
    return {
        "bytes_in_use": sum(s["bytes_in_use"] for s in stats),
        "peak_bytes_in_use": max(s["peak_bytes_in_use"] for s in stats),
        "bytes_limit": sum(s["bytes_limit"] for s in stats),
        "peak_fraction": round(peak_fraction, 4),
        "devices": stats,
    }


# -- on-demand profiling --------------------------------------------------

_PROFILE_LOCK = threading.Lock()


def capture_profile(out_dir: str, seconds: float) -> Dict:
    """One bounded ``jax.profiler`` trace into ``out_dir`` — the
    ``GET /debug/profile?seconds=N`` backend on the per-host obs
    server. Exactly one capture at a time per process (the profiler
    cannot nest); a concurrent request gets a busy error instead of a
    crashed trace. Never raises."""
    seconds = min(max(float(seconds), 0.1), 60.0)
    if not out_dir:
        return {"ok": False, "error": "no profile dir configured "
                                      "(set observability.flightRecorderDir)"}
    if not _PROFILE_LOCK.acquire(blocking=False):
        return {"ok": False, "error": "profile capture already in progress"}
    try:
        import jax

        path = os.path.join(out_dir, f"profile-{int(time.time() * 1e3)}")
        os.makedirs(path, exist_ok=True)
        jax.profiler.start_trace(path)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        return {"ok": True, "dir": path, "seconds": seconds}
    except Exception as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}
    finally:
        _PROFILE_LOCK.release()
