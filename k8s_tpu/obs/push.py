"""Pushed obs heartbeats — the worker half of the event-driven obs path.

The polling design had the controller sweep every worker's
``/healthz`` each reconcile tick: O(jobs × hosts) HTTP round trips per
interval whether anything changed or not. The event-driven control
plane (docs/SCHEDULER.md "Event-driven core") inverts the hot path:
each worker POSTs its own heartbeat to the operator
(``POST /v1/heartbeat/<ns>/<name>/<host>`` on the operator health
server), the controller caches it and kicks the owning job's reconcile
key — so a heartbeat costs one inbound request and zero polling.

Opt-in by env: the operator deployment sets ``KTPU_OPERATOR_HEALTH``
(``<operator-svc-dns>:<health-port>``); the trainer turns that into a
per-host ``KTPU_OBS_PUSH_URL`` on gang workers with an
``observability`` block, and :func:`maybe_start_pusher` (called from
``start_obs_server``) starts the push thread. Unset ⇒ nothing runs and
the controller falls back to its shared-poller pull.

Best-effort by design: a push failure is logged at debug and retried
next interval — the controller's pull path and resync backstop cover a
worker that can never reach the operator, so the trainer must never
block or crash on this thread's behalf.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import threading
from typing import Callable, Optional
from urllib.parse import urlsplit

log = logging.getLogger(__name__)

PUSH_URL_ENV = "KTPU_OBS_PUSH_URL"
PUSH_INTERVAL_ENV = "KTPU_OBS_PUSH_INTERVAL"
DEFAULT_INTERVAL = 5.0


class HeartbeatPusher:
    """Daemon thread POSTing ``stats_fn()`` to ``url`` every
    ``interval`` seconds over one persistent connection (re-dialed on
    error — the operator restarting must not strand the pusher)."""

    def __init__(self, url: str, stats_fn: Callable[[], dict],
                 interval: float = DEFAULT_INTERVAL):
        self.url = url
        self.stats_fn = stats_fn
        self.interval = max(0.5, interval)
        u = urlsplit(url)
        self._host = u.hostname or "localhost"
        self._port = u.port or 80
        self._path = u.path or "/"
        self._conn: Optional[http.client.HTTPConnection] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.pushed = 0  # successful POSTs (tests assert on it)

    def start(self) -> "HeartbeatPusher":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="obs-push")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        if self._conn is not None:
            try:
                self._conn.close()
            except Exception:
                pass
            self._conn = None

    def push_once(self) -> bool:
        """One POST; True on 2xx. Public so tests (and a final flush at
        teardown) can push synchronously."""
        try:
            body = json.dumps(self.stats_fn() or {}, default=str)
        except Exception as e:  # stats bug must not kill the thread
            log.debug("heartbeat push: stats_fn failed: %s", e)
            return False
        for attempt in (0, 1):  # retry once on a stale kept-alive conn
            try:
                if self._conn is None:
                    self._conn = http.client.HTTPConnection(
                        self._host, self._port, timeout=2.0)
                self._conn.request(
                    "POST", self._path, body=body,
                    headers={"Content-Type": "application/json"})
                resp = self._conn.getresponse()
                resp.read()
                if 200 <= resp.status < 300:
                    self.pushed += 1
                    return True
                return False  # 404: operator has no sink / unknown job
            except Exception as e:
                try:
                    if self._conn is not None:
                        self._conn.close()
                except Exception:
                    pass
                self._conn = None
                if attempt == 1:
                    log.debug("heartbeat push to %s failed: %s",
                              self.url, e)
        return False

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.push_once()


def maybe_start_pusher(stats_fn) -> Optional[HeartbeatPusher]:
    """Start a pusher iff ``KTPU_OBS_PUSH_URL`` is set (the trainer
    only sets it when the operator advertised its health endpoint)."""
    url = os.environ.get(PUSH_URL_ENV, "")
    if not url:
        return None
    try:
        interval = float(os.environ.get(PUSH_INTERVAL_ENV,
                                        DEFAULT_INTERVAL))
    except ValueError:
        interval = DEFAULT_INTERVAL
    return HeartbeatPusher(url, stats_fn, interval=interval).start()
