"""Gang straggler detection: pure decision logic.

One slow host drags the WHOLE gang (every collective waits for the
last arrival), so "which host is the straggler" is the first question
of any slow-step investigation — and the one aggregate counters can't
answer. The reconciler feeds this detector the per-host step
heartbeats it polls from each worker's obs endpoint
(``{host: {"step", "step_time_s", "phases_s", "age_s"}}``) and acts on
the verdict (``StragglerDetected`` condition + K8s Event + skew
gauges, ``trainer/training.py``).

Decision rule, deliberately simple and fully deterministic (the unit
test surface):

- hosts are judged on ``busy_s`` when the heartbeat carries it (step
  wall MINUS the gang-coupled phases — see
  :data:`k8s_tpu.obs.trace.GANG_PHASES`): synchronized SPMD equalizes
  wall time through the collectives, so only a host's OWN work (input
  waits, checkpoint stalls, host-side processing) attributes slowness
  to it; heartbeats without ``busy_s`` fall back to ``step_time_s``;
- baseline = median busy time of the OTHER hosts (excluding the
  slowest), so a 2-host gang still has an honest peer baseline;
- a host is a straggler CANDIDATE when its busy time >=
  ``threshold`` x that baseline;
- the verdict fires only after the SAME host is the candidate for
  ``consecutive`` FRESH observations — an observation only counts
  when the gang's max step advanced since the last counted one, so a
  reconciler re-polling an unchanged heartbeat can't inflate the
  streak (ticks are much faster than steps);
- hysteresis both ways: a raised verdict stays ``active`` (no
  re-raise flapping) until ``clear_after`` fresh clean observations,
  and an optional ``min_window_s`` of clock time must span the streak
  (guards against N heartbeats arriving in one burst after a stall);
- heartbeats staler than ``stale_after_s`` are excluded — a DEAD host
  is the gang-restart path's problem, not a straggler.
"""

from __future__ import annotations

import statistics
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional


@dataclass
class StragglerVerdict:
    """One observation's outcome. ``new_straggler`` is set exactly once
    per episode (the tick the streak crosses the bar); ``active`` holds
    while the episode lasts; ``cleared`` is set on the tick the episode
    ends."""

    observed_hosts: int = 0
    skew_s: float = 0.0        # slowest - peer median (busy time)
    median_s: float = 0.0      # peer median (excluding the slowest)
    slowest: Optional[int] = None
    ratio: float = 0.0         # slowest / peer median
    streak: int = 0
    new_straggler: Optional[int] = None
    active: Optional[int] = None
    cleared: Optional[int] = None
    step_times: Dict[int, float] = field(default_factory=dict)


class StragglerDetector:
    def __init__(
        self,
        threshold: float = 1.5,
        consecutive: int = 3,
        clear_after: int = 3,
        min_hosts: int = 2,
        stale_after_s: float = 60.0,
        min_window_s: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if threshold <= 1.0:
            raise ValueError("threshold must be > 1.0")
        self.threshold = float(threshold)
        self.consecutive = max(1, int(consecutive))
        self.clear_after = max(1, int(clear_after))
        self.min_hosts = max(2, int(min_hosts))
        self.stale_after_s = float(stale_after_s)
        self.min_window_s = float(min_window_s)
        self.clock = clock
        self._streak_host: Optional[int] = None
        self._streak = 0
        self._streak_started_at = 0.0
        self._clear_streak = 0
        self._active: Optional[int] = None
        self._last_max_step = -1

    def observe(self, stats: Dict[int, dict]) -> StragglerVerdict:
        v = StragglerVerdict(active=self._active)
        fresh = {
            int(h): s for h, s in (stats or {}).items()
            if float(s.get("step_time_s", 0.0) or 0.0) > 0.0
            and float(s.get("age_s", 0.0) or 0.0) <= self.stale_after_s
        }
        v.observed_hosts = len(fresh)
        if len(fresh) < self.min_hosts:
            return v
        # judge on busy time when PRESENT (wall minus gang-coupled
        # phases; see module docstring), wall time otherwise. Presence,
        # not truthiness: a host whose whole step was gang-coupled
        # legitimately reports busy_s == 0.0, and falling back to its
        # gang-equalized WALL there would make the least-busy host
        # look like the straggler.
        times = {
            h: float(s["busy_s"] if s.get("busy_s") is not None
                     else s["step_time_s"])
            for h, s in fresh.items()
        }
        v.step_times = dict(times)
        slowest = max(times, key=lambda h: (times[h], h))
        peers = [t for h, t in times.items() if h != slowest]
        med = statistics.median(peers)
        v.slowest = slowest
        v.median_s = med
        v.skew_s = max(0.0, times[slowest] - med)
        v.ratio = times[slowest] / med if med > 0 else 0.0
        over = med > 0 and v.ratio >= self.threshold

        # fresh-observation gate: only a gang that made progress since
        # the last counted observation yields a countable sample
        max_step = max(int(s.get("step", 0) or 0) for s in fresh.values())
        advanced = max_step > self._last_max_step
        if advanced:
            self._last_max_step = max_step

        if over:
            self._clear_streak = 0
            if advanced:
                if slowest == self._streak_host:
                    self._streak += 1
                else:
                    self._streak_host = slowest
                    self._streak = 1
                    self._streak_started_at = self.clock()
        else:
            self._streak_host, self._streak = None, 0
            if advanced and self._active is not None:
                self._clear_streak += 1
                if self._clear_streak >= self.clear_after:
                    v.cleared = self._active
                    self._active = None
                    self._clear_streak = 0
        v.streak = self._streak

        if (
            over
            and self._streak >= self.consecutive
            and self._active != self._streak_host
            and self.clock() - self._streak_started_at >= self.min_window_s
        ):
            if self._active is not None:
                # the straggler identity SWITCHED hosts: close the old
                # episode in the same verdict — without this the
                # previous host's StragglerDetected would never be
                # followed by a StragglerCleared
                v.cleared = self._active
            self._active = self._streak_host
            v.new_straggler = self._streak_host
            self._clear_streak = 0
        v.active = self._active
        return v
