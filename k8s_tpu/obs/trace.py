"""Spans, trace ids, and the per-process flight recorder.

The tracing half of the observability layer (docs/OBSERVABILITY.md):

- **Trace id** — the operator stamps every TpuJob with one
  (``KTPU_TRACE_ID = <job>-<runtimeId>``, injected by
  ``trainer/replicas.py``); every span, heartbeat, and request record
  carries it, so evidence from the reconciler, a worker's flight
  recorder, and a router response line can be joined after the fact.
- **Step phases** — :meth:`Tracer.step` wraps one train step; the
  phases inside it (``data_wait`` / ``step_compute`` / ``host_sync`` /
  ``ckpt_save``) are timed with two ``perf_counter`` calls each, so a
  step's wall time decomposes instead of being one opaque number. The
  tracer accounts its own bookkeeping time in :attr:`Tracer.overhead_s`
  — the number the llama_bench tracing-overhead guard asserts on.
- **Flight recorder** — a bounded ring of the most recent step/span
  records, re-dumped atomically (tmp + rename) to node-local disk on a
  small interval and force-dumped on SIGTERM / crash / preemption
  (``spmd_launcher`` + ``programs.common.maybe_preempt_exit`` hook the
  same signal path as the PR-4 checkpoint flush). A SIGKILLed pod —
  which no handler can catch — still leaves its last interval's spans
  on disk for the post-mortem. Served live via ``GET
  /debug/flightrecorder`` on the per-host obs endpoint
  (``controller/health.py``).
- **Chaos hook** — ``slow-host``: :func:`arm_slow_host` (in-process
  chaos matrix) or ``KTPU_CHAOS_SLOW_HOST="<host>:<seconds>[:<steps>]"``
  (subprocess e2e) throttles the matching host's steps inside the step
  span, making one gang member measurably slow — the fault the
  reconciler's straggler detection must attribute to the right pod.

When disabled (``KTPU_TRACE=0``) every surface degrades to branch-only
no-ops so the hot loop pays nothing.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# Phases that are GANG-COUPLED: in synchronized SPMD training a slow
# peer inflates every host's step wall time through the collectives,
# and depending on the backend's dispatch model that wait surfaces
# either inside the jitted step's dispatch (sync-executing backends)
# or at the host-sync readback (async dispatch) — so neither phase can
# attribute slowness to THIS host. Straggler attribution therefore
# judges busy_s = wall - gang phases: the host's OWN work (input
# waits, checkpoint saves, host-side processing, injected throttles),
# which is exactly the straggler class host-side telemetry can see.
# (Device-compute slowness is indistinguishable from a host's
# perspective — every peer's collective stretches identically; that
# diagnosis needs device profiles, out of this layer's scope.)
# `compile` (the first step of an incarnation: trace + XLA compile +
# the step) is excluded too: it is one-shot bring-up, not steady-state
# slowness — and with a node-local compile cache a replaced pod
# compiles COLD next to warm-cache survivors, which busy attribution
# would misread as a straggler on its very first heartbeat.
GANG_PHASES = ("step_compute", "host_sync", "compile")

# -- chaos slow-host hook (process-local arm; see runtime/chaos.py) ------

_SLOW_LOCK = threading.Lock()
_SLOW_ARMED = {"seconds": 0.0, "steps": 0}


def arm_slow_host(seconds: float, steps: int = 1 << 30) -> None:
    """Throttle the NEXT ``steps`` traced train steps of this process
    by ``seconds`` each — the in-process arm of the ``slow-host`` chaos
    fault (subprocess gangs arm the same throttle per-host via the
    ``KTPU_CHAOS_SLOW_HOST`` env at spawn)."""
    with _SLOW_LOCK:
        _SLOW_ARMED["seconds"] = float(seconds)
        _SLOW_ARMED["steps"] = int(steps)


def _consume_slow_throttle(tracer: "Tracer") -> float:
    """Seconds to sleep for THIS step: env-armed (per-host) plus
    process-armed (chaos matrix), each with its own step budget."""
    total = 0.0
    if tracer._env_slow_steps > 0:
        tracer._env_slow_steps -= 1
        total += tracer._env_slow_seconds
    with _SLOW_LOCK:
        if _SLOW_ARMED["steps"] > 0:
            _SLOW_ARMED["steps"] -= 1
            total += _SLOW_ARMED["seconds"]
    return total


# -- flight recorder -----------------------------------------------------


class FlightRecorder:
    """Bounded ring of recent telemetry records with atomic disk dumps.

    ``dump_path`` empty keeps the ring memory-only (the healthz route
    still serves it). With a path, :meth:`maybe_flush` re-dumps at most
    every ``flush_interval_s`` — cheap enough to call per step, frequent
    enough that a SIGKILL loses at most one interval of spans."""

    def __init__(self, capacity: int = 256, dump_path: str = "",
                 flush_interval_s: float = 0.5):
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        # RLocks: the launcher's SIGTERM handler dumps the recorder ON
        # THE MAIN THREAD between bytecodes — a plain Lock held by the
        # interrupted frame (a record() or an in-flight dump()) would
        # deadlock the handler forever and the pod would hang until the
        # kubelet's SIGKILL instead of exiting in the grace period
        self._lock = threading.RLock()
        self._dump_lock = threading.RLock()
        self.dump_path = dump_path
        self.flush_interval_s = float(flush_interval_s)
        self._last_flush = 0.0
        self.dumps = 0
        self.dump_failures = 0
        self._dump_seq = 0
        self._dump_warned = False

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def record(self, entry: dict) -> None:
        with self._lock:
            self._ring.append(entry)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def maybe_flush(self) -> None:
        if not self.dump_path:
            return
        now = time.monotonic()
        if now - self._last_flush >= self.flush_interval_s:
            self.dump("interval")

    def dump(self, reason: str = "") -> Optional[str]:
        """Atomically (per-dump tmp + fsync + rename) rewrite the dump
        file with the current ring — a reader never sees a torn file,
        and the newest complete dump survives a crash mid-write.

        Best-effort END TO END: a full/read-only node disk degrades
        the post-mortem, never the training step that flushed it
        (returns None and logs once). The tmp name is unique per dump
        so a signal-handler dump interleaving an in-flight interval
        dump on the same thread writes its own file — the older frame
        can at worst replace the final file with a marginally staler
        snapshot, never a torn one."""
        if not self.dump_path:
            return None
        try:
            payload = {
                "reason": reason,
                "dumped_at": time.time(),
                "entries": self.snapshot(),
            }
            with self._dump_lock:
                self._dump_seq += 1
                tmp = f"{self.dump_path}.tmp{os.getpid()}-{self._dump_seq}"
            d = os.path.dirname(self.dump_path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.dump_path)
        except Exception as e:
            self.dump_failures += 1
            # rate the clock anyway: retrying a dead disk every step
            # would turn telemetry into a per-step syscall storm
            self._last_flush = time.monotonic()
            if not self._dump_warned:
                self._dump_warned = True
                import logging

                logging.getLogger(__name__).warning(
                    "flight-recorder dump to %s failed (%s: %s); "
                    "post-mortem degraded, training unaffected",
                    self.dump_path, type(e).__name__, e)
            return None
        self._last_flush = time.monotonic()
        self.dumps += 1
        return self.dump_path


# -- step/phase spans ----------------------------------------------------


class _Phase:
    """One timed phase inside a step: two perf_counter calls total."""

    __slots__ = ("_st", "_name", "_t0")

    def __init__(self, st: "StepTrace", name: str):
        self._st = st
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        ph = self._st.phases
        ph[self._name] = ph.get(self._name, 0.0) + dt
        return False


class _NullPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_PHASE = _NullPhase()


class _NullStep:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def phase(self, name: str):
        return _NULL_PHASE


_NULL_STEP = _NullStep()


class StepTrace:
    """Context manager for one train step: wall time + phase breakdown.
    On exit it applies any armed slow-host throttle (chaos), records a
    step entry into the flight recorder, refreshes the tracer's
    heartbeat, and accounts its own bookkeeping time into
    ``tracer.overhead_s``."""

    __slots__ = ("tracer", "step", "phases", "_t0")

    def __init__(self, tracer: "Tracer", step: int):
        self.tracer = tracer
        self.step = int(step)
        self.phases: Dict[str, float] = {}

    def phase(self, name: str) -> _Phase:
        return _Phase(self, name)

    def __enter__(self) -> "StepTrace":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        throttle = _consume_slow_throttle(self.tracer)
        if throttle > 0:
            # the throttle lives INSIDE the step window so the skew is
            # what the gang heartbeats actually observe
            time.sleep(throttle)
            self.phases["chaos_slow_host"] = throttle
        b0 = time.perf_counter()
        wall = b0 - self._t0
        self.tracer._finish_step(self.step, wall, self.phases)
        if exc_type is not None:
            # the step is dying (preempt SystemExit, crash): force the
            # CURRENT step's span into the on-disk dump — the interval
            # flush may not have fired yet and there is no next step
            try:
                self.tracer.recorder.dump(f"step-{exc_type.__name__}")
            except Exception:
                pass
        self.tracer.overhead_s += time.perf_counter() - b0
        return False


class Tracer:
    """Per-process tracing front door. Construct directly (tests,
    benches) or via :meth:`from_env` (the operator contract:
    ``KTPU_TRACE_ID`` / ``KTPU_TRACE`` / ``KTPU_FLIGHT_DIR`` /
    ``KTPU_FLIGHT_CAPACITY`` / ``KTPU_CHAOS_SLOW_HOST``)."""

    def __init__(self, trace_id: str = "", task: str = "", host: int = 0,
                 enabled: bool = True,
                 recorder: Optional[FlightRecorder] = None):
        self.trace_id = trace_id
        self.task = task
        self.host = int(host)
        self.enabled = bool(enabled)
        self.recorder = recorder if recorder is not None else FlightRecorder()
        self.overhead_s = 0.0
        self._hb_lock = threading.Lock()
        self._hb = {"step": 0, "step_time_s": 0.0, "phases_s": {}}
        # latest step_health block (note_health) — kept OUTSIDE _hb:
        # _finish_step rebuilds the heartbeat every step, while health
        # only refreshes at log points, and must survive in between
        self._hb_health: Optional[Dict] = None
        self._hb_at = 0.0  # monotonic of last heartbeat refresh
        self._env_slow_seconds = 0.0
        self._env_slow_steps = 0

    @classmethod
    def from_env(cls, env=None, task: str = "", host: int = 0) -> "Tracer":
        env = env if env is not None else os.environ
        enabled = env.get("KTPU_TRACE", "1") not in ("0", "false")
        try:
            cap = int(env.get("KTPU_FLIGHT_CAPACITY", "256") or 256)
        except ValueError:
            cap = 256
        dump_dir = env.get("KTPU_FLIGHT_DIR", "")
        dump_path = (
            os.path.join(dump_dir, f"flight-host{int(host)}.json")
            if dump_dir else "")
        t = cls(
            trace_id=env.get("KTPU_TRACE_ID", ""),
            task=task, host=host, enabled=enabled,
            recorder=FlightRecorder(capacity=cap, dump_path=dump_path),
        )
        # KTPU_CHAOS_SLOW_HOST="<host>:<seconds>[:<steps>]" — the
        # subprocess arm of the slow-host chaos fault: only the named
        # host throttles, everyone else parses and ignores it
        spec = env.get("KTPU_CHAOS_SLOW_HOST", "")
        if spec:
            parts = spec.split(":")
            try:
                if int(parts[0]) == int(host):
                    t._env_slow_seconds = float(parts[1])
                    t._env_slow_steps = (
                        int(parts[2]) if len(parts) > 2 else 1 << 30)
            except (ValueError, IndexError):
                pass
        return t

    # -- recording --------------------------------------------------------

    def step(self, step: int):
        """``with tracer.step(n) as st: ... st.phase("data_wait") ...``"""
        if not self.enabled:
            return _NULL_STEP
        return StepTrace(self, step)

    def event(self, name: str, **attrs) -> None:
        """Record a point event (restart, restore, drain, ...) into the
        flight recorder ring."""
        if not self.enabled:
            return
        self.recorder.record({
            "kind": "event", "name": name, "t": time.time(),
            "trace_id": self.trace_id, "task": self.task, **attrs,
        })

    def span(self, name: str, **attrs):
        """Standalone timed span (outside the step loop): restore,
        compile, drain."""
        if not self.enabled:
            return _NULL_PHASE
        return _SpanCtx(self, name, attrs)

    def note_span(self, name: str, wall_s: float, **attrs) -> None:
        """Record an externally-timed span — phases a subsystem measures
        itself (the checkpoint manager's restore_plan/fetch/device and
        save_snapshot/serialize/commit breakdowns, the program's
        first-step compile) and reports after the fact — possibly from
        a background thread (the save writer/committer call in here;
        the recorder is lock-protected). Same record shape as
        :meth:`span`, so the flight recorder and /debug/flightrecorder
        render both identically."""
        if not self.enabled:
            return
        self._record_span(name, float(wall_s), attrs)

    def _finish_step(self, step: int, wall_s: float,
                     phases: Dict[str, float]) -> None:
        phases_r = {k: round(v, 6) for k, v in phases.items()}
        busy = max(0.0, wall_s - sum(
            phases.get(p, 0.0) for p in GANG_PHASES))
        self.recorder.record({
            "kind": "step", "step": step, "t": time.time(),
            "trace_id": self.trace_id, "task": self.task,
            "wall_s": round(wall_s, 6), "phases_s": phases_r,
        })
        with self._hb_lock:
            self._hb = {"step": step, "step_time_s": round(wall_s, 6),
                        "busy_s": round(busy, 6), "phases_s": phases_r}
            self._hb_at = time.monotonic()
        self.recorder.maybe_flush()

    def note_health(self, step: int, health: Dict) -> None:
        """Attach a step's numerics-health block (loss, grad norm,
        nonfinite-grad count, update ratio — the ``step_health``
        contract, docs/OBSERVABILITY.md "Training health") to the
        heartbeat the obs endpoint serves AND the flight-recorder ring,
        so a SIGKILLed diverging pod leaves its last losses/grad-norms
        on disk. Called at the program's existing log points only — the
        health scalars were device arrays until the caller read them,
        so this adds no sync of its own."""
        if not self.enabled:
            return
        t0 = time.perf_counter()
        block = {"step": int(step), **health}
        self.recorder.record({
            "kind": "health", "t": time.time(),
            "trace_id": self.trace_id, "task": self.task, **block,
        })
        with self._hb_lock:
            self._hb_health = block
        self.recorder.maybe_flush()
        # accounted like StepTrace bookkeeping: the llama_bench < 1%
        # overhead guard must cover the health-note path (including an
        # interval flush's fsync'd dump) — not just the phase spans
        self.overhead_s += time.perf_counter() - t0

    def _record_span(self, name: str, wall_s: float, attrs: dict) -> None:
        self.recorder.record({
            "kind": "span", "name": name, "t": time.time(),
            "trace_id": self.trace_id, "task": self.task,
            "wall_s": round(wall_s, 6), **attrs,
        })

    # -- export -----------------------------------------------------------

    def heartbeat(self) -> dict:
        """The per-host stats block the obs /healthz endpoint serves and
        the reconciler's straggler detector consumes: last completed
        step, its wall time + phase breakdown, and how stale it is."""
        with self._hb_lock:
            hb = dict(self._hb)
            at = self._hb_at
            if self._hb_health is not None:
                hb["health"] = dict(self._hb_health)
        hb["trace_id"] = self.trace_id
        hb["task"] = self.task
        hb["host"] = self.host
        hb["age_s"] = round(time.monotonic() - at, 3) if at else -1.0
        return hb

    def last_step(self) -> dict:
        """The latest step record (step + wall + phases) — what
        llama_train prints at log points as the ``step_phases`` event."""
        with self._hb_lock:
            return dict(self._hb)

    def flush(self, reason: str = "") -> Optional[str]:
        return self.recorder.dump(reason)


class _SpanCtx:
    __slots__ = ("_tracer", "_name", "_attrs", "_t0")

    def __init__(self, tracer: Tracer, name: str, attrs: dict):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tracer._record_span(
            self._name, time.perf_counter() - self._t0, self._attrs)
        return False


# -- process-global default (the launcher's signal path dumps it) --------

_DEFAULT: Optional[Tracer] = None
_DEFAULT_LOCK = threading.Lock()


def set_default_tracer(tracer: Optional[Tracer]) -> None:
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = tracer


def default_tracer() -> Optional[Tracer]:
    return _DEFAULT


def dump_default(reason: str = "") -> Optional[str]:
    """Force-dump the process default tracer's flight recorder —
    called from the launcher's SIGTERM handler, the crash exits, and
    the preemption-flush path. Never raises (a post-mortem aid must
    not change how the process dies)."""
    t = _DEFAULT
    if t is None:
        return None
    try:
        return t.flush(reason)
    except Exception:
        return None
