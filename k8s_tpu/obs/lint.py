"""Metrics-docs lint: every ``ktpu_*`` series registered in code must
be cataloged in docs/OBSERVABILITY.md, and vice versa.

A metric nobody can find is dead weight and a documented metric that
no longer exists is a debugging trap, so the CI ``obs`` stage (and a
tier-1 test) fails on drift in EITHER direction. Registration sites
are found syntactically — the first string argument of any
``.counter(`` / ``.gauge(`` / ``.histogram(`` call under ``k8s_tpu/``
whose name starts with ``ktpu_`` — so a new series added anywhere in
the package is caught without a central list to forget to update.
Histograms are cataloged by their base name (the ``_bucket``/``_sum``/
``_count`` suffixes are exposition detail, not separate series).

Run: ``python -m k8s_tpu.obs.lint`` (exit 1 + readable diff on drift).
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Set

_REGISTER_RE = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*\n?\s*\"(ktpu_[a-z0-9_]*[a-z0-9])\"")
_DOC_RE = re.compile(r"\bktpu_[a-z0-9_]*[a-z0-9]\b")

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_DOC = os.path.join(_REPO_ROOT, "docs", "OBSERVABILITY.md")
DEFAULT_SRC = os.path.join(_REPO_ROOT, "k8s_tpu")


def registered_series(src_root: str = DEFAULT_SRC) -> Set[str]:
    """Every ktpu_* series name passed to a .counter()/.gauge() call
    under ``src_root`` (tests excluded by construction — they live
    outside the package)."""
    out: Set[str] = set()
    for dirpath, _dirnames, filenames in os.walk(src_root):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                out.update(_REGISTER_RE.findall(f.read()))
    return out


def documented_series(doc_path: str = DEFAULT_DOC) -> Set[str]:
    """Every ktpu_* token mentioned in the catalog doc. The doc must
    therefore spell out full series names (no ``ktpu_foo_*`` wildcard
    prose) — that is the point: the catalog IS the inventory."""
    if not os.path.exists(doc_path):
        return set()
    with open(doc_path) as f:
        return set(_DOC_RE.findall(f.read()))


def lint(src_root: str = DEFAULT_SRC, doc_path: str = DEFAULT_DOC
         ) -> List[str]:
    """Return a list of human-readable problems (empty = clean)."""
    problems: List[str] = []
    if not os.path.exists(doc_path):
        return [f"metrics catalog missing: {doc_path}"]
    reg = registered_series(src_root)
    doc = documented_series(doc_path)
    for name in sorted(reg - doc):
        problems.append(
            f"registered but not documented in "
            f"{os.path.relpath(doc_path, _REPO_ROOT)}: {name}")
    for name in sorted(doc - reg):
        problems.append(
            f"documented but not registered anywhere under k8s_tpu/: "
            f"{name}")
    return problems


def main(argv=None) -> int:
    problems = lint()
    if problems:
        print("metrics-lint: FAILED")
        for p in problems:
            print(f"  - {p}")
        return 1
    n = len(registered_series())
    print(f"metrics-lint: ok ({n} ktpu_* series, all documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
