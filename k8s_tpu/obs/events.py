"""Structured-event parsing: the ``{"event": ...}`` JSON-lines contract.

Every program under the launcher emits machine-readable lifecycle
events as single-line JSON objects with an ``"event"`` key
(``serving_ready``, ``restored``, ``ckpt_goodput``, ``router_drained``,
``step_phases``, ...). Until this module the subprocess e2es each
re-invented the parse as ad-hoc substring greps; this is the ONE
shared parser they (and any log-scraping tooling) go through.

Default parsing is tolerant — pod logs interleave event lines with
free-form prints, tracebacks, and (after a SIGKILL) a possibly
truncated final line, none of which should crash a post-mortem.
``strict=True`` raises on a line that *claims* to be an event
(contains ``"event"``) but does not parse or validate — the mode for
asserting a producer's own output is well-formed.
"""

from __future__ import annotations

import json
from typing import Iterator, List, Optional


class EventParseError(ValueError):
    """A line that looks like an event is not a valid event record."""


def iter_events(text: str, strict: bool = False) -> Iterator[dict]:
    """Yield every valid event dict in ``text``, in order."""
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line.startswith("{"):
            continue
        looks_like_event = '"event"' in line
        try:
            obj = json.loads(line)
        except ValueError:
            if strict and looks_like_event:
                raise EventParseError(
                    f"line {lineno}: unparseable event line: {line[:200]}")
            continue
        if not isinstance(obj, dict):
            continue
        ev = obj.get("event")
        if isinstance(ev, str) and ev:
            yield obj
        elif strict and looks_like_event:
            raise EventParseError(
                f"line {lineno}: \"event\" key is not a non-empty "
                f"string: {line[:200]}")


def parse_events(text: str, strict: bool = False) -> List[dict]:
    """All event dicts in ``text`` (see :func:`iter_events`)."""
    return list(iter_events(text, strict=strict))


def events_of(text: str, name: str, strict: bool = False) -> List[dict]:
    """All events named ``name``, in emission order."""
    return [e for e in iter_events(text, strict=strict)
            if e["event"] == name]


def last_event(text: str, name: str) -> Optional[dict]:
    """The most recent event named ``name``, or None."""
    found = None
    for e in iter_events(text):
        if e["event"] == name:
            found = e
    return found
