"""Observability layer: tracing, step-phase telemetry, flight
recorder, structured events, straggler detection (docs/OBSERVABILITY.md).

Dependency-free (stdlib only) by design: every piece of it rides in
the same ConfigMap-shipped image as the launcher and must import in a
bare pod, a test harness, and the operator process alike.
"""

from k8s_tpu.obs.events import (  # noqa: F401
    events_of,
    last_event,
    parse_events,
)
from k8s_tpu.obs.straggler import (  # noqa: F401
    StragglerDetector,
    StragglerVerdict,
)
from k8s_tpu.obs.trace import (  # noqa: F401
    FlightRecorder,
    Tracer,
    arm_slow_host,
    default_tracer,
    dump_default,
    set_default_tracer,
)
