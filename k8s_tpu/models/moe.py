"""Mixture-of-Experts layer with expert parallelism.

Completes the EP row of SURVEY §2.5 (absent in the reference). A
top-2-gated expert MLP whose expert dimension is sharded over the
``expert`` mesh axis. The token→expert routing uses the dense
"einsum dispatch" formulation: dispatch/combine one-hot einsums lower
to all-to-all-shaped collectives under GSPMD, which is the
compiler-friendly (static-shape, MXU-dense) way to express MoE on TPU
— no scatter/gather, no dynamic shapes inside jit.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    num_experts: int = 8
    expert_capacity_factor: float = 2.0
    top_k: int = 2
    hidden_size: int = 128
    intermediate_size: int = 256
    dtype: jnp.dtype = jnp.bfloat16
    router_aux_loss_weight: float = 0.01
    # z-loss on the router logits (stabilizes their scale, ST-MoE §2.2)
    router_z_loss_weight: float = 1e-3


class MoeMlp(nn.Module):
    """Top-k routed expert SwiGLU MLP, capacity-bounded."""

    config: MoeConfig

    @nn.compact
    def __call__(self, x):  # [B, S, E_model]
        cfg = self.config
        b, s, d = x.shape
        n_tok = b * s
        e = cfg.num_experts
        capacity = max(
            1, int(cfg.expert_capacity_factor * n_tok * cfg.top_k / e)
        )

        tokens = x.reshape(n_tok, d)
        router_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("embed", "expert")
            ),
            name="router",
        )(tokens.astype(jnp.float32))  # [T, E]
        probs = jax.nn.softmax(router_logits, axis=-1)

        # top-k choice per token
        gate_vals, expert_idx = jax.lax.top_k(probs, cfg.top_k)  # [T, K]
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(axis=-1, keepdims=True), 1e-9
        )

        # position of each (token, k) within its expert's capacity
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)  # [T, K, E]
        # priority: k=0 assignments first, then token order
        flat = onehot.transpose(1, 0, 2).reshape(cfg.top_k * n_tok, e)
        pos_flat = jnp.cumsum(flat, axis=0) - flat  # [K·T, E]
        pos = pos_flat.reshape(cfg.top_k, n_tok, e).transpose(1, 0, 2)  # [T,K,E]
        within_cap = (pos < capacity) & (onehot > 0)
        slot = jnp.sum(pos * onehot, axis=-1)  # [T, K]

        # dispatch tensor [T, K, E, C] → combine over (K)
        slot_oh = jax.nn.one_hot(slot, capacity, dtype=x.dtype)  # [T,K,C]
        keep = within_cap.any(-1).astype(x.dtype)  # [T, K]
        dispatch = (
            onehot.astype(x.dtype)[..., None]
            * slot_oh[:, :, None, :]
            * keep[..., None, None]
        )  # [T, K, E, C]
        combine = dispatch * gate_vals[..., None, None].astype(x.dtype)

        # route tokens to expert buffers: [E, C, D]
        expert_in = jnp.einsum("tkec,td->ecd", dispatch, tokens)
        expert_in = nn.with_logical_constraint(expert_in, ("expert", None, "embed"))

        # expert MLPs (weights stacked on the expert axis)
        def pdense(features, axes, name):
            return self.param(
                name,
                nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(batch_axis=(0,)), axes
                ),
                (e, *features),
                jnp.float32,
            )

        w_gate = pdense((cfg.hidden_size, cfg.intermediate_size),
                        ("expert", "embed", "mlp"), "w_gate")
        w_up = pdense((cfg.hidden_size, cfg.intermediate_size),
                      ("expert", "embed", "mlp"), "w_up")
        w_down = pdense((cfg.intermediate_size, cfg.hidden_size),
                        ("expert", "mlp", "embed"), "w_down")
        h = jnp.einsum("ecd,edm->ecm", expert_in, w_gate.astype(cfg.dtype))
        u = jnp.einsum("ecd,edm->ecm", expert_in, w_up.astype(cfg.dtype))
        h = nn.silu(h) * u
        h = nn.with_logical_constraint(h, ("expert", None, "mlp"))
        expert_out = jnp.einsum("ecm,emd->ecd", h, w_down.astype(cfg.dtype))

        # combine back to tokens
        out = jnp.einsum("tkec,ecd->td", combine, expert_out)
        out = out.reshape(b, s, d)

        # load-balancing auxiliary loss (Switch-style): mean prob ×
        # fraction routed, summed over experts
        me = probs.mean(axis=0)  # [E]
        ce = onehot[:, 0, :].astype(jnp.float32).mean(axis=0)  # top-1 fraction
        aux_loss = cfg.router_aux_loss_weight * e * jnp.sum(me * ce)
        self.sow("intermediates", "router_aux_loss", aux_loss)
        # router z-loss: keeps logit magnitudes bounded so the f32
        # softmax stays well-conditioned at scale
        logz = jax.nn.logsumexp(router_logits, axis=-1)
        z_loss = cfg.router_z_loss_weight * jnp.mean(jnp.square(logz))
        self.sow("intermediates", "router_z_loss", z_loss)
        return out
