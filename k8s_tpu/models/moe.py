"""Mixture-of-Experts layer with expert parallelism.

Completes the EP row of SURVEY §2.5 (absent in the reference). A
top-k-gated expert MLP whose expert dimension is sharded over the
``expert`` mesh axis.

Token→expert routing is SORT-BASED with static shapes: flatten the
(token, k) assignments k-major, stable-argsort by expert (k=0
assignments win capacity slots first, then token order), compute each
assignment's slot within its expert from the sorted running index, and
scatter rows into the ``[E, C, D]`` expert buffers (out-of-capacity
assignments scatter to an out-of-bounds index and are dropped by
``mode="drop"``). Everything is fixed-shape, differentiable
(scatter/gather transpose to each other), and O(T·K + E·C·D) memory.

The first version of this layer used the GShard-style dense one-hot
"einsum dispatch" ([T, K, E, C] dispatch/combine tensors). That is
compiler-friendly but O(T²·k·capacity_factor) memory at fixed capacity
factor — fine for unit-test shapes, 4 TB at bench scale (T = 16k,
E = 8). The sort formulation is how MoE actually scales on TPU.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    num_experts: int = 8
    expert_capacity_factor: float = 2.0
    top_k: int = 2
    hidden_size: int = 128
    intermediate_size: int = 256
    dtype: jnp.dtype = jnp.bfloat16
    router_aux_loss_weight: float = 0.01
    # z-loss on the router logits (stabilizes their scale, ST-MoE §2.2)
    router_z_loss_weight: float = 1e-3


class MoeMlp(nn.Module):
    """Top-k routed expert SwiGLU MLP, capacity-bounded."""

    config: MoeConfig

    @nn.compact
    def __call__(self, x):  # [B, S, E_model]
        cfg = self.config
        b, s, d = x.shape
        n_tok = b * s
        e = cfg.num_experts
        capacity = max(
            1, int(cfg.expert_capacity_factor * n_tok * cfg.top_k / e)
        )

        tokens = x.reshape(n_tok, d)
        router_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("embed", "expert")
            ),
            name="router",
        )(tokens.astype(jnp.float32))  # [T, E]
        probs = jax.nn.softmax(router_logits, axis=-1)

        # top-k choice per token
        gate_vals, expert_idx = jax.lax.top_k(probs, cfg.top_k)  # [T, K]
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(axis=-1, keepdims=True), 1e-9
        )

        # sort-based dispatch, k-major so k=0 assignments claim
        # capacity slots first (then token order — stable sort)
        kt = cfg.top_k * n_tok
        flat_expert = expert_idx.T.reshape(kt)          # [K·T], k-major
        order = jnp.argsort(flat_expert, stable=True)   # sorted by expert
        sorted_expert = flat_expert[order]
        src_tok = order % n_tok                         # token of each entry
        # slot within expert = sorted running index − expert's start
        counts = jnp.zeros((e,), jnp.int32).at[flat_expert].add(1)
        starts = jnp.cumsum(counts) - counts            # exclusive prefix
        slot = jnp.arange(kt, dtype=jnp.int32) - starts[sorted_expert]
        keep = slot < capacity
        # out-of-capacity → index E*C, dropped by scatter mode="drop"
        buf_idx = jnp.where(keep, sorted_expert * capacity + slot,
                            e * capacity)

        # route tokens into expert buffers [E, C, D] (unique buf_idx:
        # one (expert, slot) pair per kept assignment)
        expert_in = (
            jnp.zeros((e * capacity, d), x.dtype)
            .at[buf_idx]
            .set(tokens[src_tok].astype(x.dtype), mode="drop")
            .reshape(e, capacity, d)
        )
        expert_in = nn.with_logical_constraint(expert_in, ("expert", None, "embed"))

        # expert MLPs (weights stacked on the expert axis)
        def pdense(features, axes, name):
            return self.param(
                name,
                nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(batch_axis=(0,)), axes
                ),
                (e, *features),
                jnp.float32,
            )

        w_gate = pdense((cfg.hidden_size, cfg.intermediate_size),
                        ("expert", "embed", "mlp"), "w_gate")
        w_up = pdense((cfg.hidden_size, cfg.intermediate_size),
                      ("expert", "embed", "mlp"), "w_up")
        w_down = pdense((cfg.intermediate_size, cfg.hidden_size),
                        ("expert", "mlp", "embed"), "w_down")
        h = jnp.einsum("ecd,edm->ecm", expert_in, w_gate.astype(cfg.dtype))
        u = jnp.einsum("ecd,edm->ecm", expert_in, w_up.astype(cfg.dtype))
        h = nn.silu(h) * u
        h = nn.with_logical_constraint(h, ("expert", None, "mlp"))
        expert_out = jnp.einsum("ecm,emd->ecd", h, w_down.astype(cfg.dtype))

        # combine back to tokens: gather each kept assignment's expert
        # output, weight by its (renormalized) gate, scatter-add over k
        gates_sorted = gate_vals.T.reshape(kt)[order].astype(x.dtype)
        safe_idx = jnp.where(keep, buf_idx, 0)  # clamped read, masked below
        picked = expert_out.reshape(e * capacity, d)[safe_idx]
        weighted = picked * (gates_sorted * keep.astype(x.dtype))[:, None]
        out = (
            jnp.zeros((n_tok, d), x.dtype).at[src_tok].add(weighted)
        ).reshape(b, s, d)

        # load-balancing auxiliary loss (Switch-style): mean prob ×
        # fraction routed, summed over experts
        me = probs.mean(axis=0)  # [E]
        top1 = jax.nn.one_hot(expert_idx[:, 0], e, dtype=jnp.float32)
        ce = top1.mean(axis=0)  # top-1 routed fraction per expert
        aux_loss = cfg.router_aux_loss_weight * e * jnp.sum(me * ce)
        self.sow("intermediates", "router_aux_loss", aux_loss)
        # router z-loss: keeps logit magnitudes bounded so the f32
        # softmax stays well-conditioned at scale
        logz = jax.nn.logsumexp(router_logits, axis=-1)
        z_loss = cfg.router_z_loss_weight * jnp.mean(jnp.square(logz))
        self.sow("intermediates", "router_z_loss", z_loss)
        return out
