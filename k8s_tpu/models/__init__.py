"""Model zoo for the five benchmark configs (BASELINE.md):

#1 CPU smoke (mesh check, launcher built-in) · #2 MNIST (v5e-8 DP) ·
#3 ResNet-50/ImageNet (v5p-16 DP) · #4 BERT-base (v5p-64 TP) ·
#5 Llama-3-8B (v5p-128 multi-slice FSDP).

All models are flax.linen with logical-axis partitioning metadata, so
the parallel strategy is a rules table (k8s_tpu.parallel.sharding), not
a model edit. Compute dtype is bf16 with f32 params/accumulation (MXU-
native), shapes static, layers scanned where depth warrants it.
"""

from k8s_tpu.models.mnist import MnistCNN  # noqa: F401
from k8s_tpu.models.resnet import ResNet, ResNet50  # noqa: F401
from k8s_tpu.models.bert import BertConfig, BertForPretraining  # noqa: F401
from k8s_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    LlamaForCausalLM,
    fuse_params_for_decode,
    generate,
    unroll_params_for_decode,
)
