"""MNIST CNN — benchmark config #2 (single-host v5e-8, SPMD DP).

The "hello world" the reference ran as ``tf_smoke``/MNIST samples
(``examples/tf_sample``); here a small conv net whose batch axis is
sharded over the whole mesh.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # x: [B, 28, 28, 1]
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
