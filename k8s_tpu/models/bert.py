"""BERT-base encoder + MLM/NSP pretraining heads — benchmark config #4
(pjit model-parallel on v5p-64).

Bidirectional (non-causal) attention on the same flash-attention
kernel, GELU MLP, learned positional embeddings, logical partitioning
identical in spirit to the Llama model so the TP rules table shards
heads/mlp/vocab over the ``tensor`` axis.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp

from k8s_tpu.ops.attention import flash_attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: jnp.dtype = jnp.bfloat16
    # HuggingFace-compatible heads: MLM transform (dense+gelu+LN before
    # the decoder, decoder with bias) and NSP pooler (dense+tanh on
    # [CLS]) — required to import pretrained HF BERT weights
    # (k8s_tpu/tools/hf_import.py). Off by default: the plain heads are
    # leaner for from-scratch pretraining.
    hf_head: bool = False
    # encoder gelu variant: None derives from hf_head (HF BERT uses the
    # exact erf gelu; the tanh approximation is marginally cheaper).
    # Set explicitly when fine-tuning a checkpoint across head configs
    # so the activation never changes out from under trained weights.
    exact_gelu: "bool | None" = None
    # W8A8 dynamic int8 on the encoder matmuls (qkv/o/fc_in/fc_out),
    # bf16 straight-through backward — same machinery and caveats as
    # LlamaConfig.quant (k8s_tpu/ops/quant.py): numerics change, OPT-IN
    # per config, never a default.
    quant: str = "none"
    # LayerNorms in bf16 instead of f32 (statistics still accumulate in
    # f32 inside the bf16 kernel's mean/var reduction). BERT is post-LN
    # — 25 norms touch the full residual stream every step, and in f32
    # they are pure HBM bandwidth. Opt-in: loss curves should be
    # validated per pretraining config.
    bf16_norms: bool = False
    # single [E, 3, H, D] qkv projection instead of three [E, H, D]
    # matmuls (one wider MXU dispatch). Changes the checkpoint layout —
    # opt-in, like Llama's fuse_params_for_decode.
    fused_qkv: bool = False
    # multi-device mesh: like LlamaConfig.mesh — when set (size > 1),
    # attention runs through the shard_map-wrapped flash kernel
    # (Mosaic can't be auto-partitioned by GSPMD)
    mesh: "object | None" = dataclasses.field(
        default=None, hash=False, compare=False
    )

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def use_exact_gelu(self) -> bool:
        return self.hf_head if self.exact_gelu is None else self.exact_gelu

    @staticmethod
    def base(**kw) -> "BertConfig":
        return BertConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "BertConfig":
        base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                    num_layers=2, num_heads=4, max_seq_len=128)
        base.update(kw)
        return BertConfig(**base)


def _dense(features, axes, name, dtype, axis=-1, quant="none"):
    extra = {}
    if quant != "none":
        from k8s_tpu.models.llama import _quant_extra

        extra = _quant_extra(quant)
    return nn.DenseGeneral(
        features=features,
        axis=axis,
        dtype=dtype,
        param_dtype=jnp.float32,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(stddev=0.02), axes
        ),
        name=name,
        **extra,
    )


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask=None):
        cfg = self.config
        h, d = cfg.num_heads, cfg.head_dim
        ln_dtype = cfg.dtype if cfg.bf16_norms else jnp.float32
        ln1 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=ln_dtype, name="ln_attn")
        ln2 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=ln_dtype, name="ln_mlp")
        if cfg.fused_qkv:
            qkv = _dense((3, h, d), ("embed", None, "heads", "head_dim"),
                         "qkv_proj", cfg.dtype, quant=cfg.quant)(x)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:
            q = _dense((h, d), ("embed", "heads", "head_dim"), "q_proj",
                       cfg.dtype, quant=cfg.quant)(x)
            k = _dense((h, d), ("embed", "heads", "head_dim"), "k_proj",
                       cfg.dtype, quant=cfg.quant)(x)
            v = _dense((h, d), ("embed", "heads", "head_dim"), "v_proj",
                       cfg.dtype, quant=cfg.quant)(x)
        q = nn.with_logical_constraint(q, ("batch", "length", "heads", "head_dim"))
        # padding mask rides the kernel's segment-id masking (1=real,
        # 0=pad): pad keys are invisible; pad-query outputs are garbage
        # and the MLM loss mask is expected to drop them
        if cfg.mesh is not None and getattr(cfg.mesh, "size", 1) > 1:
            from k8s_tpu.ops.attention import flash_attention_sharded

            attn = flash_attention_sharded(
                q, k, v, cfg.mesh, causal=False,
                segment_ids=attention_mask,
            )
        else:
            attn = flash_attention(
                q, k, v, causal=False, segment_ids=attention_mask
            )
        attn = _dense(cfg.hidden_size, ("heads_out", "head_dim", "embed"),
                      "o_proj", cfg.dtype, axis=(-2, -1), quant=cfg.quant)(attn)
        x = ln1(x + attn)
        y = _dense(cfg.intermediate_size, ("embed", "mlp"), "fc_in", cfg.dtype,
                   quant=cfg.quant)(x)
        # exact erf gelu matches HF BERT weights (cfg.use_exact_gelu)
        y = nn.gelu(y, approximate=not cfg.use_exact_gelu)
        y = nn.with_logical_constraint(y, ("batch", "length", "mlp"))
        y = _dense(cfg.hidden_size, ("mlp_down", "embed"), "fc_out", cfg.dtype,
                   quant=cfg.quant)(y)
        return ln2(x + y)


class BertForPretraining(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 return_hidden=False):
        """``return_hidden`` skips the MLM head and returns
        ``(hidden [B,S,E], nsp_logits)`` — feed hidden to
        :func:`k8s_tpu.ops.fused_ce.fused_lm_head_cross_entropy` with
        ``params["mlm_head"]["kernel"]`` so the [B,S,V] logits never
        materialize (the NSP head is two columns; it stays in-model)."""
        cfg = self.config
        b, s = input_ids.shape
        tok = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=jnp.float32,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("vocab", "embed")
            ),
            name="tok_embed",
        )(input_ids)
        pos = nn.Embed(
            cfg.max_seq_len, cfg.hidden_size, dtype=cfg.dtype, param_dtype=jnp.float32,
            name="pos_embed",
        )(jnp.broadcast_to(jnp.arange(s), (b, s)))
        x = tok + pos
        if token_type_ids is not None:
            x = x + nn.Embed(
                cfg.type_vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                param_dtype=jnp.float32, name="type_embed",
            )(token_type_ids)
        x = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps,
            dtype=cfg.dtype if cfg.bf16_norms else jnp.float32,
            name="ln_embed",
        )(x)
        for i in range(cfg.num_layers):
            x = BertLayer(cfg, name=f"layer_{i}")(x, attention_mask)

        if cfg.hf_head:
            # HF-compatible heads: the MLM transform runs BEFORE the
            # decoder, so return_hidden hands back the transformed
            # hidden states (feed fused CE with the decoder kernel AND
            # its bias); NSP goes through the tanh pooler
            t = nn.Dense(cfg.hidden_size, dtype=jnp.float32,
                         name="mlm_transform")(x)
            t = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                             name="mlm_transform_ln")(
                nn.gelu(t, approximate=False)
            )
            pooled = nn.tanh(
                nn.Dense(cfg.hidden_size, dtype=jnp.float32, name="pooler")(
                    x[:, 0]
                )
            )
            nsp_logits = nn.Dense(2, dtype=jnp.float32,
                                  name="nsp_head")(pooled)
            if return_hidden:
                return t, nsp_logits
            mlm_logits = nn.DenseGeneral(
                features=cfg.vocab_size, dtype=jnp.float32,
                param_dtype=jnp.float32,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.normal(stddev=0.02), ("embed", "vocab")
                ),
                name="mlm_head",
            )(t)
            return mlm_logits, nsp_logits

        if return_hidden:
            nsp_logits = nn.Dense(2, dtype=jnp.float32, name="nsp_head")(x[:, 0])
            return x, nsp_logits
        mlm_logits = nn.DenseGeneral(
            features=cfg.vocab_size, dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("embed", "vocab")
            ),
            name="mlm_head",
        )(x)
        nsp_logits = nn.Dense(2, dtype=jnp.float32, name="nsp_head")(x[:, 0])
        return mlm_logits, nsp_logits
