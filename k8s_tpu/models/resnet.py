"""ResNet-v1.5 — benchmark config #3 and the north-star metric
(steps/sec/chip on v5p-16, BASELINE.json).

TPU-first choices: NHWC layout (XLA-TPU native), bf16 convs, stride-2
in the 3×3 (the v1.5 variant used by the MLPerf reference results).
BatchNorm keeps f32 *statistics* (flax computes mean/var in f32) but
emits bf16 activations — measured +24% step throughput on v5e versus
f32 BN output, because ResNet training on v5e is HBM-bandwidth-bound
and f32 normalized activations double the elementwise traffic. The
optional space-to-depth stem (``stem="space_to_depth"``, ~1% faster,
opt-in because it changes conv_init's kernel shape and therefore the
checkpoint format) rewrites the 7×7/s2 conv on 3 channels — which pads
terribly onto the 128-wide MXU — as a 4×4/s1 conv on 12 channels after
a 2×2 space-to-depth rearrangement; with the explicit (2,1) padding
its receptive window contains the original 7×7 one, so the rewrite is
a strict functional superset. Under jit with a sharded batch,
the BatchNorm reductions become global (XLA inserts the cross-replica
psum), which is exactly synchronized-BN data parallelism — no
parameter server, no manual cross-replica averaging.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    norm_dtype: jnp.dtype = jnp.bfloat16  # output dtype; stats stay f32
    stem: str = "conv7"  # "conv7" | "space_to_depth"

    @nn.compact
    def __call__(self, x, train: bool = True):  # x: [B, H, W, 3]
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.norm_dtype,
        )
        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            B, H, W, C = x.shape
            if H % 2 or W % 2:
                raise ValueError(
                    f"space_to_depth stem requires even H and W, got {(H, W)}"
                )
            x = x.reshape(B, H // 2, 2, W // 2, 2, C)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, H // 2, W // 2, 4 * C)
            # padding (2,1): output pixel i sees original rows [2i-4, 2i+3],
            # which contains the 7x7/s2 window [2i-3, 2i+3] — the stem can
            # represent the original conv exactly
            x = conv(self.num_filters, (4, 4), (1, 1),
                     padding=[(2, 1), (2, 1)], name="conv_init")(x)
        elif self.stem == "conv7":
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}; "
                             "expected 'conv7' or 'space_to_depth'")
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(
                    self.num_filters * 2 ** i, strides, conv, norm
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3))
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3))
ResNet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3))
