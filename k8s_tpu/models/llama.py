"""Llama-family decoder — benchmark config #5 (Llama-3-8B FSDP on
multi-slice v5p-128 over DCN).

TPU-first transformer: RMSNorm (f32), rotary embeddings, grouped-query
attention running on the in-repo flash-attention pallas kernel (or the
ring-attention path when the ``seq`` mesh axis is >1 — long-context
context-parallelism, SURVEY §5's "must introduce" item), SwiGLU MLP,
bf16 compute / f32 params. Layers are ``nn.scan``-stacked (one XLA
while-loop, O(1) compile time in depth) with ``nn.remat``
rematerialization to trade FLOPs for HBM.

Every parameter carries logical-axis metadata
(``nn.with_logical_partitioning``), so DP/FSDP/TP/SP are rule-table
swaps (k8s_tpu.parallel.sharding.LogicalRules), not model edits.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from k8s_tpu.ops.attention import flash_attention
from k8s_tpu.ops.norms import rms_norm
from k8s_tpu.parallel.sharding import logical_constraint, sharded_embedding_lookup


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = True
    # "nothing_saveable": recompute everything in bwd (min HBM, the
    # default — at 705M/2k-seq on one v5e chip it lets batch 4 fit and
    # wins end-to-end); "dots": keep matmul outputs, recompute only
    # elementwise (halves the fittable batch at this scale; useful when
    # HBM is plentiful relative to model size, e.g. small models or
    # large FSDP meshes)
    remat_policy: str = "nothing_saveable"
    scan_layers: bool = True
    # "flash" (pallas kernel / XLA fallback), "ring" (KV rotates around
    # the `seq` ICI ring; requires mesh), or "ulysses" (all-to-all
    # re-shard seq->heads over `seq`; requires mesh, seq-degree must
    # divide the head counts)
    attention: str = "flash"
    mesh: Optional[object] = dataclasses.field(default=None, hash=False, compare=False)
    # Mixture-of-Experts: >0 replaces the dense MLP with a top-2 routed
    # expert MLP sharded over the `expert` mesh axis
    num_experts: int = 0
    expert_capacity_factor: float = 2.0
    # autoregressive decoding: attention reads/writes a static
    # [B, max_seq_len] KV cache ("cache" collection) instead of running
    # the training kernels; see :func:`generate`
    decode: bool = False
    # "int8": W8A8 forward on q/k/v and the MLP (2x MXU rate on v5e),
    # bf16 straight-through backward; "int8_bwd": int8 backward matmuls
    # too (EXPERIMENTAL numerics — validate convergence). Opt-in; embed,
    # lm_head, and o_proj stay high-precision (o_proj: measured net
    # loss when quantized — see the o_proj comment below and
    # k8s_tpu/ops/quant.py)
    quant: str = "none"
    # Fused projections: q/k/v as ONE [E, (Hq+2Hkv)*D] GEMM and
    # gate/up as ONE [E, 2F] GEMM (params "qkv_proj"/"gate_up_proj";
    # convert a canonical tree with fuse_params_for_decode). Math-
    # identical — the win is decode, where the per-step latency is
    # fusion-count-bound: 3 fewer GEMM dispatches per layer and x read
    # once per fused pair.
    fused_proj: bool = False
    # "int8": KV cache STORED int8 with per-row scales, dequantized in
    # VMEM by the fused decode kernel — halves the cache-read bandwidth
    # term that dominates long-context decode. Numerics change
    # (per-row symmetric quantization of cached k/v); opt-in.
    kv_quant: str = "none"
    # RAGGED decode (continuous batching): every batch row sits at its
    # own cache depth. The append index comes from ``positions[:, 0]``
    # per row instead of a shared scalar "cache_index" variable — the
    # caller (k8s_tpu/serving's engine) owns per-slot lengths and the
    # cache has no index state at all. Requires decode=True. Prefill
    # (s > 1) comes in two flavors: a FRESH cache is a first chunk
    # (offset 0 by contract — rides the flash kernel), a warm cache is
    # a CONTINUATION chunk appended at the per-row offset carried in
    # ``positions[:, 0]`` — chunked prefill writes a prompt into its
    # slot across multiple calls (the serving engine's token-budget
    # scheduler interleaves these chunks with decode).
    ragged_decode: bool = False

    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        return LlamaConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        base = dict(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32,
            max_seq_len=256, remat=False,
        )
        base.update(kw)
        return LlamaConfig(**base)


def _remat_policy(name: str):
    if name == "nothing_saveable":
        return jax.checkpoint_policies.nothing_saveable
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if name == "flash":
        # save the flash kernel's residuals (output + logsumexp, named
        # in its fwd rule) so the backward never re-runs the attention
        # forward; projections/norms/MLP still remat. ~50 MB/layer at
        # the bench config vs. the S^2-free attention recompute it buys.
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse"
        )
    if name == "flash_qkv":
        # flash + the post-rope q/k/v projections (~84 MB/layer at the
        # 705M bench): the backward then recomputes only norms + MLP
        # GEMMs. Numerics-identical to "flash"; pure memory-for-FLOPs.
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse", "attn_q", "attn_k", "attn_v"
        )
    raise ValueError(
        f"unknown remat_policy {name!r}; expected 'nothing_saveable', "
        "'dots', 'flash', or 'flash_qkv'"
    )


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding, [B, S, H, D] layout, f32 rotation."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _quant_extra(quant: str) -> dict:
    """kwargs for nn.DenseGeneral selecting the quantized dot_general —
    same params/metadata/shardings, only the compute changes."""
    if quant == "int8":
        from k8s_tpu.ops.quant import int8_dot_general

        return {"dot_general": int8_dot_general}
    if quant == "int8_bwd":
        from k8s_tpu.ops.quant import int8_dot_general_bwd8

        return {"dot_general": int8_dot_general_bwd8}
    if quant != "none":
        raise ValueError(f"unknown quant {quant!r}")
    return {}


def _dense(features, axes, name, dtype, quant="none"):
    if quant == "int8_serving":
        from k8s_tpu.ops.quant import Int8ServingDense

        # weight-only int8 for decode: kernel STORED int8 (+ scale),
        # params produced by quantize_params_for_serving
        return Int8ServingDense(
            features, n_in=1, dtype=dtype, axes=axes, name=name
        )
    extra = _quant_extra(quant)
    return nn.DenseGeneral(
        features=features,
        use_bias=False,
        dtype=dtype,
        param_dtype=jnp.float32,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), axes
        ),
        name=name,
        **extra,
    )


def _cached_attention(q, k_all, v_all, mask, scale):
    """Prefill/fallback attention against the full static cache.

    q [B, s, Hq, D], k/v HEAD-MAJOR [B, Hkv, max_seq, D], mask
    [B, s, max_seq] bool (True = visible). Bandwidth-bound einsum —
    single-token decode instead goes through the fused pallas kernel
    (:func:`k8s_tpu.ops.attention.decode_attention_update`)."""
    b, s, hq, d = q.shape
    _, hkv, smax, _ = k_all.shape
    groups = hq // hkv
    # k/v stay in cache dtype (bf16) on TPU: casting the full
    # [B, max_seq] cache to f32 would double the HBM traffic of a
    # bandwidth-bound op — preferred_element_type gives f32
    # accumulation without copies. The CPU backend cannot execute
    # bf16 x bf16 -> f32 dots (DotThunk limitation), so tests upcast.
    cdt = jnp.float32 if jax.default_backend() == "cpu" else q.dtype
    qf = (q.astype(jnp.float32) * scale).reshape(b, s, hkv, groups, d)
    logits = jnp.einsum(
        "bqhgd,bhkd->bhgqk", qf.astype(cdt), k_all.astype(cdt),
        preferred_element_type=jnp.float32,
    )
    logits = jnp.where(mask[:, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhgqk,bhkd->bqhgd", probs.astype(cdt), v_all.astype(cdt),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, s, hq, d).astype(q.dtype)


def _use_pallas_decode(head_dim: int, max_seq_len: int,
                       kv_q8: bool = False) -> bool:
    """Pallas decode kernel gate. Deliberately conservative:

    - TPU backend only (tests exercise the kernel in interpret mode)
    - single device only: the kernel is a plain pallas_call with no
      GSPMD partitioning rule, so under tensor-parallel serving it
      would force replication (or fail to lower) — the XLA cached-
      attention path is shardable and stays the multi-chip route
    - head_dim 128-aligned and cache length 8-aligned: the only shapes
      the Mosaic compilation is validated for (the bench model); the
      tiny e2e model (head_dim 16) falls back to XLA
    - cache slabs must FIT VMEM: the kernel stages the full [S, D] K
      and V slabs per (batch, kv-head) grid cell, so an oversized
      max_seq_len (≳24k at head_dim 128 bf16 on a ~16 MB-VMEM chip)
      would fail Mosaic compilation — such contexts fall back to the
      shardable XLA path instead of erroring (round-2 advisor finding)
    - ``KTPU_DISABLE_PALLAS_DECODE=1`` force-disables (escape hatch)
    """
    import os

    if os.environ.get("KTPU_DISABLE_PALLAS_DECODE"):
        return False
    if head_dim % 128 or max_seq_len % (32 if kv_q8 else 8):
        return False
    # K + V slabs (+ int8 scale rows) per grid cell vs a conservative
    # VMEM budget (leave headroom for q/out/accumulator tiles)
    bytes_per_elem = 1 if kv_q8 else 2
    slab_bytes = 2 * max_seq_len * head_dim * bytes_per_elem
    if kv_q8:
        slab_bytes += 2 * max_seq_len * 4  # f32 scale rows
    if slab_bytes > 12 * 1024 * 1024:
        return False
    try:
        return jax.default_backend() == "tpu" and len(jax.devices()) == 1
    except Exception:
        return False


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.config
        b, s, _ = x.shape
        h, kv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        if cfg.fused_proj:
            qkv = _dense((h + 2 * kv, d), ("embed", "heads", "head_dim"),
                         "qkv_proj", cfg.dtype, cfg.quant)(x)
            q, k, v = jnp.split(qkv, [h, h + kv], axis=-2)
        else:
            q = _dense((h, d), ("embed", "heads", "head_dim"), "q_proj",
                       cfg.dtype, cfg.quant)(x)
            k = _dense((kv, d), ("embed", "kv_heads", "head_dim"), "k_proj",
                       cfg.dtype, cfg.quant)(x)
            v = _dense((kv, d), ("embed", "kv_heads", "head_dim"), "v_proj",
                       cfg.dtype, cfg.quant)(x)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        q = logical_constraint(q, ("batch", "length", "heads", "head_dim"), cfg.mesh)
        k = logical_constraint(k, ("batch", "length", "kv_heads", "head_dim"), cfg.mesh)
        v = logical_constraint(v, ("batch", "length", "kv_heads", "head_dim"), cfg.mesh)
        # named so remat policies can pin the post-rope projections:
        # the flash backward consumes q/k/v directly, so saving them
        # (84 MB/layer at the 705M bench) removes the qkv-GEMM + rope
        # recompute from every layer's backward (policy "flash_qkv")
        from jax.ad_checkpoint import checkpoint_name

        q = checkpoint_name(q, "attn_q")
        k = checkpoint_name(k, "attn_k")
        v = checkpoint_name(v, "attn_v")
        if cfg.decode:
            if segment_ids is not None:
                raise NotImplementedError(
                    "packed segments are not supported in decode mode"
                )
            # Static-shape KV cache, HEAD-MAJOR [B, Hkv, S, D]: each
            # (batch, head)'s keys are a contiguous [S, D] slab — the
            # layout the fused decode kernel streams, and a better
            # einsum layout for the XLA path too. Prefill writes s
            # entries at the current index; decode appends one per
            # step through the fused kernel (attention + in-place
            # single-row cache update — the XLA fallback's functional
            # update copies the whole cache every step).
            if cfg.kv_quant not in ("none", "int8"):
                raise ValueError(
                    f"unknown kv_quant {cfg.kv_quant!r}; expected "
                    "'none' or 'int8'"
                )
            kv_q8 = cfg.kv_quant == "int8"
            cache_dtype = jnp.int8 if kv_q8 else cfg.dtype
            # Statically known BEFORE the variables are created: a
            # fresh cache means this apply() is the FIRST prefill call
            # (position 0) — the one case where prompt self-attention
            # is the complete answer and the flash kernel can serve
            # prefill with O(s·block) memory instead of the fallback's
            # O(s·max_seq) f32 score tensor (VERDICT r2 weak #4: 4k
            # one-shot prefill OOM'd and needed chunking).
            fresh_cache = not self.has_variable("cache", "cached_key")
            ck = self.variable(
                "cache", "cached_key",
                jnp.zeros, (b, kv, cfg.max_seq_len, d), cache_dtype,
            )
            cv = self.variable(
                "cache", "cached_value",
                jnp.zeros, (b, kv, cfg.max_seq_len, d), cache_dtype,
            )
            if kv_q8:
                # per-row dequant scales ride alongside the int8 cache
                # [B, Hkv, 1, S]: the trailing-(1, S) layout Mosaic
                # accepts for full-row scale blocks
                kscale = self.variable(
                    "cache", "key_scale",
                    jnp.zeros, (b, kv, 1, cfg.max_seq_len), jnp.float32,
                )
                vscale = self.variable(
                    "cache", "value_scale",
                    jnp.zeros, (b, kv, 1, cfg.max_seq_len), jnp.float32,
                )
            if cfg.ragged_decode:
                # engine-owned depths: positions[:, 0] IS the per-row
                # append index; the cache carries no index state. A
                # warm-cache s > 1 call is a chunked-prefill
                # continuation: rows [offset, offset+s) append at the
                # per-row offset and attention sees exactly
                # cache[:offset] + the chunk's own causal prefix (the
                # per-row position mask below)
                idx = None
                cur = positions[:, 0]
            else:
                idx = self.variable(
                    "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
                )
                cur = idx.value
            kh = k.transpose(0, 2, 1, 3).astype(cfg.dtype)  # [B,Hkv,s,D]
            vh = v.transpose(0, 2, 1, 3).astype(cfg.dtype)
            use_fused = s == 1 and _use_pallas_decode(
                d, cfg.max_seq_len, kv_q8
            )
            if use_fused and kv_q8:
                from k8s_tpu.ops.attention import decode_attention_update_q8

                (out, ck.value, cv.value, kscale.value, vscale.value) = (
                    decode_attention_update_q8(
                        q[:, 0], kh[:, :, 0], vh[:, :, 0],
                        ck.value, cv.value, kscale.value, vscale.value,
                        cur, scale=1.0 / math.sqrt(d),
                    )
                )
                out = out[:, None]
            elif use_fused:
                from k8s_tpu.ops.attention import decode_attention_update

                out, ck.value, cv.value = decode_attention_update(
                    q[:, 0], kh[:, :, 0], vh[:, :, 0],
                    ck.value, cv.value, cur,
                    scale=1.0 / math.sqrt(d),
                )
                out = out[:, None]  # [B, 1, Hq, D]
            else:
                # XLA-fallback cache writes. Three index regimes:
                # shared scalar (classic decode), ragged FIRST prefill
                # chunk (fresh cache, offset 0 by contract), ragged
                # per-row offsets via vmapped DUS (single-token decode
                # AND warm-cache continuation chunks — DUS writes all
                # s rows of a chunk at each row's own offset).
                if not cfg.ragged_decode:
                    row_at, scale_at = cur, cur
                elif s > 1 and fresh_cache:
                    row_at, scale_at = 0, 0
                else:
                    row_at = scale_at = None  # vmapped per-row below

                def _rows(cache_val, new):  # [B,H,S,D] <- [B,H,s,D]
                    if row_at is not None:
                        return jax.lax.dynamic_update_slice(
                            cache_val, new, (0, 0, row_at, 0)
                        )
                    return jax.vmap(
                        lambda c, n, p: jax.lax.dynamic_update_slice(
                            c, n, (0, p, 0)
                        )
                    )(cache_val, new, cur)

                def _scales(scale_val, new):  # [B,H,1,S] <- [B,H,1,s]
                    if scale_at is not None:
                        return jax.lax.dynamic_update_slice(
                            scale_val, new, (0, 0, 0, scale_at)
                        )
                    return jax.vmap(
                        lambda c, n, p: jax.lax.dynamic_update_slice(
                            c, n, (0, 0, p)
                        )
                    )(scale_val, new, cur)

                if kv_q8:
                    from k8s_tpu.ops.attention import quantize_kv_rows

                    kq, ksr = quantize_kv_rows(kh)
                    vq, vsr = quantize_kv_rows(vh)
                    ck.value = _rows(ck.value, kq)
                    cv.value = _rows(cv.value, vq)
                    kscale.value = _scales(kscale.value, ksr[:, :, None])
                    vscale.value = _scales(vscale.value, vsr[:, :, None])
                else:
                    ck.value = _rows(ck.value, kh)
                    cv.value = _rows(cv.value, vh)
                if s > 1 and fresh_cache:
                    # one-shot prefill: the prompt IS the whole visible
                    # context, so causal self-attention over the new
                    # k/v streams through the flash kernel — no
                    # max_seq-sized score tensor, no chunking needed.
                    # (flash_attention self-gates: off-shape models
                    # fall back to its XLA path, still O(s²) on the
                    # PROMPT only, never O(s·max_seq).)
                    out = flash_attention(
                        q, k, v, causal=True, scale=1.0 / math.sqrt(d)
                    )
                else:
                    # chunked continuation / single-token XLA fallback:
                    # attend against the full cache
                    if kv_q8:
                        k_all = (ck.value.astype(jnp.float32)
                                 * kscale.value[:, :, 0, :, None]).astype(cfg.dtype)
                        v_all = (cv.value.astype(jnp.float32)
                                 * vscale.value[:, :, 0, :, None]).astype(cfg.dtype)
                    else:
                        k_all, v_all = ck.value, cv.value
                    k_pos = jnp.arange(cfg.max_seq_len)
                    if cfg.ragged_decode:
                        # per-row visibility: row b sees cache[:pos_b]
                        # plus its own token at pos_b
                        mask = k_pos[None, None, :] <= positions[:, :, None]
                    else:
                        q_pos = cur + jnp.arange(s)  # this chunk, global
                        mask = jnp.broadcast_to(
                            k_pos[None, None, :] <= q_pos[None, :, None],
                            (b, s, cfg.max_seq_len),
                        )
                    out = _cached_attention(
                        q, k_all, v_all, mask, 1.0 / math.sqrt(d)
                    )
            if idx is not None:
                idx.value = cur + s
        elif cfg.attention == "ring":
            from k8s_tpu.parallel.ring_attention import ring_attention

            out = ring_attention(
                q, k, v, cfg.mesh, causal=True, segment_ids=segment_ids
            )
        elif cfg.attention == "ulysses":
            from k8s_tpu.parallel.ulysses import ulysses_attention

            out = ulysses_attention(
                q, k, v, cfg.mesh, causal=True, segment_ids=segment_ids
            )
        elif cfg.mesh is not None and getattr(cfg.mesh, "size", 1) > 1:
            # multi-device flash: the pallas kernel is per-device —
            # GSPMD can't partition Mosaic, so batch/heads shard via an
            # explicit shard_map (ops/attention.py)
            from k8s_tpu.ops.attention import flash_attention_sharded

            out = flash_attention_sharded(
                q, k, v, cfg.mesh, causal=True, segment_ids=segment_ids
            )
        else:
            out = flash_attention(q, k, v, causal=True, segment_ids=segment_ids)
        if cfg.quant == "int8_serving":
            from k8s_tpu.ops.quant import Int8ServingDense

            out = Int8ServingDense(
                cfg.hidden_size, n_in=2, dtype=cfg.dtype,
                axes=("heads_out", "head_dim", "embed"), name="o_proj",
            )(out)
        else:
            out = nn.DenseGeneral(
                features=cfg.hidden_size,
                axis=(-2, -1),
                use_bias=False,
                dtype=cfg.dtype,
                param_dtype=jnp.float32,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(),
                    ("heads_out", "head_dim", "embed"),
                ),
                # o_proj deliberately NOT quantized in TRAINING int8
                # mode: its K=H*D contraction is too small to amortize
                # the dynamic quantize pass (measured -4% end-to-end,
                # docs/BENCHMARKS.md). Serving mode quantizes it: the
                # weights are pre-quantized, so reading them at 1 B is
                # pure bandwidth win
                name="o_proj",
            )(out)
        return out


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        if cfg.fused_proj:
            gate_up = _dense(2 * cfg.intermediate_size, ("embed", "mlp"),
                             "gate_up_proj", cfg.dtype, cfg.quant)(x)
            gate, up = jnp.split(gate_up, 2, axis=-1)
        else:
            gate = _dense(cfg.intermediate_size, ("embed", "mlp"),
                          "gate_proj", cfg.dtype, cfg.quant)(x)
            up = _dense(cfg.intermediate_size, ("embed", "mlp"), "up_proj",
                        cfg.dtype, cfg.quant)(x)
        y = nn.silu(gate) * up
        y = logical_constraint(y, ("batch", "length", "mlp"), cfg.mesh)
        return _dense(cfg.hidden_size, ("mlp_down", "embed"), "down_proj", cfg.dtype,
                      cfg.quant)(y)


class RMSNorm(nn.Module):
    eps: float
    axis_name: str = "embed"

    @nn.compact
    def __call__(self, x):
        w = self.param(
            "weight",
            nn.with_logical_partitioning(nn.initializers.ones, (self.axis_name,)),
            (x.shape[-1],),
            jnp.float32,
        )
        return rms_norm(x, w, self.eps)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.config
        x = logical_constraint(x, ("batch", "length", "embed"), cfg.mesh)
        h = RMSNorm(cfg.rms_eps, name="input_norm")(x)
        x = x + LlamaAttention(cfg, name="attn")(h, positions, segment_ids)
        h = RMSNorm(cfg.rms_eps, name="post_attn_norm")(x)
        if cfg.num_experts > 0:
            from k8s_tpu.models.moe import MoeConfig, MoeMlp

            moe_cfg = MoeConfig(
                num_experts=cfg.num_experts,
                expert_capacity_factor=cfg.expert_capacity_factor,
                hidden_size=cfg.hidden_size,
                intermediate_size=cfg.intermediate_size,
                dtype=cfg.dtype,
            )
            x = x + MoeMlp(moe_cfg, name="moe_mlp")(h)
        else:
            x = x + LlamaMLP(cfg, name="mlp")(h)
        return x


class _ScannedBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids):
        return LlamaBlock(self.config, name="block")(x, positions, segment_ids), None


class LlamaForCausalLM(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(
        self, input_ids, positions=None, segment_ids=None,
        last_logit_only=False, return_hidden=False,
    ):
        """input_ids [B, S] int32. For packed pretraining pass
        ``segment_ids`` ([B, S]: which document each token belongs to;
        attention is masked across documents) and ``positions``
        (restarting at 0 per document so RoPE sees local offsets).
        ``last_logit_only`` computes the lm_head for the final position
        only — prefill wants [B, 1, V], not [B, plen, V].
        ``return_hidden`` skips the lm_head and returns the final-norm
        hidden states [B, S, E] — the input contract of
        :func:`k8s_tpu.ops.fused_ce.fused_lm_head_cross_entropy`, which
        fuses the head matmul into the loss so the [B, S, V] logits are
        never materialized (load-bearing at 128k vocab)."""
        cfg = self.config
        b, s = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s), (b, s))
        embed = nn.Embed(
            cfg.vocab_size,
            cfg.hidden_size,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("vocab", "embed")
            ),
            name="embed_tokens",
        )
        # use-site-gathered lookup with explicit boundary shardings —
        # see parallel.sharding.sharded_embedding_lookup (shared with
        # the pipeline apply path so the two lookups cannot drift)
        x = sharded_embedding_lookup(
            embed.embedding, input_ids, cfg.mesh, dtype=cfg.dtype)
        if cfg.scan_layers:
            block_cls = _ScannedBlock
            if cfg.remat:
                block_cls = nn.remat(
                    block_cls,
                    prevent_cse=False,
                    policy=_remat_policy(cfg.remat_policy),
                )
            x, _ = nn.scan(
                block_cls,
                variable_axes={"params": 0, "cache": 0, "intermediates": 0},
                split_rngs={"params": True},
                in_axes=nn.broadcast,
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, name="layers")(x, positions, segment_ids)
        else:
            block = LlamaBlock
            if cfg.remat:
                block = nn.remat(
                    block,
                    prevent_cse=False,
                    policy=_remat_policy(cfg.remat_policy),
                )
            for i in range(cfg.num_layers):
                x = block(cfg, name=f"layer_{i}")(x, positions, segment_ids)
        x = RMSNorm(cfg.rms_eps, name="final_norm")(x)
        if return_hidden:
            return x
        if last_logit_only:
            x = x[:, -1:]
        if cfg.quant == "int8_serving":
            from k8s_tpu.ops.quant import Int8ServingDense

            return Int8ServingDense(
                cfg.vocab_size, n_in=1, dtype=jnp.float32,
                axes=("embed", "vocab"), name="lm_head",
            )(x)
        logits = nn.DenseGeneral(
            features=cfg.vocab_size,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")
            ),
            name="lm_head",
        )(x)
        return logits


def _pick_token(logits_last, r, temperature):
    if temperature == 0.0:
        return jnp.argmax(logits_last, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        r, logits_last / temperature, axis=-1
    ).astype(jnp.int32)


def unroll_params_for_decode(params, num_layers: int):
    """Stacked (``scan_layers=True``, trained) params tree → per-layer
    (``scan_layers=False``) layout for serving. Decode with an
    UNROLLED layer loop is the big decode win: a scanned stacked cache
    carry costs full-cache copies plus per-layer slab dynamic-slice/
    update traffic every step (measured 56% → 75% of the decode
    bandwidth roofline at batch 8; docs/BENCHMARKS.md)."""
    out = {k: v for k, v in params.items() if k != "layers"}
    block = params["layers"]["block"]
    for i in range(num_layers):
        out[f"layer_{i}"] = jax.tree_util.tree_map(lambda x: x[i], block)
    return out


def fuse_params_for_decode(params):
    """Rewrite a canonical (trained) params tree into the
    ``fused_proj=True`` layout: q/k/v kernels concatenated on the heads
    axis into ``qkv_proj`` and gate/up on the features axis into
    ``gate_up_proj``. Math-identical; the scan-stacked leading layer
    axis passes through. Compose BEFORE quantize_params_for_serving."""

    def rewrite(d):
        if not isinstance(d, dict):
            return d
        if {"q_proj", "k_proj", "v_proj"} <= set(d):
            out = {k: v for k, v in d.items()
                   if k not in ("q_proj", "k_proj", "v_proj")}
            out["qkv_proj"] = {
                "kernel": jnp.concatenate(
                    [d["q_proj"]["kernel"], d["k_proj"]["kernel"],
                     d["v_proj"]["kernel"]], axis=-2,
                )
            }
            return {k: rewrite(v) for k, v in out.items()}
        if {"gate_proj", "up_proj"} <= set(d):
            out = {k: v for k, v in d.items()
                   if k not in ("gate_proj", "up_proj")}
            out["gate_up_proj"] = {
                "kernel": jnp.concatenate(
                    [d["gate_proj"]["kernel"], d["up_proj"]["kernel"]],
                    axis=-1,
                )
            }
            return {k: rewrite(v) for k, v in out.items()}
        return {k: rewrite(v) for k, v in d.items()}

    return rewrite(params)


# module-level jits keyed on (model, static shapes): defining these
# inside generate() would make every generate() call a fresh function
# object → jit cache miss → FULL RECOMPILE per call (measured 5.8x
# decode slowdown before the hoist, 409 → 2,367 tok/s at batch 8).
# params/cache go through jit as ARGUMENTS: a jitted closure over
# concrete weight arrays embeds them as HLO constants, which makes
# compilation pathologically slow.
@functools.partial(jax.jit, static_argnames=("model", "temperature", "chunk"))
def _prefill(model, params, prompt_ids, r, temperature, chunk=0):
    """Prompt ingestion. The default (``chunk=0``) runs the whole
    prompt in ONE forward: a fresh-cache prefill routes attention
    through the flash kernel (causal self-attention over the prompt,
    O(plen·block) memory), so no chunking is needed at any prompt
    length. ``chunk`` > 0 remains as the legacy/ablation path: it
    processes the prompt in chunks through the cache-fallback
    attention, whose continuation chunks materialize
    [B, Hq, chunk, max_seq] f32 scores."""
    b, plen = prompt_ids.shape
    cache = None
    start = 0
    sizes = []
    if chunk and plen > chunk:
        head = plen % chunk
        sizes = ([head] if head else []) + [chunk] * (plen // chunk)
    else:
        sizes = [plen]
    for size in sizes:
        ids = jax.lax.slice_in_dim(prompt_ids, start, start + size, axis=1)
        positions = jnp.broadcast_to(
            start + jnp.arange(size), (b, size)
        )
        variables = {"params": params}
        if cache is not None:
            variables["cache"] = cache
        logits, mut = model.apply(
            variables, ids, positions=positions,
            last_logit_only=True, mutable=["cache"],
        )
        cache = mut["cache"]
        start += size
    return cache, _pick_token(logits[:, -1], r, temperature)


@functools.partial(
    jax.jit, static_argnames=("model", "new_tokens", "temperature")
)
def _decode_loop(model, params, cache, tok, r, plen, new_tokens, temperature):
    # plen is a DYNAMIC operand (only seeds the position carry):
    # keeping it static would recompile the whole decode scan for
    # every distinct prompt length
    b = tok.shape[0]

    def step(carry, _):
        cache, tok, pos, r = carry
        r, r_step = jax.random.split(r)
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            positions=jnp.full((b, 1), pos, jnp.int32),
            mutable=["cache"],
        )
        nxt = _pick_token(logits[:, -1], r_step, temperature)
        return (mut["cache"], nxt, pos + 1, r), tok

    return jax.lax.scan(
        step, (cache, tok, plen.astype(jnp.int32), r), None,
        length=new_tokens - 1,
    )


def _auto_prefill_chunk(plen: int, head_dim: int) -> int:
    """One-shot flash prefill (0) only when the pallas kernel will
    actually engage: alignment AND a TPU backend — flash_attention's
    non-TPU / off-shape fallback is the quadratic XLA path whose
    [B, Hq, plen, plen] f32 scores this gate exists to avoid."""
    flash_ok = (plen % 128 == 0 and head_dim % 64 == 0
                and jax.default_backend() == "tpu")
    return 0 if flash_ok else 512


def generate(
    model: LlamaForCausalLM,
    params,
    prompt_ids: jax.Array,  # [B, prompt_len] int32
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    prefill_chunk: Optional[int] = None,
) -> jax.Array:
    """Autoregressive generation with a static KV cache.

    ``model.config.decode`` must be True. Prefill runs the whole prompt
    in one jitted forward (lm_head on the final position only, writing
    the cache), then one token decodes per step under a jitted
    ``lax.scan`` — fixed shapes throughout, two compilations total
    (cached across calls: the jits are module-level, keyed on the
    model and static shapes). temperature 0 = greedy, else softmax
    sampling. Returns [B, max_new_tokens].

    ``prefill_chunk=None`` auto-selects: one-shot flash prefill
    (``0``) when the prompt can actually ride the flash kernel
    (plen % 128 == 0 and head_dim % 64 == 0 — its Mosaic alignment
    gate), else the chunked cache-path prefill (``512``) whose memory
    is capped at O(chunk·max_seq) — an un-aligned long prompt must NOT
    fall into flash_attention's XLA fallback, which materializes
    [B, Hq, plen, plen] f32 scores (~8 GB at batch 8 / 4000 tokens).
    Pass an explicit value to force either path.
    """
    cfg = model.config
    if not cfg.decode:
        raise ValueError("generate() needs LlamaConfig(decode=True)")
    b, plen = prompt_ids.shape
    if max_new_tokens <= 0:
        return jnp.zeros((b, 0), jnp.int32)
    if plen + max_new_tokens > cfg.max_seq_len:
        raise ValueError(
            f"prompt {plen} + new {max_new_tokens} exceeds cache "
            f"size {cfg.max_seq_len}"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    rng, prefill_rng = jax.random.split(rng)

    if prefill_chunk is None:
        prefill_chunk = _auto_prefill_chunk(plen, cfg.head_dim)
    cache, tok = _prefill(model, params, prompt_ids, prefill_rng,
                           temperature, chunk=prefill_chunk)

    if max_new_tokens == 1:
        return tok[:, None]

    (_, last, _, _), toks = _decode_loop(
        model, params, cache, tok, rng, jnp.int32(plen), max_new_tokens,
        temperature,
    )
    # toks holds the inputs of each step (tokens 0..n-2); append the last
    out = jnp.concatenate([toks, last[None]], axis=0)  # [new, B]
    return out.transpose(1, 0)
