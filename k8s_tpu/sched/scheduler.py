"""Cluster scheduler decision core (docs/SCHEDULER.md).

Pure decision logic in the StragglerDetector/SloAutoscaler idiom: an
injected clock, no I/O, no threads — the Controller feeds it job
requests and drives :meth:`ClusterScheduler.tick`; every verdict is
returned as data for the operator to act on (spawn a reconciler, drive
a preempt flush, export gauges). That is what makes the whole decision
table unit-testable on a fake clock, including the O(100)-job scale
matrix.

Decision rules, in order, per tick (full table in docs/SCHEDULER.md):

1. pending jobs are scanned by (priority desc, submit order) —
   priority orders admission, FIFO breaks ties;
2. a re-queued preemption victim in its cooldown window is skipped
   (no-flap: a victim must not be re-admitted into the churn that just
   evicted it);
3. per-queue quota is metered in CHIPS: a queue at quota blocks only
   its own jobs, never the other queues;
4. a job whose whole gang footprint fits is admitted — slices charge
   atomically, a partial gang is never placed;
5. a job that does not fit may PREEMPT: victims must be preemptible,
   strictly lower priority, on the same accelerator; they are chosen
   by (priority asc, checkpoint cost asc) — cost = steps at risk since
   the victim's last healthy checkpoint, read from the goodput
   telemetry — and only taken if the freed slices actually fit the
   preemptor (never preempt uselessly);
6. a capacity-blocked job RESERVES its accelerator for the rest of the
   scan: nothing behind it in the order may backfill onto that pool
   (starvation protection for big gangs — head-of-line reservation);
7. with ``backfill=True`` (docs/SCHEDULER.md "Placement"), the
   reservation is priced instead of absolute: the reserved job gets an
   expected-start horizon (free slices + slices it could preempt at
   any moment + declared ``runtimeEstimateSeconds`` finish times of
   the jobs it is waiting out), and a strictly-smaller job behind it
   may slot into the gap ONLY when it provably cannot move that
   horizon — it finishes before the horizon, or the pool still holds
   the reserved gang's slices at the horizon even with it running.
   Zero starvation is asserted per round: after the scan the horizon
   is recomputed and must not have regressed (:class:`StarvationError`
   is a scheduler bug, exactly like OversubscriptionError).
"""

from __future__ import annotations

import logging
import math
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from k8s_tpu.sched.inventory import Footprint, SliceInventory

log = logging.getLogger(__name__)

DEFAULT_QUEUE = "default"
# Re-admission hold-off after a preemption: long enough for the
# victim's preempt flush + teardown to land before its next placement
# is even considered (no-flap), short enough that a freed slice is
# never idle for long. Overridable per scheduler (tests run it at 0).
DEFAULT_PREEMPTION_COOLDOWN = 5.0


class StarvationError(RuntimeError):
    """A backfill admission moved a reserved job's expected-start
    horizon later (scheduler invariant bug — backfill must be free)."""


@dataclass
class JobRequest:
    """One job as the scheduler sees it (derived from spec.scheduling
    + the footprint lookup; the scheduler never reads a CRD).

    ``runtime_estimate_s`` is the operator-declared expected runtime
    (``scheduling.runtimeEstimateSeconds``; 0 = undeclared). It is
    advisory and only ever used by conservative backfill — a job is
    never killed for outliving its estimate, it just stops being
    eligible to slot into reservation gaps."""

    key: str
    footprint: Footprint = field(default_factory=Footprint)
    priority: int = 0
    queue: str = DEFAULT_QUEUE
    preemptible: bool = True
    runtime_estimate_s: float = 0.0
    seq: int = 0  # submit order, assigned by the scheduler

    def sort_key(self):
        return (-self.priority, self.seq)


@dataclass(frozen=True)
class Preemption:
    """One eviction verdict: ``victim`` loses its slices to
    ``preemptor``; ``cost`` is the victim's priced checkpoint cost
    (steps at risk since its last save) at decision time."""

    victim: str
    preemptor: str
    queue: str  # the VICTIM's queue
    cost: int = 0


@dataclass
class TickResult:
    admitted: List[JobRequest] = field(default_factory=list)
    preempted: List[Preemption] = field(default_factory=list)
    # key → human-readable reason the job stayed queued this tick
    blocked: Dict[str, str] = field(default_factory=dict)
    # key → machine-readable WHY for the same jobs, one of BLOCKED_*:
    # the reconciler surfaces this in the Queued condition so a parked
    # job tells the operator which lever (quota? capacity? estimate?)
    # would move it
    blocked_category: Dict[str, str] = field(default_factory=dict)
    # the admitted keys that entered through a reservation gap
    backfilled: List[str] = field(default_factory=list)


# blocked_category vocabulary (stable strings — surfaced in conditions)
BLOCKED_COOLDOWN = "cooldown"
BLOCKED_QUOTA = "quota"
BLOCKED_NO_POOL = "no-pool"
BLOCKED_CAPACITY = "capacity"
BLOCKED_RESERVATION = "reservation"
BLOCKED_BACKFILL_REFUSED = "backfill-refused"


@dataclass
class _Reservation:
    """Head-of-line reservation, priced: ``horizon`` is the absolute
    clock time the reserved gang can expect to start (``math.inf``
    when the jobs it waits on declared no runtime estimate), and
    ``avail_at_horizon`` the slices projected free at that instant —
    current free + slices held by jobs the reserved gang may preempt
    whenever it likes (the victim-pricing input: their eviction is
    already paid for by priority) + slices returned by declared-
    estimate finishes. Slack-based backfill draws this balance down;
    it must never dip below the reserved gang's own need."""

    key: str
    slices: int
    horizon: float
    avail_at_horizon: int


class ClusterScheduler:
    """Quota + priority + bin-packing + preemption over one inventory.

    ``quotas`` meters chips per queue (absent queue = unlimited).
    ``cost_fn(key) -> int`` prices a running job's eviction (steps at
    risk since its last healthy checkpoint — the operator wires it to
    the goodput telemetry; defaults to 0 = cheapest).
    ``backfill`` turns the head-of-line reservation from an absolute
    wall into a priced one (decision rule 7; default off — the
    decision table is bit-identical to the pre-backfill scheduler
    until the operator opts in)."""

    def __init__(
        self,
        inventory: SliceInventory,
        quotas: Optional[Dict[str, int]] = None,
        clock: Callable[[], float] = time.monotonic,
        cost_fn: Optional[Callable[[str], int]] = None,
        preemption_cooldown: float = DEFAULT_PREEMPTION_COOLDOWN,
        backfill: bool = False,
    ):
        self.inventory = inventory
        self.quotas = dict(quotas or {})
        self.clock = clock
        self.cost_fn = cost_fn
        self.preemption_cooldown = preemption_cooldown
        self.backfill = backfill
        self._pending: Dict[str, JobRequest] = {}
        self._running: Dict[str, JobRequest] = {}
        self._holdoff: Dict[str, float] = {}
        # when each running job was (re-)admitted: remaining-runtime
        # estimates for the backfill horizon count from here
        self._admitted_at: Dict[str, float] = {}
        # every key that has ever held a head-of-line reservation, and
        # the cumulative backfill count — the bench's starvation audit
        # and the ktpu_sched_backfill_total counter feed
        self.reserved_ever: set = set()
        self.backfills_total = 0
        self._last_blocked: Dict[str, Tuple[str, str]] = {}
        self._seq = 0
        import threading

        self._lock = threading.RLock()

    # ------------------------------------------------------------- intake

    def submit(self, req: JobRequest) -> bool:
        """Enqueue a job (idempotent: a key already pending or running
        is left untouched — watch replays must not re-order the queue).
        Returns True when the request was newly enqueued."""
        with self._lock:
            if req.key in self._pending or req.key in self._running:
                return False
            self._seq += 1
            req.seq = self._seq
            self._pending[req.key] = req
            return True

    def update_pending(self, req: JobRequest) -> bool:
        """Replace a PENDING job's terms in place (spec edited while
        queued — no reconciler exists to police immutability yet, and
        the ledger must charge what the reconciler will actually
        materialize on admission). Queue position (seq) and any
        cooldown are preserved. Running jobs are left alone: their
        charge reflects placed reality."""
        with self._lock:
            cur = self._pending.get(req.key)
            if cur is None:
                return False
            req.seq = cur.seq
            self._pending[req.key] = req
            return True

    def adopt_running(self, req: JobRequest) -> None:
        """Adoption path (operator restart): the gang is already
        physically running, so it is charged FORCE — the ledger must
        reflect reality even if a config shrink made reality exceed
        capacity (logged; the pool admits nothing until it drains)."""
        with self._lock:
            if req.key in self._running:
                return
            self._pending.pop(req.key, None)
            self._seq += 1
            req.seq = self._seq
            if not self.inventory.fits(req.footprint):
                log.warning(
                    "adopting %s (%s) over capacity — fleet shrank "
                    "under a running gang; pool blocked until it drains",
                    req.key, req.footprint)
            self.inventory.charge(req.key, req.footprint, force=True)
            self._running[req.key] = req
            # estimates restart from adoption time: conservative (an
            # adopted gang mid-run looks LONGER than it is, never
            # shorter — backfill horizons may only be pessimistic)
            self._admitted_at[req.key] = self.clock()

    def remove(self, key: str) -> bool:
        """The job is gone (terminal or deleted): drop it from wherever
        it is and free its slices."""
        with self._lock:
            self._holdoff.pop(key, None)
            self._admitted_at.pop(key, None)
            if self._pending.pop(key, None) is not None:
                return True
            if self._running.pop(key, None) is not None:
                self.inventory.release(key)
                return True
            return False

    def reinstate(self, req: JobRequest) -> None:
        """Return a just-admitted job to the queue WITHOUT losing its
        submit order — the operator could not act on the admission
        (previous reconciler still winding down, or the footprint
        changed under the decision). Slices are released; ``req.seq``
        is preserved so the job keeps its head-of-line position (the
        no-flap contract ``requeue`` honors for preemption victims);
        no cooldown — nothing was torn down."""
        with self._lock:
            if self._running.pop(req.key, None) is not None:
                self.inventory.release(req.key)
            self._admitted_at.pop(req.key, None)
            if req.seq <= 0:
                self._seq += 1
                req.seq = self._seq
            self._pending[req.key] = req

    def resize_running(self, key: str, new_fp: Footprint,
                       require_pool_deficit: bool = False) -> bool:
        """Re-admit a RUNNING job's reshaped footprint in place — the
        elastic-resize ledger move (docs/ELASTIC.md): the inventory
        swap is atomic (shrink frees slices, grow re-charges them, the
        high-water mark never sees both shapes at once), and the
        running request's terms are updated so later decisions (quota
        pricing, victim selection) see the real shape. Returns False —
        changing nothing — when the job is not running here, the grown
        footprint does not fit, or ``require_pool_deficit`` is set and
        the pool is no longer over-subscribed (an inventory-triggered
        shrink whose deficit another gang's shrink already absorbed:
        N gangs sharing a pool must surrender exactly ONE slice per
        revoked slice, not one each); the caller keeps the old shape
        and re-decides against the fresh inventory next tick."""
        with self._lock:
            req = self._running.get(key)
            if req is None:
                return False
            if (require_pool_deficit
                    and self.inventory.available(new_fp.accelerator) >= 0):
                log.info(
                    "inventory-triggered shrink of %s refused: pool "
                    "'%s' deficit already absorbed", key,
                    new_fp.accelerator)
                return False
            try:
                self.inventory.recharge(key, new_fp)
            except Exception as e:
                log.warning("resize of %s to %s refused: %s",
                            key, new_fp, e)
                return False
            req.footprint = new_fp
            return True

    def requeue(self, key: str, cooldown: Optional[float] = None) -> bool:
        """Move a RUNNING job back to the queue (the preemption /
        chaos-eviction path): slices freed, original submit order kept
        (a victim re-enters ahead of later arrivals at its priority),
        re-admission held off for the cooldown window."""
        with self._lock:
            req = self._running.pop(key, None)
            if req is None:
                return False
            self.inventory.release(key)
            self._admitted_at.pop(key, None)
            self._pending[key] = req
            cd = self.preemption_cooldown if cooldown is None else cooldown
            self._holdoff[key] = self.clock() + cd
            return True

    # ------------------------------------------------------------- reads

    def running_keys(self, preemptible_only: bool = False) -> List[str]:
        with self._lock:
            return sorted(
                k for k, r in self._running.items()
                if (not preemptible_only
                    or (r.preemptible and not r.footprint.empty)))

    def pending_keys(self) -> List[str]:
        with self._lock:
            return sorted(self._pending)

    def next_holdoff_expiry(self) -> Optional[float]:
        """Clock time when the earliest pending job's preemption
        cooldown expires (None = nothing held). The event-driven tick
        loop wakes exactly then instead of discovering the expiry one
        periodic backstop later (docs/SCHEDULER.md)."""
        with self._lock:
            now = self.clock()
            expiries = [t for k, t in self._holdoff.items()
                        if k in self._pending and t > now]
            return min(expiries) if expiries else None

    def is_running(self, key: str) -> bool:
        with self._lock:
            return key in self._running

    def running_request(self, key: str) -> Optional[JobRequest]:
        with self._lock:
            return self._running.get(key)

    def queue_used_chips(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for r in self._running.values():
                out[r.queue] = out.get(r.queue, 0) + r.footprint.chips
            return out

    def stats(self) -> Dict[str, Dict]:
        """The gauge feed (ktpu_sched_*): queue depths, quota usage,
        free slices per pool, per-pool placement scoring, and the last
        tick's per-job blocked verdicts (category + readable reason —
        the Queued-condition diagnosability feed)."""
        with self._lock:
            depth: Dict[str, int] = {}
            for r in self._pending.values():
                depth[r.queue] = depth.get(r.queue, 0) + 1
            return {
                "queue_depth": depth,
                "quota_used_chips": self.queue_used_chips(),
                "pools": self.inventory.snapshot(),
                "placement": self.inventory.placement_stats(),
                "running": len(self._running),
                "pending": len(self._pending),
                "backfills_total": self.backfills_total,
                "blocked": {
                    k: {"category": c, "reason": r}
                    for k, (c, r) in self._last_blocked.items()
                    if k in self._pending
                },
            }

    # ------------------------------------------------------------- decide

    def tick(self) -> TickResult:
        """One scheduling round over the pending queue. Deterministic:
        same submissions + same clock ⇒ same decisions, in the same
        order (the O(100) scale test replays a whole run twice and
        compares decision logs)."""
        with self._lock:
            now = self.clock()
            result = TickResult()
            reserved: Dict[str, _Reservation] = {}  # accelerator → head
            quota_used = self.queue_used_chips()
            for req in sorted(self._pending.values(),
                              key=JobRequest.sort_key):
                fp = req.footprint
                hold = self._holdoff.get(req.key, 0.0)
                if now < hold:
                    self._block(result, req, BLOCKED_COOLDOWN,
                                f"preemption cooldown "
                                f"({hold - now:.1f}s left)")
                    continue
                if fp.empty:
                    self._admit(req, result, quota_used)
                    continue
                quota = self.quotas.get(req.queue)
                used = quota_used.get(req.queue, 0)
                if quota is not None and used + fp.chips > quota:
                    self._block(result, req, BLOCKED_QUOTA,
                                f"queue '{req.queue}' quota: "
                                f"{used}+{fp.chips} > {quota} chips")
                    continue
                if not self.inventory.knows(fp.accelerator):
                    self._block(result, req, BLOCKED_NO_POOL,
                                f"fleet has no '{fp.accelerator}' pool")
                    continue
                if fp.accelerator in reserved:
                    res = reserved[fp.accelerator]
                    if not self.backfill:
                        self._block(result, req, BLOCKED_RESERVATION,
                                    f"held behind higher-priority "
                                    f"{res.key} waiting on "
                                    f"{fp.accelerator}")
                        continue
                    ok, why = self._backfill_check(req, res, now)
                    if ok:
                        self._admit(req, result, quota_used)
                        result.backfilled.append(req.key)
                        self.backfills_total += 1
                        continue
                    self._block(result, req, BLOCKED_BACKFILL_REFUSED,
                                f"backfill behind {res.key} refused: "
                                f"{why}")
                    continue
                if self.inventory.fits(fp):
                    self._admit(req, result, quota_used)
                    continue
                victims = self._select_victims(req)
                if victims is None:
                    self._block(result, req, BLOCKED_CAPACITY,
                                f"capacity: {fp} > "
                                f"{self.inventory.available(fp.accelerator)} "
                                f"free {fp.accelerator} slices")
                    # head-of-line reservation: nothing behind this job
                    # may take the pool it is waiting for — except,
                    # under rule 7, a backfill that provably cannot
                    # delay it
                    reserved[fp.accelerator] = self._reservation_for(
                        req, now)
                    self.reserved_ever.add(req.key)
                    continue
                for victim, cost in victims:
                    self._running.pop(victim.key, None)
                    self.inventory.release(victim.key)
                    self._admitted_at.pop(victim.key, None)
                    self._pending[victim.key] = victim
                    self._holdoff[victim.key] = (
                        now + self.preemption_cooldown)
                    quota_used[victim.queue] = max(
                        0, quota_used.get(victim.queue, 0)
                        - victim.footprint.chips)
                    result.preempted.append(Preemption(
                        victim=victim.key, preemptor=req.key,
                        queue=victim.queue, cost=cost))
                self._admit(req, result, quota_used)
            # zero-starvation invariant, asserted every round exactly
            # like the oversubscription high-water mark: whatever this
            # round backfilled, no reservation's expected start may
            # have moved later. A violation is a bug in the safety
            # rules, not an operational condition.
            if self.backfill:
                for accel, res in reserved.items():
                    head = self._pending.get(res.key)
                    if head is None:
                        continue
                    fresh = self._reservation_for(head, now)
                    if fresh.horizon > res.horizon + 1e-6:
                        raise StarvationError(
                            f"backfill delayed reserved {res.key} on "
                            f"{accel}: expected start moved "
                            f"{res.horizon:.1f} → {fresh.horizon:.1f}")
            self._last_blocked = {
                k: (result.blocked_category[k], r)
                for k, r in result.blocked.items()
            }
            return result

    @staticmethod
    def _block(result: TickResult, req: JobRequest, category: str,
               reason: str) -> None:
        result.blocked[req.key] = reason
        result.blocked_category[req.key] = category

    def _remaining_estimate(self, req: JobRequest,
                            now: float) -> Optional[float]:
        """Declared-estimate remaining runtime of a RUNNING job (None
        when it declared nothing — an unbounded job for horizon math)."""
        est = req.runtime_estimate_s or 0.0
        if est <= 0:
            return None
        started = self._admitted_at.get(req.key, now)
        return max(0.0, est - (now - started))

    def _reservation_for(self, req: JobRequest,
                         now: float) -> _Reservation:
        """Price the head-of-line reservation: walk the pool's running
        jobs; slices held by jobs ``req`` may preempt at will (its
        priced victims) count as available immediately, declared-
        estimate jobs return their slices at their expected finish,
        undeclared jobs never (math.inf — conservative). The horizon is
        the earliest instant the cumulative balance covers the gang."""
        fp = req.footprint
        free = max(0, self.inventory.available(fp.accelerator))
        victim_slices = 0
        finishers: List[Tuple[float, int]] = []  # (remaining_s, slices)
        for r in self._running.values():
            if (r.footprint.empty
                    or r.footprint.accelerator != fp.accelerator):
                continue
            if r.preemptible and r.priority < req.priority:
                victim_slices += r.footprint.slices
                continue
            rem = self._remaining_estimate(r, now)
            if rem is not None:
                finishers.append((rem, r.footprint.slices))
        finishers.sort()
        have = free + victim_slices
        horizon = math.inf
        if have >= fp.slices:  # races only: tick would have admitted
            horizon = now
        else:
            for rem, s in finishers:
                have += s
                if have >= fp.slices:
                    horizon = now + rem
                    break
        if math.isinf(horizon):
            avail = free + victim_slices
        else:
            avail = free + victim_slices + sum(
                s for rem, s in finishers
                if now + rem <= horizon + 1e-9)
        return _Reservation(req.key, fp.slices, horizon, avail)

    def _backfill_check(self, req: JobRequest, res: _Reservation,
                        now: float) -> Tuple[bool, str]:
        """Decision rule 7's safety proof, per candidate. A backfill is
        admitted only on one of two grounds, both of which keep the
        reservation horizon fixed by construction:

        - **gap-fit**: the candidate declared a runtime estimate and
          finishes before the horizon — the slices it borrows are back
          before the reserved gang can use them;
        - **slack**: even if the candidate runs forever, the pool still
          holds the reserved gang's slices at the horizon
          (``avail_at_horizon`` is drawn down so stacked backfills
          share one slack budget, not each the whole of it).

        Everything else — bigger-than-the-gang, no free slices, no
        declared estimates to price the horizon with — is refused with
        the reason in hand."""
        fp = req.footprint
        if fp.slices >= res.slices:
            return False, (
                f"{fp.slices} slices is not strictly smaller than the "
                f"reserved gang's {res.slices}")
        if not self.inventory.fits(fp):
            return False, "no free slices to backfill into"
        if math.isinf(res.horizon):
            return False, (
                "reservation has no expected-start horizon (running "
                "jobs declared no runtimeEstimateSeconds)")
        est = req.runtime_estimate_s or 0.0
        if est > 0 and now + est <= res.horizon + 1e-9:
            return True, "fits inside the reservation gap"
        if res.avail_at_horizon - fp.slices >= res.slices:
            res.avail_at_horizon -= fp.slices
            return True, "leaves slack at the reservation horizon"
        return False, (
            f"would hold slices the reserved gang needs at its "
            f"expected start (in {res.horizon - now:.1f}s)")

    def _admit(self, req: JobRequest, result: TickResult,
               quota_used: Dict[str, int]) -> None:
        self._pending.pop(req.key, None)
        self._holdoff.pop(req.key, None)
        self.inventory.charge(req.key, req.footprint)  # raises on bug
        self._running[req.key] = req
        self._admitted_at[req.key] = self.clock()
        quota_used[req.queue] = (
            quota_used.get(req.queue, 0) + req.footprint.chips)
        result.admitted.append(req)

    def _select_victims(self, req: JobRequest):
        """Pick the cheapest sufficient victim set for ``req``:
        candidates are preemptible, STRICTLY lower priority, on the
        same pool; ordered by (priority asc, checkpoint cost asc,
        newest first) so the least important work with the least
        un-checkpointed progress is evicted first. Returns
        ``[(victim, cost), ...]`` or None when even evicting every
        candidate would not fit the gang — in which case nobody is
        evicted at all (an eviction that cannot place the preemptor is
        pure loss)."""
        fp = req.footprint
        cands = []
        for r in self._running.values():
            if (not r.preemptible or r.footprint.empty
                    or r.footprint.accelerator != fp.accelerator
                    or r.priority >= req.priority):
                continue
            cost = 0
            if self.cost_fn is not None:
                try:
                    cost = max(0, int(self.cost_fn(r.key)))
                except Exception:  # pricing must never break placement
                    cost = 0
            cands.append((r, cost))
        cands.sort(key=lambda rc: (rc[0].priority, rc[1], -rc[0].seq))
        freed = 0
        chosen = []
        available = self.inventory.available(fp.accelerator)
        for r, cost in cands:
            if available + freed >= fp.slices:
                break
            chosen.append((r, cost))
            freed += r.footprint.slices
        if available + freed < fp.slices:
            return None
        return chosen
