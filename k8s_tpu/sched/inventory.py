"""Slice inventory: the cluster's TPU capacity model.

The reference design doc targets O(100) concurrent jobs per cluster
(PAPER.md, tf_job_design_doc.md:24-26) but placed every job's pods
independently — two jobs could both believe they owned the last free
slice. This module gives the operator ONE ledger of truth:

- capacity comes from the controller-config ``fleet:`` block
  (accelerator type → number of slices of that shape the cluster owns);
- every admitted job is charged its **gang footprint**, derived from
  ``spec.tpu`` through the existing :mod:`k8s_tpu.spec.topology`
  lookup. A training gang charges ``numSlices`` WHOLE slices
  atomically (a slice is all-or-nothing — there is no partial gang);
  a serving fleet charges one single-host slice per replica over its
  full autoscale range (``maxReplicas``), so a scale-up can never
  discover mid-flight that the chips it was promised are gone.

The inventory enforces the zero-oversubscription invariant at the
charge site — :class:`OversubscriptionError` is a scheduler bug, not a
recoverable condition — and keeps a high-water mark per accelerator so
tests can assert the invariant held across a whole run, not just at
the end.

Placement (docs/SCHEDULER.md "Placement"): a pool may additionally
declare a :class:`PoolTopology` — its slices become NAMED positions on
a grid of ICI pods (each pod a linear chain of ``slicesPerPod``
positions; ICI contiguity exists only WITHIN a pod, pods talk over
DCN). ``charge`` then also plans and returns a
:class:`SliceAssignment` — which concrete positions the gang holds —
via the pure scorer :func:`plan_placement`: multi-slice gangs prefer
an ICI-contiguous block, single slices best-fit into the smallest free
block so the large contiguous blocks future gangs need stay whole.
The counting ledger stays the ONLY admission authority: with no
topology configured nothing below changes at all, and even with one,
placement annotates decisions but never vetoes them (a gang that fits
by count but not contiguously is placed fragmented, not refused —
multislice runs over DCN).
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from k8s_tpu.spec import topology as topo


class OversubscriptionError(RuntimeError):
    """A charge would exceed fleet capacity (scheduler invariant bug)."""


@dataclass(frozen=True)
class Footprint:
    """What one admitted job costs the fleet.

    ``slices`` whole slices of ``accelerator`` are charged atomically;
    ``chips`` (= slices × chips/slice) is the quota currency — queues
    meter chips so one v5p-512 counts 64× a v5e-8 wherever quotas mix
    shapes. ``per_replica`` marks serving fleets (each replica is an
    independent single-host slice, charged over the autoscale range)."""

    accelerator: str = ""
    slices: int = 0
    chips: int = 0
    per_replica: bool = False

    @property
    def empty(self) -> bool:
        """Zero-footprint jobs (no ``tpu:`` block — CPU smoke jobs,
        control-plane-only workloads) bypass the inventory entirely."""
        return self.slices <= 0 or not self.accelerator

    def __str__(self) -> str:
        if self.empty:
            return "no accelerator footprint"
        kind = "replica-slice" if self.per_replica else "slice"
        return (f"{self.slices} × {self.accelerator} {kind}"
                f"{'s' if self.slices != 1 else ''} ({self.chips} chips)")


def footprint_of(spec) -> Footprint:
    """Derive a job spec's gang footprint via the ``spec.topology``
    lookup. Unknown accelerators yield an EMPTY footprint on purpose:
    the spec will fail validation in the reconciler with the readable
    error, instead of queueing forever behind capacity that cannot
    exist."""
    tpu = getattr(spec, "tpu", None)
    if tpu is None or not tpu.accelerator:
        return Footprint()
    t = topo.lookup(tpu.accelerator)
    if t is None:
        return Footprint()
    serving = getattr(spec, "serving", None)
    if serving is not None:
        # per-replica economics over the WHOLE autoscale range: the
        # slices an SLO scale-up may claim are reserved at admission
        n = max(serving.replicas, serving.bounds()[1])
        return Footprint(tpu.accelerator, slices=n, chips=n * t.chips,
                         per_replica=True)
    n = max(1, tpu.num_slices)
    return Footprint(tpu.accelerator, slices=n, chips=n * t.chips)


# ---------------------------------------------------------------------------
# Named slices: pool topology + assignments + the pure placement scorer
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PoolTopology:
    """ICI shape of one pool: ``pods`` independent ICI domains, each a
    linear chain of ``slices_per_pod`` slice positions. Position ``p``
    on the global grid lives in pod ``p // slices_per_pod``; two
    positions are ICI-adjacent iff they are in the same pod at
    consecutive indices. (A linear chain is deliberately the whole
    model: it captures the thing the scorer must protect — contiguous
    blocks are scarce and fragmentation destroys them — without
    modeling torus wraparound the fleet config cannot express yet.)"""

    pods: int
    slices_per_pod: int

    @property
    def positions(self) -> int:
        return self.pods * self.slices_per_pod

    def validate(self) -> None:
        if self.pods <= 0 or self.slices_per_pod <= 0:
            raise ValueError(
                f"pool topology needs positive pods/slicesPerPod, got "
                f"{self.pods}×{self.slices_per_pod}")


@dataclass(frozen=True)
class SliceAssignment:
    """Which concrete grid positions one admitted gang holds.
    ``contiguous`` is True when the whole gang sits on ICI-adjacent
    positions inside one pod (the multislice fast path); single-slice
    jobs are trivially contiguous."""

    accelerator: str
    positions: Tuple[int, ...]
    slices_per_pod: int
    contiguous: bool

    def pods(self) -> Tuple[int, ...]:
        return tuple(sorted({p // self.slices_per_pod
                             for p in self.positions}))

    def __str__(self) -> str:
        coords = ",".join(
            f"{p // self.slices_per_pod}.{p % self.slices_per_pod}"
            for p in self.positions)
        kind = "ici-contiguous" if self.contiguous else "dcn-spanning"
        return f"{self.accelerator}[{coords}] ({kind})"


def _free_runs(free: Set[int], t: PoolTopology) -> List[Tuple[int, int]]:
    """Maximal runs of free positions that do not cross a pod boundary,
    as ``(start, length)`` ascending by start. Pure."""
    runs: List[Tuple[int, int]] = []
    start = None
    for p in range(t.positions):
        boundary = p % t.slices_per_pod == 0
        if p in free and not (boundary and start is not None):
            if start is None:
                start = p
            continue
        if start is not None:
            runs.append((start, p - start))
            start = None
        if p in free:  # run ended exactly at a pod boundary
            start = p
    if start is not None:
        runs.append((start, t.positions - start))
    return runs


def plan_placement(free: Set[int], t: PoolTopology, slices: int,
                   packing: bool = True) -> Tuple[Tuple[int, ...], bool]:
    """The pure placement scorer. Given the free positions of one pool,
    pick ``slices`` of them. Returns ``(positions, contiguous)``;
    callers guarantee ``len(free) >= slices`` (admission is counting).

    ``packing=True`` (the backfill+pack policy):

    - a multi-slice gang takes the SMALLEST free in-pod run that still
      holds it whole (best-fit: exact fits are consumed first, the big
      contiguous blocks survive for bigger gangs); when no single run
      fits, it falls back to consuming the smallest runs first — the
      fragments — so the spill costs the least future contiguity;
    - a single slice best-fits the same way: into the smallest run,
      never splitting a large block a gang could have used.

    ``packing=False`` models a topology-blind ledger: first-fit at the
    lowest free positions, whatever that does to the blocks."""
    if slices <= 0:
        return (), True
    runs = _free_runs(free, t)
    if not packing:
        chosen = sorted(free)[:slices]
        contiguous = any(
            s <= chosen[0] and chosen[-1] < s + ln
            for s, ln in runs) and (
            chosen[-1] - chosen[0] + 1 == slices)
        return tuple(chosen), contiguous
    fitting = [(ln, s) for s, ln in runs if ln >= slices]
    if fitting:
        ln, s = min(fitting)
        return tuple(range(s, s + slices)), True
    # no single in-pod run holds the gang: spend the smallest fragments
    # first so the largest surviving block stays as large as possible
    chosen: List[int] = []
    for ln, s in sorted((ln, s) for s, ln in runs):
        take = min(ln, slices - len(chosen))
        chosen.extend(range(s, s + take))
        if len(chosen) >= slices:
            break
    return tuple(sorted(chosen)), False


class SliceInventory:
    """The fleet ledger: capacity per accelerator type, charges per job.

    Thread-safe (the scheduler mutates it under its own lock, but
    metrics exporters and tests read it from other threads).

    ``topology`` optionally names the slices of some pools (see
    :class:`PoolTopology`); those pools additionally track WHICH
    positions each holder owns and ``charge``/``recharge`` return the
    planned :class:`SliceAssignment`. ``packing`` selects the scorer
    policy (:func:`plan_placement`); it changes assignments only,
    never admission counts."""

    def __init__(self, fleet: Dict[str, int],
                 topology: Optional[Dict[str, PoolTopology]] = None,
                 packing: bool = True):
        self._capacity: Dict[str, int] = {
            a: int(n) for a, n in (fleet or {}).items() if int(n) > 0
        }
        self._used: Dict[str, int] = {a: 0 for a in self._capacity}
        self._holders: Dict[str, Footprint] = {}
        self._lock = threading.RLock()
        # per-accelerator high-water mark: lets a scale test assert the
        # zero-oversubscription invariant held over the WHOLE run
        self.max_used: Dict[str, int] = {a: 0 for a in self._capacity}
        # capacity-return listeners (docs/ELASTIC.md): called with the
        # accelerator name whenever free slices INCREASE (a release, an
        # elastic shrink, a pool grow) — the elastic-resize grow tick.
        # Called OUTSIDE the lock: a listener that re-enters the
        # inventory (or nudges a reconciler) must never deadlock it.
        self._capacity_listeners: list = []
        # ------------------------------------------------ named slices
        self.packing = bool(packing)
        self._topology: Dict[str, PoolTopology] = {}
        # accelerator → position → holder key (occupied positions only)
        self._grid: Dict[str, Dict[int, str]] = {}
        # positions administratively off after a capacity shrink —
        # they stay on the grid (coordinates are physical) but the
        # scorer may not place on them
        self._revoked: Dict[str, Set[int]] = {}
        self._assignments: Dict[str, SliceAssignment] = {}
        # contiguity hit-rate inputs (multi-slice placements only)
        self.contiguity_requests: Dict[str, int] = {}
        self.contiguity_hits: Dict[str, int] = {}
        for a, t in (topology or {}).items():
            if a not in self._capacity:
                continue
            t.validate()
            self._topology[a] = t
            self._grid[a] = {}
            self._revoked[a] = set()
            self.contiguity_requests[a] = 0
            self.contiguity_hits[a] = 0
            self._sync_topology_locked(a)

    # ------------------------------------------------------------- reads

    def knows(self, accelerator: str) -> bool:
        with self._lock:
            return accelerator in self._capacity

    def capacity(self, accelerator: str) -> int:
        with self._lock:
            return self._capacity.get(accelerator, 0)

    def used(self, accelerator: str) -> int:
        with self._lock:
            return self._used.get(accelerator, 0)

    def available(self, accelerator: str) -> int:
        with self._lock:
            return (self._capacity.get(accelerator, 0)
                    - self._used.get(accelerator, 0))

    def fits(self, fp: Footprint) -> bool:
        if fp.empty:
            return True
        with self._lock:
            return (fp.accelerator in self._capacity
                    and self.available(fp.accelerator) >= fp.slices)

    def holder(self, key: str) -> Optional[Footprint]:
        with self._lock:
            return self._holders.get(key)

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """Per-pool view for metrics/tests. ``free`` is clamped at 0:
        a pool driven over capacity by adoption or a config shrink has
        zero UNASSIGNED slices, not a negative number — the
        ktpu_sched_slices_free gauge must stay sane; ``available()``
        (the decision input) stays unclamped so admission still sees
        the deficit."""
        with self._lock:
            return {
                a: {"capacity": c, "used": self._used.get(a, 0),
                    "free": max(0, c - self._used.get(a, 0))}
                for a, c in self._capacity.items()
            }

    def topology(self, accelerator: str) -> Optional[PoolTopology]:
        with self._lock:
            return self._topology.get(accelerator)

    def assignment(self, key: str) -> Optional[SliceAssignment]:
        with self._lock:
            return self._assignments.get(key)

    def fragmentation(self, accelerator: str) -> float:
        """How broken the pool's free space is: ``1 − largest free
        in-pod run / total free positions`` (0 = every free slice sits
        in one contiguous block, →1 = pure confetti; 0 when the pool
        is full or has no topology)."""
        with self._lock:
            t = self._topology.get(accelerator)
            if t is None:
                return 0.0
            free = self._free_positions_locked(accelerator)
            if not free:
                return 0.0
            runs = _free_runs(free, t)
            return 1.0 - max(ln for _s, ln in runs) / len(free)

    def contiguity_hit_rate(self, accelerator: str) -> Optional[float]:
        """Fraction of multi-slice placements that landed ICI-contiguous
        (None until the pool has seen one)."""
        with self._lock:
            n = self.contiguity_requests.get(accelerator, 0)
            if n == 0:
                return None
            return self.contiguity_hits.get(accelerator, 0) / n

    def placement_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-topology-pool scoring feed (the ktpu_sched_fragmentation
        / contiguity gauges): empty when no pool declares a topology."""
        with self._lock:
            out: Dict[str, Dict[str, float]] = {}
            for a, t in self._topology.items():
                free = self._free_positions_locked(a)
                runs = _free_runs(free, t) if free else []
                out[a] = {
                    "fragmentation": self.fragmentation(a),
                    "largest_free_block": float(
                        max((ln for _s, ln in runs), default=0)),
                    "contiguity_requests": float(
                        self.contiguity_requests.get(a, 0)),
                    "contiguity_hits": float(
                        self.contiguity_hits.get(a, 0)),
                }
            return out

    # ------------------------------------------------------------- writes

    def charge(self, key: str, fp: Footprint,
               force: bool = False) -> Optional[SliceAssignment]:
        """Charge ``key``'s whole footprint atomically. ``force`` is the
        adoption path ONLY (an operator restart re-adopting a gang that
        is already physically running must never kill it over a ledger
        it cannot have corrupted) — everywhere else an over-capacity
        charge raises, because admitting past capacity is exactly the
        two-jobs-own-one-slice bug this subsystem exists to end.

        Returns the planned :class:`SliceAssignment` when the pool has
        a topology (None otherwise, and None for a force-charge the
        grid has no room for — the counting ledger still holds the
        charge; placement never overrules it)."""
        if fp.empty:
            return None
        with self._lock:
            self._charge_count_locked(key, fp, force)
            return self._place_locked(key, fp)

    def _charge_count_locked(self, key: str, fp: Footprint,
                             force: bool) -> None:
        if key in self._holders:
            raise ValueError(f"{key} is already charged")
        if not force and not self.fits(fp):
            raise OversubscriptionError(
                f"charging {key} ({fp}) would oversubscribe "
                f"{fp.accelerator}: used {self.used(fp.accelerator)}"
                f"/{self.capacity(fp.accelerator)} slices")
        self._used[fp.accelerator] = (
            self._used.get(fp.accelerator, 0) + fp.slices)
        self._capacity.setdefault(fp.accelerator, 0)
        self._holders[key] = fp
        self.max_used[fp.accelerator] = max(
            self.max_used.get(fp.accelerator, 0),
            self._used[fp.accelerator])

    def release(self, key: str) -> Optional[Footprint]:
        with self._lock:
            fp = self._holders.pop(key, None)
            if fp is not None:
                self._used[fp.accelerator] = max(
                    0, self._used.get(fp.accelerator, 0) - fp.slices)
                self._unplace_locked(key, fp.accelerator)
        if fp is not None and not fp.empty:
            self._notify_capacity(fp.accelerator)
        return fp

    def recharge(self, key: str, fp: Footprint) -> Optional[SliceAssignment]:
        """Atomically replace ``key``'s charge with ``fp`` — the
        elastic-resize ledger move (docs/ELASTIC.md): a shrink frees
        slices and a grow re-charges them in ONE critical section, so
        no observer (and no high-water mark) ever sees the job owning
        both shapes at once, and a grow that would oversubscribe raises
        WITHOUT losing the old charge (the gang still physically holds
        its current slices). On a topology pool the gang resizes IN
        PLACE: a shrink surrenders its highest positions, a grow
        extends from its existing ones — a resize is a re-partition of
        the same hardware, not a move."""
        freed = False
        with self._lock:
            old = self._holders.pop(key, None)
            old_asg = self._assignments.get(key)
            if old is not None:
                self._used[old.accelerator] = max(
                    0, self._used.get(old.accelerator, 0) - old.slices)
                self._unplace_locked(key, old.accelerator, sync=False)
            try:
                if not fp.empty:
                    self._charge_count_locked(key, fp, force=False)
            except Exception:
                if old is not None:  # restore the old charge untouched
                    self._used[old.accelerator] = (
                        self._used.get(old.accelerator, 0) + old.slices)
                    self._holders[key] = old
                    if old_asg is not None:
                        self._restore_locked(key, old_asg)
                raise
            asg = (self._place_locked(key, fp, prefer=old_asg)
                   if not fp.empty else None)
            if old is not None:
                self._sync_topology_locked(old.accelerator)
            freed = (old is not None and not old.empty
                     and (fp.empty or fp.slices < old.slices
                          or fp.accelerator != old.accelerator))
        if freed:
            self._notify_capacity(old.accelerator)
        return asg

    def set_capacity(self, accelerator: str, slices: int) -> None:
        """Resize one pool (node-pool scale events, the
        permanent-pod-loss chaos fault). Shrinking below current usage
        never retro-preempts — running gangs keep their slices and the
        pool simply admits nothing until it drains back under the new
        capacity (the no-flap rule: inventory flaps must not translate
        into admission/preemption churn). Growing the pool notifies the
        capacity-return listeners (the elastic grow tick). On a
        topology pool a shrink revokes concrete FREE positions (highest
        first); when usage exceeds the new capacity the revocation debt
        is collected from future releases instead — same no-flap rule,
        expressed in named slices."""
        grew = False
        with self._lock:
            if slices <= 0:
                self._capacity.pop(accelerator, None)
            else:
                grew = int(slices) > self._capacity.get(accelerator, 0)
                self._capacity[accelerator] = int(slices)
            self._sync_topology_locked(accelerator)
        if grew:
            self._notify_capacity(accelerator)

    # --------------------------------------------------- placement (locked)

    def _free_positions_locked(self, accelerator: str) -> Set[int]:
        t = self._topology[accelerator]
        taken = set(self._grid[accelerator]) | self._revoked[accelerator]
        return {p for p in range(t.positions) if p not in taken}

    def _place_locked(self, key: str, fp: Footprint,
                      prefer: Optional[SliceAssignment] = None
                      ) -> Optional[SliceAssignment]:
        t = self._topology.get(fp.accelerator)
        if t is None:
            return None
        free = self._free_positions_locked(fp.accelerator)
        keep: Tuple[int, ...] = ()
        if (prefer is not None
                and prefer.accelerator == fp.accelerator):
            # in-place resize: retain the (lowest) positions the gang
            # already physically holds, plan only the delta
            keep = tuple(sorted(prefer.positions))[:fp.slices]
            free -= set(keep)
        needed = fp.slices - len(keep)
        if len(free) < needed:
            # force-charge past capacity (adoption over a shrunken
            # fleet): the annotation cannot name slices that do not
            # exist — the counting ledger still records the deficit
            return None
        extra, _ = plan_placement(free, t, needed, self.packing)
        positions = tuple(sorted(keep + extra))
        contiguous = self._contiguous(positions, t)
        asg = SliceAssignment(fp.accelerator, positions,
                              t.slices_per_pod, contiguous)
        grid = self._grid[fp.accelerator]
        for p in positions:
            grid[p] = key
        self._assignments[key] = asg
        if fp.slices > 1:
            self.contiguity_requests[fp.accelerator] = (
                self.contiguity_requests.get(fp.accelerator, 0) + 1)
            if contiguous:
                self.contiguity_hits[fp.accelerator] = (
                    self.contiguity_hits.get(fp.accelerator, 0) + 1)
        return asg

    @staticmethod
    def _contiguous(positions: Tuple[int, ...], t: PoolTopology) -> bool:
        if len(positions) <= 1:
            return True
        lo, hi = positions[0], positions[-1]
        return (hi - lo + 1 == len(positions)
                and lo // t.slices_per_pod == hi // t.slices_per_pod)

    def _unplace_locked(self, key: str, accelerator: str,
                        sync: bool = True) -> None:
        asg = self._assignments.pop(key, None)
        if asg is None or accelerator not in self._grid:
            return
        grid = self._grid[accelerator]
        for p in asg.positions:
            if grid.get(p) == key:
                del grid[p]
        if sync:
            # a shrink may be waiting on this release to collect its
            # revocation debt (set_capacity below usage never preempts)
            self._sync_topology_locked(accelerator)

    def _restore_locked(self, key: str, asg: SliceAssignment) -> None:
        grid = self._grid.get(asg.accelerator)
        if grid is None:
            return
        for p in asg.positions:
            grid[p] = key
        self._revoked[asg.accelerator] -= set(asg.positions)
        self._assignments[key] = asg

    def _sync_topology_locked(self, accelerator: str) -> None:
        """Reconcile the revoked-position set with the counting
        capacity: grid positions beyond capacity are revoked (highest
        FREE positions first — never an occupied one), and a grow
        un-revokes (lowest first) or extends the grid by whole pods."""
        t = self._topology.get(accelerator)
        if t is None:
            return
        cap = self._capacity.get(accelerator, 0)
        if cap > t.positions:
            pods = math.ceil(cap / t.slices_per_pod)
            t = PoolTopology(pods, t.slices_per_pod)
            self._topology[accelerator] = t
        revoked = self._revoked[accelerator]
        target = t.positions - cap
        while len(revoked) > target:
            revoked.discard(min(revoked))
        if len(revoked) < target:
            occupied = set(self._grid[accelerator])
            for p in range(t.positions - 1, -1, -1):
                if len(revoked) >= target:
                    break
                if p not in occupied:
                    revoked.add(p)
            # any remaining debt is held by running gangs; collected
            # as they release (no retro-preemption)

    # --------------------------------------------------------- listeners

    def on_capacity(self, fn) -> None:
        """Subscribe to capacity-return ticks: ``fn(accelerator)`` runs
        (outside the inventory lock, best-effort) whenever free slices
        increase. The elastic-resize grow path rides this so a freed
        slice reaches a shrunken gang within a reconcile tick instead
        of a polling interval."""
        self._capacity_listeners.append(fn)

    def _notify_capacity(self, accelerator: str) -> None:
        for fn in list(self._capacity_listeners):
            try:
                fn(accelerator)
            except Exception:  # a listener bug must never break the ledger
                import logging

                logging.getLogger(__name__).warning(
                    "capacity listener failed for %s", accelerator,
                    exc_info=True)
