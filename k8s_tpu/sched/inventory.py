"""Slice inventory: the cluster's TPU capacity model.

The reference design doc targets O(100) concurrent jobs per cluster
(PAPER.md, tf_job_design_doc.md:24-26) but placed every job's pods
independently — two jobs could both believe they owned the last free
slice. This module gives the operator ONE ledger of truth:

- capacity comes from the controller-config ``fleet:`` block
  (accelerator type → number of slices of that shape the cluster owns);
- every admitted job is charged its **gang footprint**, derived from
  ``spec.tpu`` through the existing :mod:`k8s_tpu.spec.topology`
  lookup. A training gang charges ``numSlices`` WHOLE slices
  atomically (a slice is all-or-nothing — there is no partial gang);
  a serving fleet charges one single-host slice per replica over its
  full autoscale range (``maxReplicas``), so a scale-up can never
  discover mid-flight that the chips it was promised are gone.

The inventory enforces the zero-oversubscription invariant at the
charge site — :class:`OversubscriptionError` is a scheduler bug, not a
recoverable condition — and keeps a high-water mark per accelerator so
tests can assert the invariant held across a whole run, not just at
the end.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional

from k8s_tpu.spec import topology as topo


class OversubscriptionError(RuntimeError):
    """A charge would exceed fleet capacity (scheduler invariant bug)."""


@dataclass(frozen=True)
class Footprint:
    """What one admitted job costs the fleet.

    ``slices`` whole slices of ``accelerator`` are charged atomically;
    ``chips`` (= slices × chips/slice) is the quota currency — queues
    meter chips so one v5p-512 counts 64× a v5e-8 wherever quotas mix
    shapes. ``per_replica`` marks serving fleets (each replica is an
    independent single-host slice, charged over the autoscale range)."""

    accelerator: str = ""
    slices: int = 0
    chips: int = 0
    per_replica: bool = False

    @property
    def empty(self) -> bool:
        """Zero-footprint jobs (no ``tpu:`` block — CPU smoke jobs,
        control-plane-only workloads) bypass the inventory entirely."""
        return self.slices <= 0 or not self.accelerator

    def __str__(self) -> str:
        if self.empty:
            return "no accelerator footprint"
        kind = "replica-slice" if self.per_replica else "slice"
        return (f"{self.slices} × {self.accelerator} {kind}"
                f"{'s' if self.slices != 1 else ''} ({self.chips} chips)")


def footprint_of(spec) -> Footprint:
    """Derive a job spec's gang footprint via the ``spec.topology``
    lookup. Unknown accelerators yield an EMPTY footprint on purpose:
    the spec will fail validation in the reconciler with the readable
    error, instead of queueing forever behind capacity that cannot
    exist."""
    tpu = getattr(spec, "tpu", None)
    if tpu is None or not tpu.accelerator:
        return Footprint()
    t = topo.lookup(tpu.accelerator)
    if t is None:
        return Footprint()
    serving = getattr(spec, "serving", None)
    if serving is not None:
        # per-replica economics over the WHOLE autoscale range: the
        # slices an SLO scale-up may claim are reserved at admission
        n = max(serving.replicas, serving.bounds()[1])
        return Footprint(tpu.accelerator, slices=n, chips=n * t.chips,
                         per_replica=True)
    n = max(1, tpu.num_slices)
    return Footprint(tpu.accelerator, slices=n, chips=n * t.chips)


class SliceInventory:
    """The fleet ledger: capacity per accelerator type, charges per job.

    Thread-safe (the scheduler mutates it under its own lock, but
    metrics exporters and tests read it from other threads)."""

    def __init__(self, fleet: Dict[str, int]):
        self._capacity: Dict[str, int] = {
            a: int(n) for a, n in (fleet or {}).items() if int(n) > 0
        }
        self._used: Dict[str, int] = {a: 0 for a in self._capacity}
        self._holders: Dict[str, Footprint] = {}
        self._lock = threading.RLock()
        # per-accelerator high-water mark: lets a scale test assert the
        # zero-oversubscription invariant held over the WHOLE run
        self.max_used: Dict[str, int] = {a: 0 for a in self._capacity}
        # capacity-return listeners (docs/ELASTIC.md): called with the
        # accelerator name whenever free slices INCREASE (a release, an
        # elastic shrink, a pool grow) — the elastic-resize grow tick.
        # Called OUTSIDE the lock: a listener that re-enters the
        # inventory (or nudges a reconciler) must never deadlock it.
        self._capacity_listeners: list = []

    # ------------------------------------------------------------- reads

    def knows(self, accelerator: str) -> bool:
        with self._lock:
            return accelerator in self._capacity

    def capacity(self, accelerator: str) -> int:
        with self._lock:
            return self._capacity.get(accelerator, 0)

    def used(self, accelerator: str) -> int:
        with self._lock:
            return self._used.get(accelerator, 0)

    def available(self, accelerator: str) -> int:
        with self._lock:
            return (self._capacity.get(accelerator, 0)
                    - self._used.get(accelerator, 0))

    def fits(self, fp: Footprint) -> bool:
        if fp.empty:
            return True
        with self._lock:
            return (fp.accelerator in self._capacity
                    and self.available(fp.accelerator) >= fp.slices)

    def holder(self, key: str) -> Optional[Footprint]:
        with self._lock:
            return self._holders.get(key)

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """Per-pool view for metrics/tests. ``free`` is clamped at 0:
        a pool driven over capacity by adoption or a config shrink has
        zero UNASSIGNED slices, not a negative number — the
        ktpu_sched_slices_free gauge must stay sane; ``available()``
        (the decision input) stays unclamped so admission still sees
        the deficit."""
        with self._lock:
            return {
                a: {"capacity": c, "used": self._used.get(a, 0),
                    "free": max(0, c - self._used.get(a, 0))}
                for a, c in self._capacity.items()
            }

    # ------------------------------------------------------------- writes

    def charge(self, key: str, fp: Footprint, force: bool = False) -> None:
        """Charge ``key``'s whole footprint atomically. ``force`` is the
        adoption path ONLY (an operator restart re-adopting a gang that
        is already physically running must never kill it over a ledger
        it cannot have corrupted) — everywhere else an over-capacity
        charge raises, because admitting past capacity is exactly the
        two-jobs-own-one-slice bug this subsystem exists to end."""
        if fp.empty:
            return
        with self._lock:
            if key in self._holders:
                raise ValueError(f"{key} is already charged")
            if not force and not self.fits(fp):
                raise OversubscriptionError(
                    f"charging {key} ({fp}) would oversubscribe "
                    f"{fp.accelerator}: used {self.used(fp.accelerator)}"
                    f"/{self.capacity(fp.accelerator)} slices")
            self._used[fp.accelerator] = (
                self._used.get(fp.accelerator, 0) + fp.slices)
            self._capacity.setdefault(fp.accelerator, 0)
            self._holders[key] = fp
            self.max_used[fp.accelerator] = max(
                self.max_used.get(fp.accelerator, 0),
                self._used[fp.accelerator])

    def release(self, key: str) -> Optional[Footprint]:
        with self._lock:
            fp = self._holders.pop(key, None)
            if fp is not None:
                self._used[fp.accelerator] = max(
                    0, self._used.get(fp.accelerator, 0) - fp.slices)
        if fp is not None and not fp.empty:
            self._notify_capacity(fp.accelerator)
        return fp

    def recharge(self, key: str, fp: Footprint) -> None:
        """Atomically replace ``key``'s charge with ``fp`` — the
        elastic-resize ledger move (docs/ELASTIC.md): a shrink frees
        slices and a grow re-charges them in ONE critical section, so
        no observer (and no high-water mark) ever sees the job owning
        both shapes at once, and a grow that would oversubscribe raises
        WITHOUT losing the old charge (the gang still physically holds
        its current slices)."""
        freed = False
        with self._lock:
            old = self._holders.pop(key, None)
            if old is not None:
                self._used[old.accelerator] = max(
                    0, self._used.get(old.accelerator, 0) - old.slices)
            try:
                self.charge(key, fp)
            except Exception:
                if old is not None:  # restore the old charge untouched
                    self._used[old.accelerator] = (
                        self._used.get(old.accelerator, 0) + old.slices)
                    self._holders[key] = old
                raise
            freed = (old is not None and not old.empty
                     and (fp.empty or fp.slices < old.slices
                          or fp.accelerator != old.accelerator))
        if freed:
            self._notify_capacity(old.accelerator)

    def set_capacity(self, accelerator: str, slices: int) -> None:
        """Resize one pool (node-pool scale events, the
        permanent-pod-loss chaos fault). Shrinking below current usage
        never retro-preempts — running gangs keep their slices and the
        pool simply admits nothing until it drains back under the new
        capacity (the no-flap rule: inventory flaps must not translate
        into admission/preemption churn). Growing the pool notifies the
        capacity-return listeners (the elastic grow tick)."""
        grew = False
        with self._lock:
            if slices <= 0:
                self._capacity.pop(accelerator, None)
            else:
                grew = int(slices) > self._capacity.get(accelerator, 0)
                self._capacity[accelerator] = int(slices)
        if grew:
            self._notify_capacity(accelerator)

    # --------------------------------------------------------- listeners

    def on_capacity(self, fn) -> None:
        """Subscribe to capacity-return ticks: ``fn(accelerator)`` runs
        (outside the inventory lock, best-effort) whenever free slices
        increase. The elastic-resize grow path rides this so a freed
        slice reaches a shrunken gang within a reconcile tick instead
        of a polling interval."""
        self._capacity_listeners.append(fn)

    def _notify_capacity(self, accelerator: str) -> None:
        for fn in list(self._capacity_listeners):
            try:
                fn(accelerator)
            except Exception:  # a listener bug must never break the ledger
                import logging

                logging.getLogger(__name__).warning(
                    "capacity listener failed for %s", accelerator,
                    exc_info=True)
