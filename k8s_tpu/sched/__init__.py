"""Cluster-scale scheduler (docs/SCHEDULER.md).

The layer the Controller consults before any reconciler materializes
resources: a slice inventory model derived from the controller-config
accelerator fleet (:mod:`k8s_tpu.sched.inventory`) and a pure,
clock-injected decision core (:mod:`k8s_tpu.sched.scheduler`)
implementing per-queue quota admission, priority ordering, gang
bin-packing onto slices, and checkpoint-cost-aware preemption.
"""

from k8s_tpu.sched.inventory import (  # noqa: F401
    Footprint,
    OversubscriptionError,
    SliceInventory,
    footprint_of,
)
from k8s_tpu.sched.scheduler import (  # noqa: F401
    ClusterScheduler,
    JobRequest,
    Preemption,
    TickResult,
)
