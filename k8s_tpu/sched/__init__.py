"""Cluster-scale scheduler (docs/SCHEDULER.md).

The layer the Controller consults before any reconciler materializes
resources: a slice inventory model derived from the controller-config
accelerator fleet (:mod:`k8s_tpu.sched.inventory`) — optionally with
named slices on an ICI-pod topology grid and a pure placement scorer —
and a pure, clock-injected decision core
(:mod:`k8s_tpu.sched.scheduler`) implementing per-queue quota
admission, priority ordering, gang bin-packing onto slices,
checkpoint-cost-aware preemption, and EASY-style conservative backfill
behind the head-of-line reservation.
"""

from k8s_tpu.sched.inventory import (  # noqa: F401
    Footprint,
    OversubscriptionError,
    PoolTopology,
    SliceAssignment,
    SliceInventory,
    footprint_of,
    plan_placement,
)
from k8s_tpu.sched.scheduler import (  # noqa: F401
    ClusterScheduler,
    JobRequest,
    Preemption,
    StarvationError,
    TickResult,
)
