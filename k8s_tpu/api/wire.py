"""Kubernetes wire-format vocabulary shared by the REST client backend
(:mod:`k8s_tpu.api.restcluster`) and the local apiserver
(:mod:`k8s_tpu.api.apiserver`).

Covers exactly the API surface the control plane uses — the same set the
reference drives through client-go (``pkg/trainer/replicas.go``,
``tensorboard.go``) plus its raw-REST CRD client
(``pkg/util/k8sutil/tf_job_client.go:56-86``):

- core/v1 Pods, Services, ConfigMaps, Events, Endpoints (election lock)
- batch/v1 Jobs
- apps/v1 Deployments
- apiextensions.k8s.io/v1 CustomResourceDefinitions
- the TpuJob custom resource under ``/apis/tpu.k8s.io/v1alpha1``
"""

from __future__ import annotations

import urllib.parse
from typing import Any, Dict, Optional, Tuple

from k8s_tpu.spec import CRD_GROUP, CRD_KIND, CRD_KIND_PLURAL, CRD_VERSION


class Route:
    """One kind's REST coordinates."""

    def __init__(self, kind: str, api_version: str, plural: str, namespaced: bool = True):
        self.kind = kind
        self.api_version = api_version  # "v1" or "group/version"
        self.plural = plural
        self.namespaced = namespaced

    @property
    def prefix(self) -> str:
        # core group lives under /api/v1, everything else under /apis/g/v
        return f"/api/{self.api_version}" if "/" not in self.api_version else f"/apis/{self.api_version}"

    def collection_path(self, namespace: Optional[str]) -> str:
        if self.namespaced and namespace is not None:
            return f"{self.prefix}/namespaces/{namespace}/{self.plural}"
        return f"{self.prefix}/{self.plural}"

    def object_path(self, namespace: Optional[str], name: str) -> str:
        return f"{self.collection_path(namespace)}/{name}"


ROUTES: Dict[str, Route] = {
    "Pod": Route("Pod", "v1", "pods"),
    "Service": Route("Service", "v1", "services"),
    "ConfigMap": Route("ConfigMap", "v1", "configmaps"),
    "Event": Route("Event", "v1", "events"),
    "Endpoints": Route("Endpoints", "v1", "endpoints"),
    "Job": Route("Job", "batch/v1", "jobs"),
    "Deployment": Route("Deployment", "apps/v1", "deployments"),
    CRD_KIND: Route(CRD_KIND, f"{CRD_GROUP}/{CRD_VERSION}", CRD_KIND_PLURAL),
}

CRD_ROUTE = Route(
    "CustomResourceDefinition",
    "apiextensions.k8s.io/v1",
    "customresourcedefinitions",
    namespaced=False,
)

# plural (within its prefix) -> kind, for server-side path dispatch
PLURALS: Dict[Tuple[str, str], str] = {
    (r.prefix, r.plural): k for k, r in ROUTES.items()
}


def status_body(code: int, reason: str, message: str) -> Dict[str, Any]:
    """A ``metav1.Status`` failure body."""
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "status": "Failure",
        "message": message,
        "reason": reason,
        "code": code,
    }


def format_label_selector(selector: Dict[str, str]) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(selector.items()))


def parse_label_selector(raw: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"unsupported label selector term {part!r}")
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip().strip('"')
    return out


def encode_query(params: Dict[str, str]) -> str:
    return urllib.parse.urlencode(params) if params else ""


def stamp_type_meta(kind: str, obj: Dict[str, Any]) -> Dict[str, Any]:
    """Fill apiVersion/kind on the way out, the way a real apiserver does."""
    r = ROUTES.get(kind)
    if r is not None:
        obj.setdefault("apiVersion", r.api_version)
        obj.setdefault("kind", kind)
    return obj
