"""Watch-fed shared object cache — the informer the reference never had.

The reference's hot loop polled the apiserver every 8s with
O(replicas) round-trips per job (``pkg/trainer/replicas.go:432-467``:
a batch-Job GET plus a Pod LIST per replica index), which SURVEY §7.2
hard part #4 flags as the design that "won't scale to 128-host
slices; use informers + pod-condition aggregation". This module is
that informer: the operator opens ONE watch stream per kind, keeps a
local cache of every object it manages, and the reconcilers read the
cache — steady-state reconcile makes **zero** apiserver calls.

Two feed mechanisms, chosen per backend:

- :class:`k8s_tpu.api.cluster.InMemoryCluster` fires its ``hooks``
  synchronously inside the commit, so the cache is updated *before*
  the mutating call returns — a perfectly fresh cache for tests and
  single-host local mode.
- Any other backend (:class:`k8s_tpu.api.restcluster.RestCluster`
  against a real apiserver or the local wire-format one) gets a
  watch thread per kind: LIST to prime the cache, stream from the
  list's resourceVersion, relist on 410 Gone — client-go reflector
  semantics (the reference got these for free from client-go; we own
  them).

Cache readers must tolerate eventual consistency on the REST path:
an object the reconciler just deleted may still be cached for a few
milliseconds. The trainer handles that with delete tombstones
(``trainer/replicas.py``) — the informer itself stays a dumb mirror.
"""

from __future__ import annotations

import copy
import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

from k8s_tpu.api import errors
from k8s_tpu.api.cluster import InMemoryCluster, WatchEvent, _matches
from k8s_tpu.robustness.backoff import Backoff, BackoffPolicy

log = logging.getLogger(__name__)

DEFAULT_KINDS = ("Job", "Pod", "Service", "ConfigMap", "Deployment")

# Reflector resync schedule: list/watch failures and 410 relists space
# out 0.5s → 15s (jittered) instead of hammering a browned-out apiserver.
RESYNC_POLICY = BackoffPolicy(
    base=0.5, factor=2.0, cap=15.0, jitter=0.5, reset_after=60.0
)


class _KindCache:
    """Mirror of one kind: ``(namespace, name) -> object dict``."""

    def __init__(self, kind: str):
        self.kind = kind
        self.lock = threading.RLock()
        self.objects: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.synced = threading.Event()

    @staticmethod
    def _rv(obj: Dict[str, Any]) -> int:
        try:
            return int((obj.get("metadata") or {}).get("resourceVersion", 0))
        except (TypeError, ValueError):
            return 0

    @staticmethod
    def _materially_equal(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
        """Equal modulo resourceVersion — a write that only bumped the
        rv carries no information a reconciler could act on, and event
        listeners must not be kicked for it."""
        am = dict(a.get("metadata") or {})
        bm = dict(b.get("metadata") or {})
        am.pop("resourceVersion", None)
        bm.pop("resourceVersion", None)
        return am == bm and {k: v for k, v in a.items()
                             if k != "metadata"} == \
            {k: v for k, v in b.items() if k != "metadata"}

    def apply(self, ev: WatchEvent) -> bool:
        """Apply one event; True iff the cache *materially* changed
        (the listener-notification gate)."""
        key = (ev.namespace or "default", ev.name)
        with self.lock:
            if ev.type == "DELETED":
                return self.objects.pop(key, None) is not None
            if ev.type in ("ADDED", "MODIFIED"):
                cur = self.objects.get(key)
                # never regress to an older copy (initial-list overlap)
                if cur is None or self._rv(ev.object) >= self._rv(cur):
                    changed = (cur is None
                               or not self._materially_equal(
                                   cur, ev.object))
                    self.objects[key] = copy.deepcopy(ev.object)
                    return changed
        return False

    def replace(self, items: List[Dict[str, Any]]) -> None:
        """Relist: the list snapshot becomes the whole cache (objects
        deleted while the watch was down must vanish)."""
        fresh = {
            ((o.get("metadata") or {}).get("namespace", "default"),
             (o.get("metadata") or {}).get("name", "")): copy.deepcopy(o)
            for o in items
        }
        with self.lock:
            self.objects = fresh

    def get(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        with self.lock:
            obj = self.objects.get((namespace, name))
            return copy.deepcopy(obj) if obj is not None else None

    def list(self, namespace: Optional[str],
             selector: Optional[Dict[str, str]]) -> List[Dict[str, Any]]:
        with self.lock:
            out = []
            for (ns, _), obj in sorted(self.objects.items()):
                if namespace is not None and ns != namespace:
                    continue
                if selector and not _matches(
                    (obj.get("metadata") or {}).get("labels", {}) or {}, selector
                ):
                    continue
                out.append(copy.deepcopy(obj))
            return out


class Informer:
    """Shared watch-fed cache over the kinds the trainer manages."""

    def __init__(self, cluster, kinds=DEFAULT_KINDS, namespace: Optional[str] = None):
        self.cluster = cluster
        self.namespace = namespace
        self.caches: Dict[str, _KindCache] = {k: _KindCache(k) for k in kinds}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._hook = None
        self._started = False
        # event listeners (the event-driven control plane's feed,
        # docs/SCHEDULER.md "Event-driven core"): called with each
        # WatchEvent that MATERIALLY changed the cache, plus a
        # synthetic type="RESYNC" event after every reflector relist
        # (anything could have changed while the watch was down).
        # Listeners must be cheap and never raise.
        self._listeners: List = []

    def add_listener(self, fn) -> None:
        self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        if fn in self._listeners:
            self._listeners.remove(fn)

    def _notify(self, ev: WatchEvent) -> None:
        for fn in list(self._listeners):
            try:
                fn(ev)
            except Exception as e:  # a listener bug must not stall the feed
                log.error("informer listener failed on %s %s/%s: %s",
                          ev.type, ev.kind, ev.name, e)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "Informer":
        if self._started:
            return self
        self._started = True
        if isinstance(self.cluster, InMemoryCluster):
            # synchronous feed: the cache commits inside the cluster's
            # own commit, so readers never observe staleness. Events
            # fired while we prime from list() are BUFFERED and
            # replayed after: applying them live could interleave a
            # DELETED before its object's stale listed copy, leaving a
            # phantom entry with no further event to evict it.
            state = {"priming": True, "buffer": []}

            def hook(ev: WatchEvent) -> None:
                if ev.kind not in self.caches or (
                    self.namespace is not None and ev.namespace != self.namespace
                ):
                    return
                if state["priming"]:
                    state["buffer"].append(ev)
                    return
                if self.caches[ev.kind].apply(ev):
                    self._notify(ev)

            self._hook = hook
            self.cluster.hooks.append(hook)
            for kind, cache in self.caches.items():
                for obj in self.cluster.list(kind, self.namespace):
                    cache.apply(WatchEvent("ADDED", kind, obj))
            # drain + flip under the cluster's commit lock (hooks fire
            # while it is held, so no event can race the flip)
            with self.cluster._lock:
                for ev in state["buffer"]:
                    self.caches[ev.kind].apply(ev)
                state["priming"] = False
            for cache in self.caches.values():
                cache.synced.set()
            return self
        for kind in self.caches:
            t = threading.Thread(
                target=self._reflect, args=(kind,), daemon=True,
                name=f"informer-{kind.lower()}",
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._hook is not None and self._hook in getattr(self.cluster, "hooks", []):
            self.cluster.hooks.remove(self._hook)
        for t in self._threads:
            t.join(timeout=5)

    def wait_for_sync(self, timeout: float = 30.0) -> bool:
        import time

        end = time.monotonic() + timeout
        for cache in self.caches.values():
            remaining = end - time.monotonic()
            if remaining <= 0 or not cache.synced.wait(remaining):
                return False
        return True

    @property
    def synced(self) -> bool:
        return all(c.synced.is_set() for c in self.caches.values())

    # ------------------------------------------------------------ reflector

    def _reflect(self, kind: str) -> None:
        """client-go reflector loop: list → watch(rv) → apply; relist on
        410; re-dial on stream errors (the RestWatcher already re-dials
        EOFs internally — only staleness surfaces here)."""
        cache = self.caches[kind]
        bo = Backoff(RESYNC_POLICY)  # unified resync/relist schedule
        while not self._stop.is_set():
            if bo.wait(self._stop):
                return
            try:
                lister = getattr(self.cluster, "list_with_rv", None)
                if lister is not None:
                    # the LIST's own resourceVersion is the watch
                    # anchor; the client-wide high-water mark can be
                    # AHEAD of this snapshot (other threads share the
                    # client) and would skip events committed between
                    items, rv = lister(kind, self.namespace)
                else:
                    items = self.cluster.list(kind, self.namespace)
                    rv = self.cluster.resource_version
                cache.replace(items)
                cache.synced.set()
                # anything may have changed while the watch was down —
                # one synthetic event lets listeners resync themselves
                # (the controller re-kicks every job key on it)
                self._notify(WatchEvent("RESYNC", kind, {
                    "metadata": {"name": "", "namespace": ""}}))
                watcher = self.cluster.watch(kind, self.namespace, rv)
            except Exception as e:
                delay = bo.note_failure()
                log.warning("informer %s: list/watch failed (%s); retry in %.1fs",
                            kind, e, delay)
                continue
            bo.note_success()
            try:
                while not self._stop.is_set():
                    ev = watcher.next(timeout=0.2)
                    if ev is None:
                        continue
                    if cache.apply(ev):
                        self._notify(ev)
            except errors.OutdatedVersionError:
                # a 410 storm (chaos watch-drop, compacted history)
                # relists through the same backoff as any other failure
                delay = bo.note_failure()
                log.info("informer %s: watch outdated; relisting in %.1fs",
                         kind, delay)
            except Exception as e:
                delay = bo.note_failure()
                log.warning("informer %s: watch error (%s); relisting in %.1fs",
                            kind, e, delay)
            finally:
                try:
                    watcher.stop()
                except Exception:
                    pass

    # ------------------------------------------------------------ readers

    def get(self, kind: str, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        return self.caches[kind].get(namespace, name)

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None) -> List[Dict[str, Any]]:
        return self.caches[kind].list(namespace, label_selector)
