"""K8s API plumbing: object model, clients, in-memory cluster, election, retry.

Analogue of reference ``pkg/util/`` + ``pkg/util/k8sutil/``.
"""
