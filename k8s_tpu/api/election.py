"""Leader election by annotation compare-and-swap.

Analogue of the reference's vendored leader-election fork
(``pkg/util/k8sutil/election/``): a ``LeaderElectionRecord`` stored in
the annotation ``control-plane.alpha.kubernetes.io/leader`` of an
Endpoints-like lock object, acquired/renewed by CAS on resourceVersion
(``election.go:140-265``, ``resourcelock/endpointslock.go:29-103``).
Lease semantics (15s lease / 5s renew / 3s retry defaults) match
``cmd/tf_operator/main.go:42-44``.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from k8s_tpu.api import errors

LEADER_ANNOTATION = "control-plane.alpha.kubernetes.io/leader"
LOCK_KIND = "Endpoints"

DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_DEADLINE = 5.0
DEFAULT_RETRY_PERIOD = 3.0


@dataclass
class LeaderElectionRecord:
    holder_identity: str
    lease_duration_seconds: float
    acquire_time: float
    renew_time: float

    def to_json(self) -> str:
        return json.dumps(
            {
                "holderIdentity": self.holder_identity,
                "leaseDurationSeconds": self.lease_duration_seconds,
                "acquireTime": self.acquire_time,
                "renewTime": self.renew_time,
            }
        )

    @classmethod
    def from_json(cls, s: str) -> "LeaderElectionRecord":
        d = json.loads(s)
        return cls(
            d.get("holderIdentity", ""),
            d.get("leaseDurationSeconds", DEFAULT_LEASE_DURATION),
            d.get("acquireTime", 0.0),
            d.get("renewTime", 0.0),
        )


class LeaderElector:
    def __init__(
        self,
        cluster,  # InMemoryCluster surface; RestCluster gives real CAS
        namespace: str,
        name: str,
        identity: str,
        lease_duration: float = DEFAULT_LEASE_DURATION,
        renew_deadline: float = DEFAULT_RENEW_DEADLINE,
        retry_period: float = DEFAULT_RETRY_PERIOD,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._cluster = cluster
        self.namespace = namespace
        self.name = name
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self._clock = clock
        self.observed: Optional[LeaderElectionRecord] = None
        self._observed_at = 0.0

    # -- one CAS round (reference tryAcquireOrRenew, election.go:213-265) --

    def try_acquire_or_renew(self) -> bool:
        now = self._clock()
        desired = LeaderElectionRecord(
            self.identity, self.lease_duration, now, now
        )
        try:
            lock = self._cluster.get(LOCK_KIND, self.namespace, self.name)
        except errors.NotFoundError:
            lock = {
                "metadata": {
                    "namespace": self.namespace,
                    "name": self.name,
                    "annotations": {LEADER_ANNOTATION: desired.to_json()},
                }
            }
            try:
                self._cluster.create(LOCK_KIND, lock)
            except errors.AlreadyExistsError:
                return False
            self.observed = desired
            self._observed_at = now
            return True

        raw = (lock["metadata"].get("annotations") or {}).get(LEADER_ANNOTATION, "")
        current = LeaderElectionRecord.from_json(raw) if raw else None
        if current is not None:
            if self.observed is None or current.renew_time != self.observed.renew_time:
                self.observed = current
                self._observed_at = now
            lease_valid = self._observed_at + self.lease_duration > now
            if current.holder_identity != self.identity and lease_valid:
                return False  # someone else holds an unexpired lease
            if current.holder_identity == self.identity:
                desired.acquire_time = current.acquire_time
        lock["metadata"].setdefault("annotations", {})[LEADER_ANNOTATION] = desired.to_json()
        try:
            self._cluster.update(LOCK_KIND, lock, check_version=True)
        except (errors.ConflictError, errors.NotFoundError):
            return False
        self.observed = desired
        self._observed_at = now
        return True

    def is_leader(self) -> bool:
        return self.observed is not None and self.observed.holder_identity == self.identity

    # -- blocking run loop (reference RunOrDie/Run, election.go:140-208) ---

    def run(
        self,
        on_started_leading: Callable[[threading.Event], None],
        on_stopped_leading: Callable[[], None],
        stop: Optional[threading.Event] = None,
    ) -> None:
        stop = stop or threading.Event()
        while not stop.is_set():
            if self.try_acquire_or_renew():
                break
            stop.wait(self.retry_period)
        if stop.is_set():
            return
        lost = threading.Event()

        def renew_loop():
            while not stop.is_set():
                stop.wait(self.renew_deadline)
                if stop.is_set():
                    break
                try:
                    renewed = self.try_acquire_or_renew()
                except Exception as e:
                    # a transient API error mid-renew previously killed
                    # this thread SILENTLY: the lease then expired with
                    # `lost` never set — the old leader kept leading
                    # while a new one took over (split brain). Failing
                    # safe — treat it as a lost lease — is the only
                    # correct direction.
                    import logging

                    logging.getLogger(__name__).warning(
                        "lease renew errored (%s); conceding leadership", e)
                    renewed = False
                if not renewed:
                    lost.set()
                    break

        t = threading.Thread(target=renew_loop, daemon=True, name="lease-renew")
        t.start()
        try:
            on_started_leading(lost)
        finally:
            stop.set()
            on_stopped_leading()
